#!/usr/bin/env python
"""Production drill harness: capture replay + chaos soak (ptpu_drill).

The capture half lives in C (csrc/ptpu_capture.h): a sampled raw-frame
ring taps every framed request the serving/PS net core dispatches and
persists "ptpu-capture v1" files (ptpu_capture_save) or serves the
newest window over GET /capturez. This tool is the OTHER half of the
drill loop:

  fetch     GET /capturez from a live server -> capture file;
  replay    re-fire a capture file against a (fresh) server at
            1x..Nx original speed, preserving per-connection frame
            ordering and the recorded inter-arrival shape, and report
            the throughput knee plus p50/p99 latency. The replayed
            per-op mix (tag + row-bucket histogram) must match the
            original capture within REPLAY_MIX_TOL (5%) and the
            server's `requests` delta must equal frames sent EXACTLY;
  soak      loop a capture against a PTPU_CHAOS server, reconciling
            the server's injected-fault counters against what this
            client OBSERVED — exact equality, not "roughly right";
  selfbench end-to-end evidence run (exports an MLP artifact, captures
            live traffic, replays the capture at a speed sweep; with
            --ab-rounds, adds the interleaved drills-off vs
            baseline-.so overhead A/B) -> BENCH_DRILL_rNN.json;
  selfsoak  end-to-end chaos drill (lossless kinds then lossy kinds)
            against self-hosted servers — the run_checks.sh
            DRILL_SOAK_SECS leg.

Wire-format constants below are byte-for-byte twins of
csrc/ptpu_capture.h (tools/ptpu_check.py cross-checks them):
header [u32 magic][u32 version][u32 count][u32 body_bytes], record
[i64 ts_us][u64 conn][u32 frame_len][u32 cap_len][u8 ver][u8 tag]
[u16 reserved=0] + cap_len payload bytes. Parsing REJECTS the whole
file on any violation (never-crash / full-reject, the tune-cache
posture).
"""
from __future__ import annotations

import argparse
import hashlib
import hmac as _hmac
import json
import os
import socket
import struct
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# --- csrc/ptpu_capture.h twins (checked by tools/ptpu_check.py) ----
CAPTURE_MAGIC = 0x50414350          # "PCAP" little-endian
CAPTURE_VERSION = 1
CAPTURE_HEADER_BYTES = 16
CAPTURE_REC_BYTES = 28              # fixed part, payload follows
CAPTURE_MAX_REC_PAYLOAD = 4096
CAPTURE_MAX_RECORDS = 65536

REPLAY_MIX_TOL = 0.05               # 5% per-op mix tolerance

_U32 = struct.Struct("<I")
_HDR = struct.Struct("<IIII")
_REC = struct.Struct("<qQIIBBH")


class CaptureFormatError(ValueError):
    """Malformed capture file — the WHOLE file is rejected."""


# ------------------------------------------------ capture file twin
def parse_capture_bytes(data: bytes) -> list:
    """bytes -> [{ts_us, conn, frame_len, ver, tag, payload}].

    Mirrors capture::ParseCaptureBytes exactly: same checks, same
    order, whole-file reject (raise) on the first violation."""
    if len(data) < CAPTURE_HEADER_BYTES:
        raise CaptureFormatError("short header")
    magic, version, count, body = _HDR.unpack_from(data, 0)
    if magic != CAPTURE_MAGIC:
        raise CaptureFormatError(f"bad magic {magic:#x}")
    if version != CAPTURE_VERSION:
        raise CaptureFormatError(f"bad version {version}")
    if count > CAPTURE_MAX_RECORDS:
        raise CaptureFormatError(f"count {count} over cap")
    if len(data) != CAPTURE_HEADER_BYTES + body:
        raise CaptureFormatError(
            f"size {len(data)} != header + body_bytes {body}")
    out = []
    off = CAPTURE_HEADER_BYTES
    end = CAPTURE_HEADER_BYTES + body
    for _ in range(count):
        if off + CAPTURE_REC_BYTES > end:
            raise CaptureFormatError("truncated record")
        ts, conn, flen, clen, ver, tag, rsv = _REC.unpack_from(
            data, off)
        off += CAPTURE_REC_BYTES
        if clen > flen or clen > CAPTURE_MAX_REC_PAYLOAD:
            raise CaptureFormatError(f"bad cap_len {clen}")
        if rsv != 0:
            raise CaptureFormatError("reserved != 0")
        if off + clen > end:
            raise CaptureFormatError("truncated payload")
        payload = data[off:off + clen]
        off += clen
        # ver/tag mirror payload[0]/payload[1] (0 when absent)
        if ver != (payload[0] if clen >= 1 else 0):
            raise CaptureFormatError("ver != payload[0]")
        if tag != (payload[1] if clen >= 2 else 0):
            raise CaptureFormatError("tag != payload[1]")
        out.append({"ts_us": ts, "conn": conn, "frame_len": flen,
                    "ver": ver, "tag": tag, "payload": payload})
    if off != end:
        raise CaptureFormatError("trailing bytes after records")
    return out


def serialize_capture(records) -> bytes:
    """Records -> capture-file bytes (capture::SerializeCapture twin;
    count and per-record payload are capped, never rejected)."""
    records = records[:CAPTURE_MAX_RECORDS]
    body = bytearray()
    for r in records:
        payload = bytes(r["payload"])[:CAPTURE_MAX_REC_PAYLOAD]
        flen = max(int(r.get("frame_len", len(payload))),
                   len(payload))
        ver = payload[0] if len(payload) >= 1 else 0
        tag = payload[1] if len(payload) >= 2 else 0
        body += _REC.pack(int(r["ts_us"]), int(r["conn"]), flen,
                          len(payload), ver, tag, 0)
        body += payload
    return _HDR.pack(CAPTURE_MAGIC, CAPTURE_VERSION, len(records),
                     len(body)) + bytes(body)


def load_capture(path: str) -> list:
    with open(path, "rb") as f:
        return parse_capture_bytes(f.read())


def save_capture(path: str, records) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(serialize_capture(records))
    os.replace(tmp, path)


# ------------------------------------------------------ /capturez
def http_get(host: str, port: int, path: str,
             timeout: float = 10.0) -> bytes:
    with socket.create_connection((host, port),
                                  timeout=timeout) as s:
        s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                  f"Connection: close\r\n\r\n".encode())
        buf = bytearray()
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = bytes(buf).partition(b"\r\n\r\n")
    if b" 200 " not in head.split(b"\r\n", 1)[0]:
        raise RuntimeError(
            "HTTP error: " + head.split(b"\r\n", 1)[0].decode())
    return body


def fetch_capturez(host: str, port: int, n: int = 64) -> list:
    """GET /capturez?n=N -> records (oldest first, replay order).

    The route reports newest-first; this flips it so the result slots
    straight into replay()/save_capture()."""
    body = json.loads(http_get(host, port, f"/capturez?n={n}"))
    recs = []
    for f in reversed(body.get("frames", [])):
        recs.append({"ts_us": int(f["ts_us"]),
                     "conn": int(f["conn"]),
                     "frame_len": int(f["len"]),
                     "ver": int(f["ver"]), "tag": int(f["tag"]),
                     "payload": bytes.fromhex(f["data"])})
    return recs


def fetch_shadowz(host: str, port: int) -> dict:
    """GET /shadowz -> the serving plane's shadow-diff stats object
    (enabled/sample/mismatched_batches/...). Soak and drill reports
    fold this in so a perturbed shadow model shows up next to the
    chaos counters."""
    return json.loads(http_get(host, port, "/shadowz"))


def fetch_invarz(host: str, port: int) -> dict:
    """GET /invarz -> the server's own conservation-law verdict
    (ptpu::invar::CheckJson over its live snapshot; ISSUE 20). The
    `==` laws are only authoritative at quiesce, so callers poll
    /statsz for conns_active == 0 first (assert_invarz does both)."""
    return json.loads(http_get(host, port, "/invarz"))


def assert_invarz(host: str, port: int, where: str,
                  timeout: float = 30.0) -> dict:
    """Quiesce-then-gate against a live server: wait for the
    conns_active gauge to drain over /statsz, then fail on any law
    the server's /invarz verdict reports violated."""
    deadline = time.monotonic() + timeout
    while True:
        st = json.loads(http_get(host, port, "/statsz"))
        if st.get("server", {}).get("conns_active", 0) == 0:
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                f"ptpu_invar[{where}]: server never quiesced "
                f"({st['server'].get('conns_active')} conns active)")
        time.sleep(0.05)
    rep = fetch_invarz(host, port)
    if rep.get("violations"):
        raise AssertionError(
            f"ptpu_invar[{where}]: {json.dumps(rep['violations'])}")
    return rep


# ------------------------------------------------------- op mixing
WIRE_VERSION = 1
WIRE_VERSION_TRACED = 2
TRACE_EXT = 8
TAG_INFER_REQ = 0x60

_TAG_NAMES = {0x60: "infer", 0x63: "meta", 0x65: "decode_open",
              0x66: "decode_sess", 0x67: "decode_step",
              0x69: "decode_close", 0x6a: "decode_open2",
              0x6c: "decode_fork", 0x6d: "spec_open",
              0x6e: "spec_step"}


def frame_op_key(payload: bytes) -> str:
    """Per-op mix key of one request frame: tag name, plus the
    leading-dim row bucket for INFER (the per-op counter the batcher
    actually keys on)."""
    if len(payload) < 2:
        return "short"
    tag = payload[1]
    name = _TAG_NAMES.get(tag, f"tag_{tag:#x}")
    if tag != TAG_INFER_REQ:
        return name
    base = TRACE_EXT if payload[0] == WIRE_VERSION_TRACED else 0
    # [ver][tag](+tid)[u64 rid][u16 n_in][u8 dt][u8 ndim][i64 dims..]
    off = 2 + base + 8 + 2 + 2
    if len(payload) < off + 8:
        return name
    rows = struct.unpack_from("<q", payload, off)[0]
    return f"{name}[r{rows}]"


def op_mix(records) -> dict:
    mix: dict = {}
    for r in records:
        k = frame_op_key(r["payload"])
        mix[k] = mix.get(k, 0) + 1
    return mix


def mix_matches(orig: dict, got: dict,
                tol: float = REPLAY_MIX_TOL) -> tuple:
    """-> (ok, worst_delta). Compares per-op SHARES: every op's share
    of total traffic must agree within `tol` (absolute share delta —
    an op that is 40% of the capture must be 35-45% of the replay)."""
    to = max(1, sum(orig.values()))
    tg = max(1, sum(got.values()))
    worst = 0.0
    for k in set(orig) | set(got):
        d = abs(orig.get(k, 0) / to - got.get(k, 0) / tg)
        worst = max(worst, d)
    return worst <= tol, worst


# ---------------------------------------------------- wire client
def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def dial_framed(host: str, port: int, authkey: bytes,
                timeout: float = 30.0) -> socket.socket:
    """Dial + HMAC handshake. Raises ConnectionError on a dropped
    handshake (the PTPU_CHAOS hsdrop signature: EOF before the 0x01
    ack)."""
    s = socket.create_connection((host, port), timeout=timeout)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        nonce = _read_exact(s, 16)
        mac = _hmac.new(authkey, nonce, hashlib.sha256).digest()
        s.sendall(_U32.pack(len(mac)) + mac)
        if _read_exact(s, 1) != b"\x01":
            raise ConnectionError("handshake rejected")
    except BaseException:
        s.close()
        raise
    return s


def _frame_rid(payload: bytes):
    """Request/reply id of a framed serving op (None if too short)."""
    if len(payload) < 10:
        return None
    base = TRACE_EXT if payload[0] == WIRE_VERSION_TRACED else 0
    if len(payload) < 10 + base:
        return None
    return struct.unpack_from("<Q", payload, 2 + base)[0]


class _ConnReplay:
    """Replays ONE captured connection's frames in capture order at
    `speed` x the recorded inter-arrival shape, reading replies on a
    side thread and matching them to sends by request id."""

    def __init__(self, recs, host, port, authkey, speed, t_base_us,
                 barrier):
        self.recs = recs
        self.host, self.port, self.authkey = host, port, authkey
        self.speed = speed
        self.t_base_us = t_base_us
        self.barrier = barrier   # all conns handshake, THEN fire
        self.sent = 0
        self.skipped = 0         # truncated in capture: not replayable
        self.replies = 0
        self.errors = 0          # transport death (chaos kill etc.)
        self.t_first = None      # first send (after the barrier)
        self.t_last = None       # last reply
        self.lat_us: list = []
        self.sent_keys: list = []
        self._send_ts: dict = {}
        self._lock = threading.Lock()
        self._done_sending = threading.Event()

    def _reader(self, sock):
        try:
            while True:
                n = _U32.unpack(_read_exact(sock, 4))[0]
                f = _read_exact(sock, n)
                now = time.monotonic_ns() // 1000
                rid = _frame_rid(f)
                with self._lock:
                    self.replies += 1
                    self.t_last = time.monotonic()
                    t0 = self._send_ts.pop(rid, None)
                if t0 is not None:
                    self.lat_us.append(now - t0)
                with self._lock:
                    if (self._done_sending.is_set()
                            and not self._send_ts):
                        return
        except (ConnectionError, OSError):
            pass

    def run(self):
        sock = None
        try:
            sock = dial_framed(self.host, self.port, self.authkey)
        except (ConnectionError, OSError):
            self.errors += 1
        finally:
            # setup time (dial + handshake) must not skew the rate
            # measurement: every conn reaches the barrier, then all
            # schedules start together
            try:
                self.barrier.wait(timeout=60.0)
            except threading.BrokenBarrierError:
                pass
        if sock is None:
            return
        rd = threading.Thread(target=self._reader, args=(sock,),
                              daemon=True)
        rd.start()
        start = time.monotonic()
        self.t_first = start
        try:
            for r in self.recs:
                if len(r["payload"]) < r["frame_len"]:
                    self.skipped += 1   # capture truncated this one
                    continue
                due = start + (r["ts_us"] - self.t_base_us) / (
                    1e6 * self.speed)
                delay = due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                rid = _frame_rid(r["payload"])
                with self._lock:
                    self._send_ts[rid] = time.monotonic_ns() // 1000
                sock.sendall(_U32.pack(len(r["payload"]))
                             + r["payload"])
                self.sent += 1
                self.sent_keys.append(frame_op_key(r["payload"]))
        except (ConnectionError, OSError):
            self.errors += 1
        finally:
            self._done_sending.set()
            rd.join(timeout=30.0)
            sock.close()


def replay(records, host: str, port: int, authkey: bytes,
           speed: float = 1.0) -> dict:
    """Re-fire a capture at `speed` x. Per-connection ordering and the
    recorded inter-arrival spacing are preserved (each captured conn
    gets its own fresh connection + thread). -> report dict."""
    if not records:
        return {"speed": speed, "sent": 0, "replies": 0,
                "skipped_truncated": 0, "conn_errors": 0,
                "wall_s": 0.0, "offered_rps": 0.0,
                "achieved_rps": 0.0, "p50_us": 0, "p99_us": 0,
                "mix": {}}
    t_base = min(r["ts_us"] for r in records)
    span_s = (max(r["ts_us"] for r in records) - t_base) / 1e6
    by_conn: dict = {}
    for r in records:
        by_conn.setdefault(r["conn"], []).append(r)
    barrier = threading.Barrier(len(by_conn))
    workers = [_ConnReplay(rs, host, port, authkey, speed, t_base,
                           barrier)
               for rs in by_conn.values()]
    t0 = time.monotonic()
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # rate window: first post-barrier send -> last reply (dial and
    # handshake excluded, so tiny captures don't read as unsustained)
    firsts = [w.t_first for w in workers if w.t_first is not None]
    lasts = [w.t_last for w in workers if w.t_last is not None]
    if firsts and lasts:
        wall = max(max(lasts) - min(firsts), 1e-9)
    else:
        wall = max(time.monotonic() - t0, 1e-9)
    lats = sorted(sum((w.lat_us for w in workers), []))

    def pct(p):
        return int(lats[min(len(lats) - 1,
                            int(p * len(lats)))]) if lats else 0

    sent = sum(w.sent for w in workers)
    mix: dict = {}
    for w in workers:
        for k in w.sent_keys:
            mix[k] = mix.get(k, 0) + 1
    ideal_s = span_s / speed if speed > 0 else 0.0
    return {"speed": speed, "sent": sent,
            "replies": sum(w.replies for w in workers),
            "skipped_truncated": sum(w.skipped for w in workers),
            "conn_errors": sum(w.errors for w in workers),
            "wall_s": round(wall, 6),
            "offered_rps": round(sent / ideal_s, 2)
            if ideal_s > 0 else float(sent),
            "achieved_rps": round(sent / wall, 2),
            "p50_us": pct(0.50), "p99_us": pct(0.99), "mix": mix}


KNEE_FRAC = 0.9      # knee = last speed sustaining 90% of offered


def sweep(records, host, port, authkey, speeds,
          stats_fn=None) -> dict:
    """Replay at each speed (ascending); -> {"rows", "knee_speed"}.

    `stats_fn() -> dict` (the serving /statsz "server" object) makes
    every round also assert server requests delta == frames sent."""
    rows = []
    knee = None
    orig_mix = op_mix(records)
    for sp in speeds:
        before = stats_fn() if stats_fn else None
        row = replay(records, host, port, authkey, speed=sp)
        if row["replies"] != row["sent"]:
            raise AssertionError(
                f"{sp}x: {row['sent']} sent but {row['replies']} "
                f"replies (conn_errors={row['conn_errors']})")
        if stats_fn:
            after = stats_fn()
            d = after["requests"] - before["requests"]
            if d != row["sent"]:
                raise AssertionError(
                    f"{sp}x: server requests delta {d} != "
                    f"frames sent {row['sent']}")
        ok, worst = mix_matches(orig_mix, row["mix"])
        row["mix_worst_delta"] = round(worst, 4)
        if not ok:
            raise AssertionError(
                f"{sp}x: replayed op mix off by {worst:.1%} "
                f"(> {REPLAY_MIX_TOL:.0%}): orig={orig_mix} "
                f"got={row['mix']}")
        sustained = (row["offered_rps"] <= 0
                     or row["achieved_rps"]
                     >= KNEE_FRAC * row["offered_rps"])
        row["sustained"] = bool(sustained)
        rows.append(row)
        if sustained:
            knee = sp
    return {"rows": rows, "knee_speed": knee,
            "orig_mix": orig_mix}


# ------------------------------------------------------ chaos soak
class SoakTally:
    """Client-observed chaos events — the reconciliation ledger."""

    def __init__(self):
        self.sent = 0
        self.replies = 0
        self.conn_deaths = 0        # EOF/reset AFTER the 0x01 ack
        self.handshake_drops = 0    # EOF DURING the handshake
        self.conns_opened = 0

    def as_dict(self):
        return dict(self.__dict__)


def chaos_soak(records, host, port, authkey, secs,
               speed: float = 8.0) -> SoakTally:
    """Loop the capture against a PTPU_CHAOS server for `secs`,
    reconnecting through injected conn kills and handshake drops and
    tallying every client-observed event for reconciliation."""
    tally = SoakTally()
    lock = threading.Lock()
    deadline = time.monotonic() + secs
    frames = [bytes(r["payload"]) for r in records
              if len(r["payload"]) >= r["frame_len"]]
    if not frames:
        raise ValueError("no complete frames to soak with")

    def worker(wid):
        i = wid      # stagger start offsets across workers
        while time.monotonic() < deadline:
            try:
                sock = dial_framed(host, port, authkey, timeout=30.0)
            except (ConnectionError, OSError):
                with lock:
                    tally.handshake_drops += 1
                continue
            with lock:
                tally.conns_opened += 1
            pending = 0
            try:
                sock.settimeout(30.0)
                while time.monotonic() < deadline:
                    f = frames[i % len(frames)]
                    i += 1
                    sock.sendall(_U32.pack(len(f)) + f)
                    with lock:
                        tally.sent += 1
                    pending += 1
                    # shallow pipeline: drain once 4 deep so kills
                    # strand only a handful of in-flight replies
                    while pending >= 4:
                        n = _U32.unpack(_read_exact(sock, 4))[0]
                        _read_exact(sock, n)
                        with lock:
                            tally.replies += 1
                        pending -= 1
                    if speed > 0:
                        time.sleep(0.001 / speed)
                while pending > 0:      # clean drain at deadline
                    n = _U32.unpack(_read_exact(sock, 4))[0]
                    _read_exact(sock, n)
                    with lock:
                        tally.replies += 1
                    pending -= 1
                sock.close()
                return
            except (ConnectionError, OSError):
                with lock:
                    tally.conn_deaths += 1
                sock.close()

    ts = [threading.Thread(target=worker, args=(w,))
          for w in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return tally


def reconcile_lossless(tally: SoakTally, before: dict,
                       after: dict) -> None:
    """Delay-style chaos (rdelay/wdelay/shortw) loses NOTHING: every
    counter must reconcile exactly, client against server."""
    d = {k: after[k] - before[k] for k in after}
    errs = []
    if tally.sent != tally.replies:
        errs.append(f"client sent {tally.sent} != "
                    f"replies {tally.replies}")
    if d["requests"] != tally.sent:
        errs.append(f"server requests {d['requests']} != "
                    f"client sent {tally.sent}")
    if d["replies"] != tally.replies:
        errs.append(f"server replies {d['replies']} != "
                    f"client replies {tally.replies}")
    if d["req_errors"] != 0:
        errs.append(f"req_errors {d['req_errors']} != 0")
    if tally.conn_deaths or tally.handshake_drops:
        errs.append("lossless kinds killed connections: "
                    f"{tally.as_dict()}")
    injected = (d["chaos_read_delays"] + d["chaos_write_delays"]
                + d["chaos_short_writes"])
    if injected == 0:
        errs.append("no faults injected — chaos not armed?")
    if errs:
        raise AssertionError("lossless reconcile: " + "; ".join(errs))


def reconcile_lossy(tally: SoakTally, before: dict,
                    after: dict) -> None:
    """kill/hsdrop chaos: dropped replies are expected, but every
    injected fault must map 1:1 to a client-observed event. The
    server-side ledger balance (requests == replies + req_errors and
    friends — the zero-stuck-requests proof this function used to
    re-derive by hand) now comes from the declarative ptpu_invar gate
    the soak runs at quiesce; only CLIENT-vs-server cross-checks
    live here."""
    d = {k: after[k] - before[k] for k in after}
    errs = []
    if d["chaos_conn_kills"] != tally.conn_deaths:
        errs.append(f"server kills {d['chaos_conn_kills']} != "
                    f"client conn deaths {tally.conn_deaths}")
    if d["chaos_handshake_drops"] != tally.handshake_drops:
        errs.append(
            f"server hsdrops {d['chaos_handshake_drops']} != client "
            f"handshake drops {tally.handshake_drops}")
    if d["handshake_fails"] != d["chaos_handshake_drops"]:
        errs.append(f"handshake_fails {d['handshake_fails']} != "
                    f"chaos drops {d['chaos_handshake_drops']}")
    if d["chaos_conn_kills"] + d["chaos_handshake_drops"] == 0:
        errs.append("no faults injected — chaos not armed?")
    if tally.replies > d["replies"]:
        errs.append(f"client saw {tally.replies} replies but server "
                    f"only counted {d['replies']}")
    if errs:
        raise AssertionError("lossy reconcile: " + "; ".join(errs))


def wait_conns_drained(stats_fn, timeout: float = 30.0) -> None:
    """Poll until the server's conns_active gauge returns to 0 —
    zero stuck sessions, the soak's exit condition."""
    deadline = time.monotonic() + timeout
    while True:
        n = stats_fn()["conns_active"]
        if n == 0:
            return
        if time.monotonic() > deadline:
            raise AssertionError(
                f"{n} connections still active after {timeout}s")
        time.sleep(0.05)


# ----------------------------------------------- self-hosted drills
def host_meta() -> dict:
    """Host fingerprint persisted into every drill/bench JSON (twin
    of the serving_bench/decode_bench "host" row)."""
    sig = hashlib.sha256()
    try:
        with open("/proc/cpuinfo", "rb") as f:
            for ln in f:
                if ln.startswith((b"model name", b"flags")):
                    sig.update(ln)
    except OSError:
        sig.update(b"unknown")
    return {"nproc": os.cpu_count() or 1,
            "cpu_sig": sig.hexdigest()[:16]}


def _export_mlp(tmpdir: str) -> str:
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.onnx.converter import trace_to_onnx

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(32, 64), pt.nn.ReLU(),
                           pt.nn.Linear(64, 8))
    net.eval()
    x = np.zeros((4, 32), np.float32)
    path = os.path.join(tmpdir, "mlp.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(lambda a: net(a), (jnp.asarray(x),)))
    return path


def _infer_frame(rid: int, rows: int, cols: int = 32,
                 seed: int = 0) -> bytes:
    """A raw v1 INFER frame: one float32 [rows, cols] input."""
    import numpy as np
    x = np.random.RandomState(seed).randn(rows, cols) \
        .astype(np.float32)
    return (bytes([WIRE_VERSION, TAG_INFER_REQ])
            + struct.pack("<QH", rid, 1)
            + bytes([1, 2])                       # f32, ndim 2
            + struct.pack("<qq", rows, cols) + x.tobytes())


def _live_traffic(host, port, authkey, n_conns=4, ops=60):
    """Original traffic for the capture phase: n_conns connections,
    each a pipelined mixed-row INFER stream (rows 1/2/4 in a 3:2:1
    mix — the per-op mix replay must reproduce)."""
    row_plan = [1, 1, 1, 2, 2, 4]

    def one(cid):
        sock = dial_framed(host, port, authkey)
        try:
            pending = 0
            for k in range(ops):
                rows = row_plan[k % len(row_plan)]
                f = _infer_frame(k, rows, seed=cid * 997 + k)
                sock.sendall(_U32.pack(len(f)) + f)
                pending += 1
                if pending >= 4:
                    n = _U32.unpack(_read_exact(sock, 4))[0]
                    _read_exact(sock, n)
                    pending -= 1
                time.sleep(0.002)   # shaped inter-arrival to replay
            while pending:
                n = _U32.unpack(_read_exact(sock, 4))[0]
                _read_exact(sock, n)
                pending -= 1
        finally:
            sock.close()

    ts = [threading.Thread(target=one, args=(c,))
          for c in range(n_conns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def _capture_lib():
    from paddle_tpu.core.native import _predictor_lib
    lib = _predictor_lib()
    if not getattr(lib, "_ptpu_has_capture", False):
        raise RuntimeError("stale _native_predictor.so: no capture "
                           "ABI — delete it and re-import")
    return lib


# ------------------------------------------ drills-off overhead A/B
def ab_leg(ops: int):
    """One measured leg in THIS process (the parent routed the native
    load via PTPU_PREDICTOR_SO and stripped every drill knob, so
    capture/chaos/shadow are fully OFF on both sides). Closed-loop
    pipelined INFERs; prints one `DRILLEG {json}` line."""
    import tempfile
    import numpy as np
    from paddle_tpu.inference import create_server

    tmpdir = tempfile.mkdtemp(prefix="ptpu_drill_ab_")
    model = _export_mlp(tmpdir)
    x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
    with create_server(model, max_batch=4, deadline_us=1500,
                       instances=2) as srv:
        cli = srv.client()
        cli.infer_many([[x]] * 64)          # warm: plans every bucket
        st0 = srv.stats()["server"]
        t0 = time.perf_counter()
        cli.infer_many([[x]] * ops)
        dt = time.perf_counter() - t0
        st1 = srv.stats()["server"]
        out = {"ops_per_s": round(ops / dt, 1),
               "exact": bool(
                   st1["requests"] - st0["requests"] == ops and
                   st1["replies"] - st0["replies"] == ops and
                   st1["req_errors"] == st0["req_errors"])}
        cli.close()
    print("DRILLEG " + json.dumps(out), flush=True)


def _ab_spawn_leg(so_pred, ops):
    import subprocess
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PTPU_CAPTURE_", "PTPU_SHADOW_")) or \
                k in ("PTPU_CHAOS", "PTPU_CHAOS_DELAY_US",
                      "PTPU_PREDICTOR_SO"):
            env.pop(k)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep +
                              env.get("PYTHONPATH", "")})
    if so_pred:
        env["PTPU_PREDICTOR_SO"] = so_pred
    r = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "ab-leg", "--ops", str(ops)], cwd=REPO,
                       env=env, capture_output=True, text=True,
                       timeout=900)
    if r.returncode != 0:
        raise RuntimeError(f"ab leg failed (so={so_pred}):\n"
                           f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("DRILLEG "):
            return json.loads(line[len("DRILLEG "):])
    raise RuntimeError("ab leg printed no DRILLEG row:\n"
                       + r.stdout[-2000:])


def _ab_build_baseline(ref: str):
    """Build the baseline predictor .so (a tree WITHOUT the drill
    code, e.g. the pre-drill commit) from a git ref in a detached
    worktree. Returns (so_path, worktree_path)."""
    import subprocess
    import tempfile
    tree = os.path.join(tempfile.mkdtemp(prefix="ptpu_drill_base_"),
                        "tree")
    subprocess.run(["git", "worktree", "add", "--detach", tree, ref],
                   cwd=REPO, check=True, capture_output=True)
    subprocess.run(["make", "-j4", "all"],
                   cwd=os.path.join(tree, "csrc"), check=True,
                   capture_output=True, timeout=1800)
    return (os.path.join(tree, "paddle_tpu",
                         "_native_predictor.so"), tree)


def off_overhead_ab(rounds=10, ops=600, baseline_so=None,
                    baseline_ref="HEAD"):
    """Drills-compiled-in-but-OFF vs a baseline .so built without the
    drill code (the r10 trace-bench methodology): leg order alternates
    per round to cancel machine drift, medians summarize. Gate: the
    off-mode server within 3% of the baseline's ops/s."""
    import subprocess
    tree = None
    if baseline_so is None:
        print(f"ab: building baseline .so from {baseline_ref} ...",
              flush=True)
        baseline_so, tree = _ab_build_baseline(baseline_ref)
        base_id = baseline_ref
    else:
        base_id = baseline_so

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    try:
        base, off = [], []
        for rnd in range(rounds):
            legs = [("base", baseline_so), ("off", None)]
            if rnd % 2:
                legs.reverse()
            for name, so in legs:
                row = _ab_spawn_leg(so, ops)
                (base if name == "base" else off).append(row)
                print(f"ab round {rnd} {name}: {row}", flush=True)
        mb = med([r["ops_per_s"] for r in base])
        mo = med([r["ops_per_s"] for r in off])
        overhead = round((mb - mo) / mb * 100.0, 2)
        return {"baseline": base_id, "rounds": rounds, "ops": ops,
                "base": [r["ops_per_s"] for r in base],
                "off": [r["ops_per_s"] for r in off],
                "base_ops_per_s": mb, "off_ops_per_s": mo,
                "overhead_pct": overhead,
                "within_3pct": bool(overhead <= 3.0),
                "acceptance_max_pct": 3.0,
                "exact": bool(all(r["exact"] for r in base + off))}
    finally:
        if tree:
            subprocess.run(["git", "worktree", "remove", "--force",
                            tree], cwd=REPO, capture_output=True)


def selfbench(out_path, speeds=(1, 2, 4, 8), n_conns=4, ops=60,
              ab_rounds=0, ab_ops=600, ab_baseline_so=None,
              ab_baseline_ref="HEAD"):
    """End-to-end drill evidence: capture live traffic on server A,
    replay the saved capture against fresh server B at a speed sweep.
    Writes the knee + p50/p99 report to `out_path`."""
    import tempfile
    from paddle_tpu.inference import create_server

    os.environ["PTPU_CAPTURE_SAMPLE"] = "1"
    os.environ["PTPU_CAPTURE_BYTES"] = "4096"
    os.environ["PTPU_CAPTURE_RING"] = "16384"
    tmpdir = tempfile.mkdtemp(prefix="ptpu_drill_")
    model = _export_mlp(tmpdir)
    lib = _capture_lib()
    cap_file = os.path.join(tmpdir, "drill.cap")

    with create_server(model, max_batch=4, deadline_us=1500,
                       instances=2) as srv:
        _live_traffic("127.0.0.1", srv.port, srv.authkey,
                      n_conns=n_conns, ops=ops)
        n = lib.ptpu_capture_save(cap_file.encode())
        if n <= 0:
            raise RuntimeError(f"ptpu_capture_save -> {n}")
    lib.ptpu_capture_set(0)     # replay servers must not re-capture

    records = load_capture(cap_file)
    print(f"captured {len(records)} frames "
          f"({len({r['conn'] for r in records})} conns), "
          f"mix={op_mix(records)}", flush=True)

    with create_server(model, max_batch=4, deadline_us=1500,
                       instances=2) as srv:
        report = sweep(records, "127.0.0.1", srv.port, srv.authkey,
                       list(speeds),
                       stats_fn=lambda: srv.stats()["server"])
    doc = {"bench": "ptpu_drill", "host": host_meta(),
           "captured_frames": len(records),
           "capture_conns": len({r["conn"] for r in records}),
           "knee_frac": KNEE_FRAC,
           "mix_tol": REPLAY_MIX_TOL, **report}
    if ab_rounds:
        doc["off_overhead_ab"] = off_overhead_ab(
            rounds=ab_rounds, ops=ab_ops, baseline_so=ab_baseline_so,
            baseline_ref=ab_baseline_ref)
        print(f"off_overhead_ab: {doc['off_overhead_ab']}",
              flush=True)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"knee_speed={report['knee_speed']}x -> {out_path}",
          flush=True)
    return doc


def selfsoak(secs: float):
    """run_checks.sh DRILL_SOAK_SECS leg: two chaos phases (lossless,
    then lossy) against self-hosted servers, each ending in EXACT
    counter reconciliation and a drained-connections check."""
    import tempfile
    from paddle_tpu.inference import create_server

    os.environ["PTPU_CAPTURE_SAMPLE"] = "1"
    os.environ["PTPU_CAPTURE_BYTES"] = "4096"
    tmpdir = tempfile.mkdtemp(prefix="ptpu_soak_")
    model = _export_mlp(tmpdir)
    lib = _capture_lib()

    # seed capture: a short clean run so the soak has frames to loop
    with create_server(model, max_batch=4, instances=2) as srv:
        _live_traffic("127.0.0.1", srv.port, srv.authkey,
                      n_conns=2, ops=20)
        cap_file = os.path.join(tmpdir, "soak.cap")
        if lib.ptpu_capture_save(cap_file.encode()) <= 0:
            raise RuntimeError("capture_save failed")
    lib.ptpu_capture_set(0)
    records = load_capture(cap_file)
    half = max(secs / 2.0, 1.0)

    phases = [("lossless", "rdelay,wdelay,shortw:17",
               reconcile_lossless),
              ("lossy", "kill,hsdrop:53", reconcile_lossy)]
    # conservation laws are a hard gate here: the C server's own
    # Stop() gate aborts on violation, and the Python twin re-checks
    # the drained snapshot before the client cross-checks run
    os.environ["PTPU_INVAR_FATAL"] = "1"
    from paddle_tpu.profiler.stats import invar_assert
    for name, chaos, check in phases:
        os.environ["PTPU_CHAOS"] = chaos
        os.environ["PTPU_CHAOS_DELAY_US"] = "500"
        try:
            with create_server(model, max_batch=4,
                               instances=2) as srv:
                stats = lambda: srv.stats()["server"]  # noqa: E731
                before = stats()
                tally = chaos_soak(records, "127.0.0.1", srv.port,
                                   srv.authkey, half)
                wait_conns_drained(stats)
                invar_assert(srv.stats(), f"soak[{name}]")
                check(tally, before, stats())
                print(f"soak[{name}] chaos={chaos}: "
                      f"{tally.as_dict()} reconciled exactly",
                      flush=True)
        finally:
            os.environ.pop("PTPU_CHAOS", None)
            os.environ.pop("PTPU_CHAOS_DELAY_US", None)
    print("selfsoak: OK", flush=True)


# ------------------------------------------------------------- CLI
def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("fetch", help="GET /capturez -> capture file")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--n", type=int, default=64)
    p.add_argument("--out", required=True)

    p = sub.add_parser("replay", help="re-fire a capture file")
    p.add_argument("--file", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--authkey-hex", required=True)
    p.add_argument("--speeds", default="1,2,4,8")
    p.add_argument("--out", default=None)

    p = sub.add_parser("soak", help="chaos soak against a live "
                                    "PTPU_CHAOS server")
    p.add_argument("--file", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--authkey-hex", required=True)
    p.add_argument("--secs", type=float, default=10.0)

    p = sub.add_parser("selfbench",
                       help="self-hosted capture->replay evidence")
    p.add_argument("--out", default="BENCH_DRILL_r01.json")
    p.add_argument("--speeds", default="1,2,4,8")
    p.add_argument("--ops", type=int, default=60)
    p.add_argument("--ab-rounds", type=int, default=0,
                   help="interleaved drills-off overhead A/B rounds "
                        "(0 = skip)")
    p.add_argument("--ab-ops", type=int, default=600)
    p.add_argument("--ab-baseline-so", default=None,
                   help="baseline _native_predictor.so (default: "
                        "build --ab-baseline-ref in a worktree)")
    p.add_argument("--ab-baseline-ref", default="HEAD",
                   help="git ref of the drill-free baseline tree")

    p = sub.add_parser("ab-leg",
                       help="(internal) one off-overhead A/B leg")
    p.add_argument("--ops", type=int, default=600)

    p = sub.add_parser("selfsoak",
                       help="self-hosted two-phase chaos drill")
    p.add_argument("--secs", type=float, default=10.0)

    a = ap.parse_args(argv)
    if a.cmd == "fetch":
        recs = fetch_capturez(a.host, a.port, a.n)
        save_capture(a.out, recs)
        print(f"{len(recs)} frames -> {a.out}")
    elif a.cmd == "replay":
        recs = load_capture(a.file)
        rep = sweep(recs, a.host, a.port,
                    bytes.fromhex(a.authkey_hex),
                    [float(s) for s in a.speeds.split(",")])
        txt = json.dumps(rep, indent=1, sort_keys=True)
        if a.out:
            with open(a.out, "w") as f:
                f.write(txt + "\n")
        print(txt)
    elif a.cmd == "soak":
        recs = load_capture(a.file)
        tally = chaos_soak(recs, a.host, a.port,
                           bytes.fromhex(a.authkey_hex), a.secs)
        # quiesce + conservation-law gate on the server's own verdict
        rep = assert_invarz(a.host, a.port, "soak")
        print(json.dumps({**tally.as_dict(),
                          "invar_checked": rep.get("checked", 0)}))
    elif a.cmd == "selfbench":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        selfbench(a.out,
                  speeds=[float(s) for s in a.speeds.split(",")],
                  ops=a.ops, ab_rounds=a.ab_rounds, ab_ops=a.ab_ops,
                  ab_baseline_so=a.ab_baseline_so,
                  ab_baseline_ref=a.ab_baseline_ref)
    elif a.cmd == "ab-leg":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        ab_leg(a.ops)
    elif a.cmd == "selfsoak":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        selfsoak(a.secs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
