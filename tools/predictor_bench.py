#!/usr/bin/env python
"""Native C-ABI predictor vs Python/XLA predictor benchmark.

VERDICT r4 item 5 acceptance gate: the C predictor (csrc/
ptpu_predictor.cc — blocked threaded SGEMM + im2col conv + op-code
dispatch) must serve ResNet-18 within 10x of the Python/XLA CPU
predictor. Also times the int8 artifact vs fp32 (VERDICT r4 item 10).

Reference bar: the native AnalysisPredictor engine
(`/root/reference/paddle/fluid/inference/api/analysis_predictor.cc:381`)
over the C API (`capi_exp/pd_inference_api.h:1`).

Run: python tools/predictor_bench.py  (CPU-only; forces jax to CPU)
Prints one JSON line per measurement and a final summary line with the
native/XLA ratio.
"""
from __future__ import annotations

import ctypes
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_artifact(tmp, batch):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.onnx import export
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision.models import resnet18

    model = resnet18(num_classes=1000)
    model.eval()
    path = export(model, os.path.join(tmp, "resnet18"),
                  input_spec=[InputSpec([batch, 3, 224, 224], "float32")])
    return model, path


def time_native(path, x, steps=5, warmup=1):
    lib = ctypes.CDLL(os.path.join(REPO, "paddle_tpu",
                                   "_native_predictor.so"))
    lib.ptpu_predictor_create.restype = ctypes.c_void_p
    err = ctypes.create_string_buffer(512)
    h = lib.ptpu_predictor_create(path.encode(), err, 512)
    assert h, err.value.decode()
    nd = len(x.shape)
    dims = (ctypes.c_int64 * nd)(*x.shape)
    data = x.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    lib.ptpu_predictor_input_name.restype = ctypes.c_char_p
    name = lib.ptpu_predictor_input_name(ctypes.c_void_p(h), 0)

    def once():
        rc = lib.ptpu_predictor_set_input(ctypes.c_void_p(h), name, data,
                                          dims, nd, err, 512)
        assert rc == 0, err.value.decode()
        rc = lib.ptpu_predictor_run(ctypes.c_void_p(h), err, 512)
        assert rc == 0, err.value.decode()

    for _ in range(warmup):
        once()
    t0 = time.perf_counter()
    for _ in range(steps):
        once()
    dt = (time.perf_counter() - t0) / steps

    # fetch the output for a correctness cross-check
    import numpy as np
    lib.ptpu_predictor_output_ndim.restype = ctypes.c_int
    lib.ptpu_predictor_output_dims.restype = \
        ctypes.POINTER(ctypes.c_int64)
    lib.ptpu_predictor_output_data.restype = \
        ctypes.POINTER(ctypes.c_float)
    nd = lib.ptpu_predictor_output_ndim(ctypes.c_void_p(h), 0)
    dd = lib.ptpu_predictor_output_dims(ctypes.c_void_p(h), 0)
    shape = [dd[k] for k in range(nd)]
    numel = int(np.prod(shape)) if shape else 1
    dp = lib.ptpu_predictor_output_data(ctypes.c_void_p(h), 0)
    out = np.ctypeslib.as_array(dp, (numel,)).copy()
    lib.ptpu_predictor_destroy(ctypes.c_void_p(h))
    return dt, out


def time_xla(model, x, steps=10, warmup=2):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)

    params = trainable_state(model)
    buffers = buffer_state(model)

    @jax.jit
    def fwd(params, x):
        out, _ = functional_call(model, params, x, buffers=buffers)
        return out

    xj = jnp.asarray(x)
    out = fwd(params, xj)
    out.block_until_ready()
    for _ in range(warmup):
        fwd(params, xj).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        fwd(params, xj).block_until_ready()
    dt = (time.perf_counter() - t0) / steps
    import numpy as np
    return dt, np.asarray(out)


def _export_bytes(tmp, name, fn, args):
    from paddle_tpu.onnx.converter import trace_to_onnx
    path = os.path.join(tmp, name + ".onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(fn, args))
    return path


def bench_int8(tmp):
    """int8-executing artifact vs the same fp32 MLP through the C
    predictor (VERDICT r4 item 10: the int8 path existed untimed)."""
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.quantization import QAT, convert_to_int8

    def mlp():
        pt.seed(0)
        return pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 512))

    rs = np.random.RandomState(0)
    x = rs.randn(64, 512).astype(np.float32)

    net_f = mlp()
    net_f.eval()
    p_f = _export_bytes(tmp, "mlp_f32", lambda a: net_f(a),
                        (jnp.asarray(x),))

    net_q = mlp()
    QAT().quantize(net_q)
    net_q.train()
    net_q(jnp.asarray(x))   # observer pass
    net_q.eval()
    convert_to_int8(net_q)
    p_q = _export_bytes(tmp, "mlp_int8", lambda a: net_q(a),
                        (jnp.asarray(x),))

    dt_f, _ = time_native(p_f, x, steps=10, warmup=2)
    dt_q, _ = time_native(p_q, x, steps=10, warmup=2)
    print(json.dumps({"metric": "mlp_native_fp32_ms",
                      "value": round(dt_f * 1e3, 2), "unit": "ms"}),
          flush=True)
    print(json.dumps({"metric": "mlp_native_int8_ms",
                      "value": round(dt_q * 1e3, 2), "unit": "ms",
                      "int8_over_fp32": round(dt_q / dt_f, 2)}),
          flush=True)


def bench_bert_tiny(tmp):
    """Transformer serving through the C engine vs XLA: BERT-tiny with
    int32 token ids — the path where every attention dot_general lowers
    to Transpose/Reshape/batched-MatMul (r5: odometer transpose +
    row-copy gather keep these off the scalar fallback)."""
    import ctypes

    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models import BertModel, bert_tiny
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)
    from paddle_tpu.static import InputSpec

    pt.seed(0)
    m = BertModel(bert_tiny())
    m.eval()
    path = pt.onnx.export(m, os.path.join(tmp, "bert_tiny"),
                          input_spec=[InputSpec([4, 128], "int32")])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, bert_tiny().vocab_size, (4, 128)).astype(np.int32)

    params = trainable_state(m)
    buffers = buffer_state(m)

    @jax.jit
    def fwd(params, ids):
        out, _ = functional_call(m, params, ids, buffers=buffers)
        return out[0] if isinstance(out, (tuple, list)) else out

    xj = jnp.asarray(ids)
    fwd(params, xj).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        fwd(params, xj).block_until_ready()
    dt_xla = (time.perf_counter() - t0) / 10

    lib = ctypes.CDLL(os.path.join(REPO, "paddle_tpu",
                                   "_native_predictor.so"))
    lib.ptpu_predictor_create.restype = ctypes.c_void_p
    lib.ptpu_predictor_input_name.restype = ctypes.c_char_p
    err = ctypes.create_string_buffer(512)
    h = lib.ptpu_predictor_create(path.encode(), err, 512)
    assert h, err.value.decode()
    name = lib.ptpu_predictor_input_name(ctypes.c_void_p(h), 0)
    dims = (ctypes.c_int64 * 2)(4, 128)
    data = ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    def once():
        assert lib.ptpu_predictor_set_input_i32(
            ctypes.c_void_p(h), name, data, dims, 2, err, 512) == 0, \
            err.value.decode()
        assert lib.ptpu_predictor_run(ctypes.c_void_p(h), err, 512) == 0, \
            err.value.decode()

    once()
    t0 = time.perf_counter()
    for _ in range(5):
        once()
    dt_nat = (time.perf_counter() - t0) / 5
    lib.ptpu_predictor_destroy(ctypes.c_void_p(h))
    print(json.dumps({"metric": "bert_tiny_native_over_xla_ratio",
                      "value": round(dt_nat / dt_xla, 2), "unit": "x",
                      "native_ms": round(dt_nat * 1e3, 2),
                      "xla_ms": round(dt_xla * 1e3, 2)}), flush=True)


def main():
    import tempfile

    import numpy as np

    batch = int(os.environ.get("PTPU_PREDBENCH_BATCH", "1"))
    with tempfile.TemporaryDirectory() as tmp:
        model, path = build_artifact(tmp, batch)
        rs = np.random.RandomState(0)
        x = rs.randn(batch, 3, 224, 224).astype(np.float32)

        dt_xla, out_xla = time_xla(model, x)
        print(json.dumps({"metric": "resnet18_xla_cpu_ms",
                          "value": round(dt_xla * 1e3, 2), "unit": "ms",
                          "batch": batch}), flush=True)

        dt_nat, out_nat = time_native(path, x)
        print(json.dumps({"metric": "resnet18_native_c_ms",
                          "value": round(dt_nat * 1e3, 2), "unit": "ms",
                          "batch": batch}), flush=True)

        np.testing.assert_allclose(
            out_nat.reshape(out_xla.shape), out_xla, rtol=2e-3, atol=2e-4)
        ratio = dt_nat / dt_xla
        print(json.dumps({
            "metric": "resnet18_native_over_xla_ratio",
            "value": round(ratio, 2), "unit": "x",
            "within_10x": bool(ratio <= 10.0)}), flush=True)

        bench_int8(tmp)
        bench_bert_tiny(tmp)


if __name__ == "__main__":
    main()
