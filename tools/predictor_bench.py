#!/usr/bin/env python
"""Native C-ABI predictor vs Python/XLA predictor benchmark.

VERDICT r4 item 5 acceptance gate, tightened by ISSUE r6: the C
predictor (csrc/ptpu_predictor.cc — packed cache-blocked GEMM with an
AVX2/FMA micro-kernel, load-time op fusion (conv+bn+relu, gemm+bias+act,
binary+act), static arena memory planning, pre-packed weights) serves
ResNet-18 against the Python/XLA CPU predictor. Also times the int8
artifact vs fp32 (VERDICT r4 item 10) and BERT-tiny transformer serving.

Reference bar: the native AnalysisPredictor engine
(`/root/reference/paddle/fluid/inference/api/analysis_predictor.cc:381`)
over the C API (`capi_exp/pd_inference_api.h:1`).

Run: python tools/predictor_bench.py [--out BENCH_SELF_rNN.json]
(CPU-only; forces jax to CPU). Rebuilds the native library with
MARCH=-march=native first — the benchmarking ISA opt-in; the Makefile
default stays portable (x86-64-v2) so shipped artifacts don't SIGILL.
Prints one JSON line per measurement and a final summary line with the
native/XLA ratio.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESULTS = []


def emit(rec):
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def build_native():
    """Benchmarking build: full native ISA (AVX2/FMA micro-kernel)."""
    try:
        subprocess.run(["make", "-B", "all", "MARCH=-march=native"],
                       cwd=os.path.join(REPO, "csrc"), check=True,
                       capture_output=True, timeout=600)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"# native rebuild skipped ({e}); using existing .so",
              file=sys.stderr)


def build_artifact(tmp, batch):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.onnx import export
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision.models import resnet18

    model = resnet18(num_classes=1000)
    model.eval()
    path = export(model, os.path.join(tmp, "resnet18"),
                  input_spec=[InputSpec([batch, 3, 224, 224], "float32")])
    return model, path


def time_native(path, x, steps=5, warmup=1):
    from paddle_tpu.core.native import NativePredictor

    with NativePredictor(path) as p:
        name = p.input_name(0)

        def once():
            p.set_input(name, x)
            p.run()

        for _ in range(warmup):
            once()
        t0 = time.perf_counter()
        for _ in range(steps):
            once()
        dt = (time.perf_counter() - t0) / steps
        out = p.output(0)
        stats = (p.num_nodes, p.fused_nodes, p.arena_bytes)
    return dt, out.reshape(-1), stats


def time_xla(model, x, steps=10, warmup=2):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)

    params = trainable_state(model)
    buffers = buffer_state(model)

    @jax.jit
    def fwd(params, x):
        out, _ = functional_call(model, params, x, buffers=buffers)
        return out

    xj = jnp.asarray(x)
    out = fwd(params, xj)
    out.block_until_ready()
    for _ in range(warmup):
        fwd(params, xj).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        fwd(params, xj).block_until_ready()
    dt = (time.perf_counter() - t0) / steps
    import numpy as np
    return dt, np.asarray(out)


def _export_bytes(tmp, name, fn, args):
    from paddle_tpu.onnx.converter import trace_to_onnx
    path = os.path.join(tmp, name + ".onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(fn, args))
    return path


def bench_int8(tmp):
    """int8-executing artifact vs the same fp32 MLP through the C
    predictor (VERDICT r4 item 10: the int8 path existed untimed)."""
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.quantization import QAT, convert_to_int8

    def mlp():
        pt.seed(0)
        return pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 512))

    rs = np.random.RandomState(0)
    x = rs.randn(64, 512).astype(np.float32)

    net_f = mlp()
    net_f.eval()
    p_f = _export_bytes(tmp, "mlp_f32", lambda a: net_f(a),
                        (jnp.asarray(x),))

    net_q = mlp()
    QAT().quantize(net_q)
    net_q.train()
    net_q(jnp.asarray(x))   # observer pass
    net_q.eval()
    convert_to_int8(net_q)
    p_q = _export_bytes(tmp, "mlp_int8", lambda a: net_q(a),
                        (jnp.asarray(x),))

    dt_f, _, _ = time_native(p_f, x, steps=10, warmup=2)
    dt_q, _, _ = time_native(p_q, x, steps=10, warmup=2)
    emit({"metric": "mlp_native_fp32_ms",
          "value": round(dt_f * 1e3, 2), "unit": "ms"})
    emit({"metric": "mlp_native_int8_ms",
          "value": round(dt_q * 1e3, 2), "unit": "ms"})
    # FIRST-CLASS ratio metric with a regression gate (ISSUE r8
    # satellite): r06 shipped the int8 MLP at 3.24x SLOWER than fp32
    # because the activation quantize/dequantize chains ran as ~11
    # unfused memory-bound passes per layer; the load-time
    # PtpuQuantize/PtpuDequant fusion (csrc/ptpu_predictor.cc
    # fuse_quant_ops) + specialized elementwise loops brought it to
    # ~1.6-1.8x on this machine. int8 still trails fp32 — the int32
    # AVX2 kernel is no faster than FMA and the quant traffic is extra
    # work — so the gate holds the REGRESSION line (< 2.5x), not a
    # speedup claim. If this trips, profile the Ptpu* quant ops first.
    ratio = round(dt_q / dt_f, 2)
    emit({"metric": "mlp_int8_over_fp32_ratio", "value": ratio,
          "unit": "x", "regression_gate": 2.5,
          "within_gate": bool(ratio <= 2.5),
          "note": "r06 regression was 3.24x; fixed by load-time "
                  "quant-chain fusion (PtpuQuantize/PtpuDequant)"})


def bench_int4(tmp):
    """Weight-only int4 (ISSUE 16, PTPU_INT4=1) vs the same fp32 MLP,
    loaded side by side (the knob is read per load) with interleaved
    timed blocks per the r10 noise methodology. Two shapes: M=64
    (compute-bound GEMM — the dequant-in-register epilogue must not
    regress it past the gate) and M=1 (the decode GEMV, where 8x less
    weight traffic is the whole point — the >= 1.5x CLAIM is gated on
    the GPT decode bench, here the batch-1 win is recorded and held
    above break-even). Quality is a measured bound, not parity: int4
    is lossy, and Gaussian random weights are its worst case (~10%
    relative L2 regardless of K)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.native import NativePredictor

    def mlp():
        pt.seed(0)
        return pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                                pt.nn.Linear(2048, 512))

    net = mlp()
    net.eval()
    rs = np.random.RandomState(0)
    x64 = rs.randn(64, 512).astype(np.float32)
    path = _export_bytes(tmp, "mlp_i4", lambda a: net(a),
                         (jnp.asarray(x64),))
    x1 = rs.randn(1, 512).astype(np.float32)
    path1 = _export_bytes(tmp, "mlp_i4_b1", lambda a: net(a),
                          (jnp.asarray(x1),))

    def load(p, int4):
        if int4:
            os.environ["PTPU_INT4"] = "1"
        try:
            return NativePredictor(p)
        finally:
            os.environ.pop("PTPU_INT4", None)

    def timed(p, x, steps):
        name = p.input_name(0)
        t0 = time.perf_counter()
        for _ in range(steps):
            p.set_input(name, x)
            p.run()
        return (time.perf_counter() - t0) / steps

    for label, mpath, x, steps, gate_kind in (
            ("m64", path, x64, 5, "regression"),
            ("m1", path1, x1, 50, "speedup")):
        pf = load(mpath, False)
        pq = load(mpath, True)
        # quality first (also warms both instances)
        pf.set_input(pf.input_name(0), x)
        pf.run()
        ref = pf.output(0)
        pq.set_input(pq.input_name(0), x)
        pq.run()
        got = pq.output(0)
        rel = float(np.linalg.norm(got - ref) /
                    max(np.linalg.norm(ref), 1e-12))
        engaged = not np.array_equal(got, ref)
        tf, tq = [], []
        for rnd in range(4):
            legs = [(tq, pq, x), (tf, pf, x)]
            if rnd % 2:
                legs.reverse()
            for acc, p, xx in legs:
                acc.append(timed(p, xx, steps))
        pf.close()
        pq.close()
        dt_f = float(np.mean(tf))
        dt_q = float(np.mean(tq))
        ratio = round(dt_q / dt_f, 2)
        if gate_kind == "regression":
            # M=64 is FLOP-bound: int4 adds dequant work per tile, so
            # the gate only holds the line (same rationale as the
            # int8 2.5x gate), it claims no speedup
            gate = {"regression_gate": 1.5,
                    "within_gate": bool(ratio <= 1.5)}
        else:
            # M=1 GEMV is weight-bandwidth-bound: int4 must at least
            # break even here or the packed layout is broken
            gate = {"acceptance_gate": 1.0,
                    "within_gate": bool(ratio <= 1.0)}
        emit({"metric": f"mlp_int4_over_fp32_ratio_{label}",
              "value": ratio, "unit": "x",
              "fp32_ms": round(dt_f * 1e3, 2),
              "int4_ms": round(dt_q * 1e3, 2),
              "quality_rel_l2": round(rel, 4),
              "quality_bound": 0.15, "engaged": bool(engaged),
              "quality_ok": bool(engaged and rel <= 0.15), **gate})


def bench_bert_tiny(tmp):
    """Transformer serving through the C engine vs XLA: BERT-tiny with
    int32 token ids — attention dot_generals lower to Transpose/Reshape/
    batched-MatMul (r6: batch-parallel packed GEMM + threaded
    elementwise/transpose keep this path on the fast engine)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models import BertModel, bert_tiny
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)
    from paddle_tpu.static import InputSpec

    pt.seed(0)
    m = BertModel(bert_tiny())
    m.eval()
    path = pt.onnx.export(m, os.path.join(tmp, "bert_tiny"),
                          input_spec=[InputSpec([4, 128], "int32")])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, bert_tiny().vocab_size, (4, 128)).astype(np.int32)

    params = trainable_state(m)
    buffers = buffer_state(m)

    @jax.jit
    def fwd(params, ids):
        out, _ = functional_call(m, params, ids, buffers=buffers)
        return out[0] if isinstance(out, (tuple, list)) else out

    xj = jnp.asarray(ids)
    fwd(params, xj).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        fwd(params, xj).block_until_ready()
    dt_xla = (time.perf_counter() - t0) / 10

    dt_nat, _, _ = time_native(path, ids, steps=10, warmup=2)
    # FIRST-CLASS gated metric (ISSUE r9 satellite): r07 shipped
    # BERT-tiny at 2.70x XLA because the attention/softmax/LayerNorm
    # glue ran as ~40 unfused passes per layer. The r9 load-time
    # fusions (PtpuAttention flash kernel, PtpuLayerNorm, PtpuGelu,
    # no-op-Cast elimination) + runtime-dispatched AVX-512 micro-
    # kernels brought it to ~1.0x on this machine. The gate holds the
    # tentpole's acceptance line (<= 1.3x). If this trips, profile the
    # Ptpu* transformer ops first (PTPU_PREDICTOR_PROFILE=1).
    ratio = round(dt_nat / dt_xla, 2)
    emit({"metric": "bert_tiny_native_over_xla_ratio",
          "value": ratio, "unit": "x",
          "native_ms": round(dt_nat * 1e3, 2),
          "xla_ms": round(dt_xla * 1e3, 2),
          "regression_gate": 1.3,
          "within_gate": bool(ratio <= 1.3),
          "note": "r07 was 2.70x; closed by load-time attention/LN/"
                  "GELU fusion + cpuid-dispatched AVX-512 kernels"})


def main():
    import tempfile

    import numpy as np

    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: predictor_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    build_native()
    batch = int(os.environ.get("PTPU_PREDBENCH_BATCH", "1"))
    with tempfile.TemporaryDirectory() as tmp:
        model, path = build_artifact(tmp, batch)
        rs = np.random.RandomState(0)
        x = rs.randn(batch, 3, 224, 224).astype(np.float32)

        dt_xla, out_xla = time_xla(model, x)
        emit({"metric": "resnet18_xla_cpu_ms",
              "value": round(dt_xla * 1e3, 2), "unit": "ms",
              "batch": batch})

        dt_nat, out_nat, stats = time_native(path, x)
        emit({"metric": "resnet18_native_c_ms",
              "value": round(dt_nat * 1e3, 2), "unit": "ms",
              "batch": batch, "nodes": stats[0],
              "fused_nodes": stats[1], "arena_mb":
              round(stats[2] / 1e6, 1)})

        np.testing.assert_allclose(
            out_nat.reshape(out_xla.shape), out_xla, rtol=2e-3, atol=2e-4)
        ratio = dt_nat / dt_xla
        emit({"metric": "resnet18_native_over_xla_ratio",
              "value": round(ratio, 2), "unit": "x",
              "within_10x": bool(ratio <= 10.0)})

        bench_int8(tmp)
        bench_int4(tmp)
        bench_bert_tiny(tmp)

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "predictor_bench",
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    main()
