#!/usr/bin/env python
"""C10K bench for the shared epoll network core (ISSUE 7 tentpole).

Drives the REAL C PS data-plane server (csrc/ptpu_ps_server.cc over
csrc/ptpu_net.cc) with thousands of CONCURRENT framed clients from an
epoll-based multi-connection client (selectors.DefaultSelector — epoll
on Linux), spread over NPROC client processes:

  1. ramp    — every process connects + HMAC-handshakes its share of
               connections (chunked so the listen backlog never
               overflows); all processes barrier with every connection
               OPEN, and the parent samples the server's live
               conns_active gauge at the hold point;
  2. ops     — every connection issues OPS_PER_CONN small framed pulls,
               one in flight per connection, driven by the epoll
               client loop; per-request latency is recorded;
  3. drain   — connections close; the parent checks the server's
               counters against the client-observed totals EXACTLY
               (zero protocol errors, zero handshake failures).

An optional serving leg repeats the hold + ops pattern against the
inference runtime (csrc/ptpu_serving.cc) with a small MLP artifact —
skipped when the serving runtime or jax is unavailable.

The headline is connection SCALE and tail latency, not bandwidth
(this box's loopback plateaus at ~2.6-2.9 GB/s; see ROADMAP): the
acceptance gate is >= 5,000 concurrent framed clients served with
zero protocol errors and counters exact.

Config via env: PTPU_NETBENCH_{CONNS,PROCS,OPS,BATCH,DIM,SERVING_CONNS}
Run: python tools/net_bench.py [--out BENCH_NET_r01.json]
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import resource
import selectors
import socket
import struct
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONNS = int(os.environ.get("PTPU_NETBENCH_CONNS", 5120))
PROCS = int(os.environ.get("PTPU_NETBENCH_PROCS", 8))
OPS = int(os.environ.get("PTPU_NETBENCH_OPS", 5))       # per conn
BATCH = int(os.environ.get("PTPU_NETBENCH_BATCH", 8))   # ids per pull
DIM = int(os.environ.get("PTPU_NETBENCH_DIM", 16))
SERVING_CONNS = int(os.environ.get("PTPU_NETBENCH_SERVING_CONNS", 1024))
AUTHKEY = b"net-bench-key"

_U32 = struct.Struct("<I")

RESULTS: list = []


def emit(row: dict):
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _raise_nofile(need: int):
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = min(hard, max(soft, need + 256))
    if want > soft:
        resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))


# ---------------------------------------------------------------------------
# epoll client (one process's share of the connection herd)
# ---------------------------------------------------------------------------

class _Conn:
    __slots__ = ("sock", "ops_left", "t_sent", "rx", "want",
                 "latencies", "errors")

    def __init__(self, sock):
        self.sock = sock
        self.ops_left = OPS
        self.t_sent = 0.0
        self.rx = bytearray()
        self.want = 4
        self.latencies = []
        self.errors = 0


def _client_proc(pidx, my_conns, port, req_frame, rep_tag, rep_len,
                 barrier, q):
    """Connect `my_conns` conns, barrier at the hold point, then run
    the request loop over one shared epoll selector. (`my_conns` is an
    explicit arg: under the spawn start method children re-derive
    module globals from env, so a parent-side override would be
    lost.)"""
    import hashlib
    import hmac
    _raise_nofile(my_conns)
    conns = []
    t_ramp0 = time.perf_counter()
    for i in range(my_conns):
        s = socket.create_connection(("127.0.0.1", port), timeout=60)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # blocking handshake during ramp (simple + it IS the slow-path
        # the server must survive 5k times over)
        nonce = b""
        while len(nonce) < 16:
            c = s.recv(16 - len(nonce))
            if not c:
                raise ConnectionError("EOF during bench handshake")
            nonce += c
        mac = hmac.new(AUTHKEY, nonce, hashlib.sha256).digest()
        s.sendall(_U32.pack(32) + mac)
        ok = s.recv(1)
        if ok != b"\x01":
            raise ConnectionError("bench handshake rejected")
        conns.append(_Conn(s))
        if i % 64 == 63:
            time.sleep(0.001)  # keep the SYN burst under the backlog
    t_ramp = time.perf_counter() - t_ramp0

    barrier.wait(timeout=600)   # every process fully connected (hold)
    barrier.wait(timeout=600)   # parent sampled conns_active

    sel = selectors.DefaultSelector()
    framed = _U32.pack(len(req_frame)) + req_frame
    for c in conns:
        c.sock.setblocking(False)
        sel.register(c.sock, selectors.EVENT_READ, c)
        c.t_sent = time.perf_counter()
        c.sock.sendall(framed)  # first request (fits the send buffer)
    pending = len(conns)
    t_ops0 = time.perf_counter()
    while pending > 0:
        for key, _ in sel.select(timeout=30):
            c = key.data
            try:
                chunk = c.sock.recv(65536)
            except BlockingIOError:
                continue
            if not chunk:
                c.errors += 1
                sel.unregister(c.sock)
                pending -= 1
                continue
            c.rx += chunk
            # parse complete reply frames out of the stream
            while True:
                if len(c.rx) < 4:
                    break
                n = _U32.unpack_from(c.rx, 0)[0]
                if len(c.rx) < 4 + n:
                    break
                frame = bytes(c.rx[4:4 + n])
                del c.rx[:4 + n]
                if (rep_len is not None and n != rep_len) or \
                        len(frame) < 2 or frame[1] != rep_tag:
                    c.errors += 1
                c.latencies.append(time.perf_counter() - c.t_sent)
                c.ops_left -= 1
                if c.ops_left > 0:
                    c.t_sent = time.perf_counter()
                    c.sock.sendall(framed)
                else:
                    sel.unregister(c.sock)
                    pending -= 1
                    break
    t_ops = time.perf_counter() - t_ops0
    lats, errs = [], 0
    for c in conns:
        lats.extend(c.latencies)
        errs += c.errors
        c.sock.close()
    sel.close()
    q.put({"pidx": pidx, "conns": my_conns, "t_ramp": t_ramp,
           "t_ops": t_ops, "latencies": lats, "errors": errs})


# ---------------------------------------------------------------------------
# PS leg
# ---------------------------------------------------------------------------

def run_ps_leg():
    import numpy as np

    from paddle_tpu.core import native
    from paddle_tpu.distributed.ps import wire

    if not native.ps_server_available():
        emit({"metric": "net_c10k_conns_held", "value": 0,
              "unit": "conns", "note": "native PS server unavailable"})
        return

    _raise_nofile(CONNS)
    vocab = 4096
    table = native.NativePsTable(vocab, DIM, "sgd", lr=1.0)
    table.data[:] = np.random.RandomState(0).randn(
        vocab, DIM).astype(np.float32)
    srv = native.PsDataServer(0, AUTHKEY)
    srv.register("t", table, lo=0)

    ids = np.arange(BATCH, dtype=np.int64)
    req = bytes(wire.build_pull_req("t", ids))
    rep_len = 10 + BATCH * DIM * 4   # PULL_REP header + body

    barrier = mp.Barrier(PROCS + 1)
    q: "mp.Queue" = mp.Queue()
    shares = [CONNS // PROCS + (1 if i < CONNS % PROCS else 0)
              for i in range(PROCS)]
    procs = [mp.Process(target=_client_proc,
                        args=(i, shares[i], srv.port, req, 0x51,
                              rep_len, barrier, q))
             for i in range(PROCS)]
    t0 = time.perf_counter()
    for p in procs:
        p.start()
    barrier.wait(timeout=600)          # hold point: all conns open
    held = srv.stats()["server"]["conns_active"]
    t_all_connected = time.perf_counter() - t0
    barrier.wait(timeout=600)          # release the op phase

    res = [q.get(timeout=600) for _ in range(PROCS)]
    for p in procs:
        p.join(timeout=120)

    lats = sorted(x for r in res for x in r["latencies"])
    total_ops = len(lats)
    errors = sum(r["errors"] for r in res)
    wall = max(r["t_ops"] for r in res)
    st = srv.stats()["server"]

    def pct(p):
        return round(lats[min(len(lats) - 1,
                              int(p * len(lats)))] * 1e3, 3)

    emit({"metric": "net_c10k_conns_held", "value": int(held),
          "unit": "conns", "target": CONNS, "procs": PROCS,
          "ramp_s": round(t_all_connected, 2),
          "note": "live conns_active gauge with every client open"})
    emit({"metric": "net_c10k_pull_ops_per_s",
          "value": round(total_ops / wall, 1), "unit": "ops/s",
          "conns": CONNS, "ops_per_conn": OPS, "batch": BATCH,
          "dim": DIM, "p50_ms": pct(0.50), "p99_ms": pct(0.99),
          "client_errors": errors})
    emit({"metric": "net_c10k_counters_exact",
          "value": int(errors == 0 and
                       st["conns_accepted"] == CONNS and
                       st["pull_ops"] == total_ops and
                       total_ops == CONNS * OPS and
                       st["proto_errors"] == 0 and
                       st["handshake_fails"] == 0 and
                       st["err_frames"] == 0),
          "unit": "bool", "server_conns_accepted": st["conns_accepted"],
          "server_pull_ops": st["pull_ops"],
          "client_ops": total_ops, "expected_ops": CONNS * OPS,
          "proto_errors": st["proto_errors"],
          "handshake_fails": st["handshake_fails"],
          "conns_shed": st["conns_shed"],
          "epoll_wakeups": st["epoll_wakeups"],
          "partial_write_flushes": st["partial_write_flushes"]})
    srv.stop()
    table.close()


# ---------------------------------------------------------------------------
# serving leg (INFER frames through the micro-batcher)
# ---------------------------------------------------------------------------

def run_serving_leg(tmpdir):
    try:
        import jax.numpy as jnp
        import numpy as np

        import paddle_tpu as pt
        from paddle_tpu.core import native
        from paddle_tpu.onnx.converter import trace_to_onnx
        if not native.serving_available():
            raise RuntimeError("serving unavailable")
    except Exception as e:  # noqa: BLE001 — leg is optional
        emit({"metric": "net_serving_conns_held", "value": 0,
              "unit": "conns", "note": f"skipped: {e!r}"})
        return

    from paddle_tpu.inference import create_server

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(16, 32), pt.nn.ReLU(),
                           pt.nn.Linear(32, 4))
    net.eval()
    path = os.path.join(tmpdir, "net_bench_mlp.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(
            lambda a: net(a),
            (jnp.asarray(np.zeros((2, 16), np.float32)),)))

    srv = create_server(path, authkey=AUTHKEY, max_batch=32,
                        deadline_us=2000, instances=2)

    # one-row INFER frame (id 7): [ver][tag][u64 id][u16 nin]
    # [dtype][ndim][dims][f32 raw]
    x = np.full((1, 16), 0.5, np.float32)
    req = bytearray([1, 0x60])
    req += struct.pack("<Q", 7) + struct.pack("<H", 1)
    req += bytes([1, 2]) + struct.pack("<qq", 1, 16) + x.tobytes()

    nconns, nprocs = SERVING_CONNS, max(2, PROCS // 2)
    try:
        barrier = mp.Barrier(nprocs + 1)
        q: "mp.Queue" = mp.Queue()
        shares = [nconns // nprocs + (1 if i < nconns % nprocs else 0)
                  for i in range(nprocs)]
        procs = [mp.Process(target=_client_proc,
                            args=(i, shares[i], srv.port, bytes(req),
                                  0x61, None, barrier, q))
                 for i in range(nprocs)]
        for p in procs:
            p.start()
        barrier.wait(timeout=600)
        held = srv.stats()["server"]["conns_active"]
        barrier.wait(timeout=600)
        res = [q.get(timeout=600) for _ in range(nprocs)]
        for p in procs:
            p.join(timeout=120)
        lats = sorted(x2 for r in res for x2 in r["latencies"])
        errors = sum(r["errors"] for r in res)
        wall = max(r["t_ops"] for r in res)
        st = srv.stats()
        emit({"metric": "net_serving_conns_held", "value": int(held),
              "unit": "conns", "target": nconns})
        emit({"metric": "net_serving_infer_ops_per_s",
              "value": round(len(lats) / wall, 1), "unit": "ops/s",
              "conns": nconns, "ops_per_conn": OPS,
              "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
              "p99_ms": round(lats[min(len(lats) - 1,
                                       int(0.99 * len(lats)))] * 1e3,
                              3),
              "client_errors": errors,
              "server_requests": st["server"]["requests"],
              "server_replies": st["server"]["replies"],
              "batches": st["batcher"]["batches"],
              "counters_exact": int(
                  errors == 0 and
                  st["server"]["requests"] == nconns * OPS and
                  st["server"]["replies"] == nconns * OPS and
                  st["server"]["proto_errors"] == 0)})
    finally:
        srv.stop()


def main():
    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: net_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    import tempfile
    run_ps_leg()
    with tempfile.TemporaryDirectory() as td:
        run_serving_leg(td)

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "net_bench", "conns": CONNS,
                       "procs": PROCS, "ops_per_conn": OPS,
                       "batch": BATCH, "dim": DIM,
                       "serving_conns": SERVING_CONNS,
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
