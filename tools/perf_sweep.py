"""One-shot perf sweep for the BASELINE conv configs + GPT headline.

Run on the real chip when available:
    python tools/perf_sweep.py [resnet|yolo|gpt] ...

Prints one line per configuration; used to pick the bench.py defaults
(BASELINE.md configs 1/3/4). Timing protocol matches bench.py: every
timed region ends in float(loss) — the only real sync through the axon
tunnel.
"""
from __future__ import annotations

import functools
import sys
import time

import numpy as np

# single source of truth for chip peaks + the float(loss) sync protocol
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))
from bench import peak_flops  # noqa: E402


def peak():
    import jax
    return peak_flops(jax.devices()[0].device_kind)


def timed(step, state, args, steps, warmup):
    for _ in range(warmup):
        state, loss = step(state, *args)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, *args)
    float(loss)
    return time.perf_counter() - t0


def resnet(batch=64, level="O1", steps=10, warmup=2, channels_last=False):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)

    model = resnet50()
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    params = trainable_state(model)
    buffers = buffer_state(model)
    opt_state = opt.init_state(params)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, 3, 224, 224), jnp.float32)
    y = jnp.asarray(rs.randint(0, 1000, (batch,)), jnp.int32)
    ce = pt.nn.CrossEntropyLoss()

    def loss_fn(params, buffers, x, y):
        with pt.amp.auto_cast(level=level):
            out, new_buf = functional_call(model, params, x,
                                           buffers=buffers)
        return ce(out, y), new_buf

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x, y):
        params, buffers, opt_state = state
        (loss, new_buf), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, buffers, x, y)
        new_p, new_s = opt.apply(params, g, opt_state)
        return (new_p, new_buf, new_s), loss

    dt = timed(step, (params, buffers, opt_state), (x, y), steps, warmup)
    imgs = batch * steps / dt
    mfu = imgs * 3 * 4.1e9 / peak()
    print(f"resnet50 batch={batch} {level}: {imgs:.0f} imgs/s "
          f"MFU={mfu * 100:.1f}%", flush=True)
    return imgs


def yolo(batch=8, size=320, level="O1", steps=8, warmup=2):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.vision.models import yolov3_darknet53, yolo_loss
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     trainable_state)

    model = yolov3_darknet53(num_classes=80)
    model.train()
    opt = pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9)
    params = trainable_state(model)
    buffers = buffer_state(model)
    opt_state = opt.init_state(params)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, 3, size, size), jnp.float32)
    gt_box = jnp.asarray(rs.uniform(0.2, 0.8, (batch, 16, 4)), jnp.float32)
    gt_cls = jnp.asarray(rs.randint(0, 80, (batch, 16)), jnp.int32)

    def loss_fn(params, buffers, x):
        with pt.amp.auto_cast(level=level):
            outs, new_buf = functional_call(model, params, x,
                                            buffers=buffers)
        return yolo_loss(outs, gt_box, gt_cls, num_classes=80), new_buf

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x):
        params, buffers, opt_state = state
        (loss, new_buf), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, buffers, x)
        new_p, new_s = opt.apply(params, g, opt_state)
        return (new_p, new_buf, new_s), loss

    dt = timed(step, (params, buffers, opt_state), (x,), steps, warmup)
    imgs = batch * steps / dt
    mfu = imgs * 3 * 39e9 / peak()
    print(f"yolov3 batch={batch}@{size} {level}: {imgs:.0f} imgs/s "
          f"MFU={mfu * 100:.1f}%", flush=True)
    return imgs


def gpt(batch=8, seq=1024, chunks=8, steps=12, warmup=2):
    """Per-chip tokens/s; `batch` is the GLOBAL batch, sharded
    over the dp mesh (throughput divides by device count)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import (GPTForPretraining, build_train_step,
                                   gpt_345m)

    cfg = gpt_345m(max_position_embeddings=max(seq, 1024))
    mesh = build_mesh(dp=len(jax.devices()))
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                             grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
    step, state = build_train_step(model, opt, mesh, num_microbatches=1,
                                   remat=True, remat_policy="dots",
                                   loss_chunks=chunks)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    dt = timed(lambda s, a: step(s, a), state, ((ids, labels),), steps,
               warmup)
    toks = batch * seq * steps / dt / len(jax.devices())  # per chip
    d, L, V, f = cfg.hidden_size, cfg.num_layers, cfg.vocab_size, \
        cfg.ffn_hidden
    fl = 6.0 * (L * (4 * d * d + 2 * d * f) + V * d) + 12.0 * L * d * seq
    mfu = fl * toks / peak()
    print(f"gpt345m batch={batch} seq={seq} chunks={chunks}: "
          f"{toks:.0f} tok/s MFU={mfu * 100:.1f}%", flush=True)
    return toks


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    if which == "resnet":
        for b in (64, 128, 256):
            resnet(batch=b)
        resnet(batch=256, level="O2")
    elif which == "yolo":
        for b in (8, 16, 32):
            yolo(batch=b)
    elif which == "gpt":
        for b in (8, 16):
            gpt(batch=b)
        gpt(batch=8, seq=2048)
    else:
        raise SystemExit(f"unknown sweep {which}")


if __name__ == "__main__":
    main()
