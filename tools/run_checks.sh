#!/usr/bin/env bash
# Single correctness-tooling entrypoint (the CI gate every perf PR runs
# against — reference: the upstream tools/ check scripts chained in CI).
#
#   build            the three shipping .so artifacts (-Werror on)
#   sancheck         all five C selftests + the pure-C demo under
#                    ASan+UBSan, fail-fast; TSan leg when libtsan exists
#   ptpu_check       the 7 static checkers (ABI / wire / stats / locks /
#                    net / nullcheck / trace) — 0 findings required
#   selftest         the plain (uninstrumented) native selftests
#
# Usage: tools/run_checks.sh [-j N]
set -euo pipefail

JOBS=4
while getopts "j:" opt; do
  case "$opt" in
    j) JOBS="$OPTARG" ;;
    *) echo "usage: $0 [-j N]" >&2; exit 2 ;;
  esac
done

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

step() { printf '\n== %s ==\n' "$*"; }

step "build (shipping .so artifacts, -Werror)"
make -C csrc -j"$JOBS" all

step "sancheck: ASan+UBSan (selftests + demo, fail-fast)"
make -C csrc -j"$JOBS" sancheck SAN=asan,ubsan

if echo 'int main(){return 0;}' | "${CXX:-g++}" -fsanitize=thread -x c++ - \
    -o /tmp/ptpu_tsan_probe.$$ 2>/dev/null && \
    /tmp/ptpu_tsan_probe.$$ 2>/dev/null; then
  rm -f /tmp/ptpu_tsan_probe.$$
  step "sancheck: TSan (empty suppression list)"
  make -C csrc -j"$JOBS" sancheck SAN=tsan
else
  rm -f /tmp/ptpu_tsan_probe.$$
  step "sancheck: TSan SKIPPED (no usable libtsan on this machine)"
fi

step "ptpu_check: static analysis (abi / wire / stats / locks / net / nullcheck / trace)"
python3 tools/ptpu_check.py

step "native selftests (uninstrumented)"
make -C csrc -j"$JOBS" selftest

printf '\nrun_checks: ALL GREEN\n'
