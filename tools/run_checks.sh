#!/usr/bin/env bash
# Single correctness-tooling entrypoint (the CI gate every perf PR runs
# against — reference: the upstream tools/ check scripts chained in CI).
#
#   build            the three shipping .so artifacts (-Werror on)
#   sancheck         all seven C selftests + the pure-C demo under
#                    ASan+UBSan, fail-fast; TSan leg when libtsan
#                    exists — selftests run LOCKDEP-enabled (the
#                    ranked-mutex validator, csrc/ptpu_sync.h) in
#                    every leg
#   ptpu_check       the 11 static checkers (ABI / wire / stats /
#                    locks / net / nullcheck / trace / sync / fuzz /
#                    sched / invar) — 0 findings required
#   invar twin       the conservation-law manifest's Python twin
#                    (profiler/stats.py) evaluated against both live
#                    .so engines: byte-identical manifest, identical
#                    reports on the same snapshot
#   selftest         the plain (lockdep-enabled, uninstrumented)
#                    native selftests incl. the seeded ABBA fixture
#   schedck          the concurrency model checker (csrc/ptpu_schedck)
#                    deep sweep: every scenario DFS-exhausted on its
#                    small config and PCT-swept SCHEDCK_SCHEDULES
#                    times (default 10000) on its large one, then both
#                    seeded historical-bug fixtures (r10 eventfd lost
#                    wakeup, r9 close-before-join) rediscovered and
#                    replayed deterministically
#   covcheck         gcov line-coverage floors on the hot contract
#                    files (ptpu_wire.h + users, ptpu_net.cc,
#                    ptpu_sync.h), merged across the selftests and the
#                    fuzz corpus replay; report artifact at
#                    csrc/covcheck_report.json
#   fuzz smoke       build every csrc/fuzz harness (ASan+UBSan +
#                    trace-pc coverage), replay the checked-in corpus
#                    (seeds + frozen crash regressions), then a
#                    bounded coverage-guided run per target
#                    (FUZZ_SMOKE_SECS, default 5s) — any finding
#                    fails the gate
#
# Usage: tools/run_checks.sh [-j N]
set -euo pipefail

JOBS=4
while getopts "j:" opt; do
  case "$opt" in
    j) JOBS="$OPTARG" ;;
    *) echo "usage: $0 [-j N]" >&2; exit 2 ;;
  esac
done

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

step() { printf '\n== %s ==\n' "$*"; }

step "build (shipping .so artifacts, -Werror)"
make -C csrc -j"$JOBS" all

step "sancheck: ASan+UBSan (selftests + demo, fail-fast)"
make -C csrc -j"$JOBS" sancheck SAN=asan,ubsan

if echo 'int main(){return 0;}' | "${CXX:-g++}" -fsanitize=thread -x c++ - \
    -o /tmp/ptpu_tsan_probe.$$ 2>/dev/null && \
    /tmp/ptpu_tsan_probe.$$ 2>/dev/null; then
  rm -f /tmp/ptpu_tsan_probe.$$
  step "sancheck: TSan (empty suppression list)"
  make -C csrc -j"$JOBS" sancheck SAN=tsan
else
  rm -f /tmp/ptpu_tsan_probe.$$
  step "sancheck: TSan SKIPPED (no usable libtsan on this machine)"
fi

step "ptpu_check: static analysis (11 checkers, 0 findings required)"
python3 tools/ptpu_check.py

step "invar twin: C engine vs profiler/stats.py manifest + report parity"
python3 - <<'PY'
import ctypes, json, os, sys
sys.path.insert(0, os.getcwd())
from paddle_tpu.profiler.stats import INVAR_MANIFEST, invar_check
snap = json.dumps({
    "server": {"conns_accepted": 3, "conns_closed": 3, "conns_active": 0,
               "requests": 7, "replies": 6, "req_errors": 1,
               "op_errors": 0, "err_frames": 1},
    "batcher": {"batches": 2}})
for lib in ("_native_predictor.so", "_native_ps.so"):
    so = ctypes.CDLL(os.path.join("paddle_tpu", lib))
    so.ptpu_invar_manifest.restype = ctypes.c_char_p
    so.ptpu_invar_check_json.restype = ctypes.c_char_p
    so.ptpu_invar_check_json.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    assert so.ptpu_invar_manifest().decode() == INVAR_MANIFEST, lib
    crep = json.loads(so.ptpu_invar_check_json(snap.encode(),
                                               b"serving").decode())
    assert crep == invar_check(json.loads(snap), "serving"), (lib, crep)
print("invar twin: manifest + report parity OK (both .so engines)")
PY

step "native selftests (uninstrumented, lockdep-enabled)"
make -C csrc -j"$JOBS" selftest

SCHEDCK_SCHEDULES="${SCHEDCK_SCHEDULES:-10000}"
step "schedck: model-checker sweep (${SCHEDCK_SCHEDULES} PCT schedules) + bug-fixture rediscovery"
make -C csrc -j"$JOBS" schedck SCHEDCK_SCHEDULES="$SCHEDCK_SCHEDULES"

step "covcheck: gcov line-coverage floors (selftests + fuzz corpus replay)"
make -C csrc -j"$JOBS" covcheck

step "fuzz smoke: build harnesses (ASan+UBSan + coverage)"
make -C csrc -j"$JOBS" fuzz

FUZZ_SMOKE_SECS="${FUZZ_SMOKE_SECS:-5}"
step "fuzz smoke: corpus replay + ${FUZZ_SMOKE_SECS}s run per target"
for t in wire_ps wire_serving http onnx json frames tune capture \
         spill; do
  echo "-- fuzz_${t}: corpus replay"
  (cd csrc/fuzz && "./fuzz_${t}.fuzz" "corpus/${t}")
  echo "-- fuzz_${t}: ${FUZZ_SMOKE_SECS}s coverage-guided run"
  (cd csrc/fuzz && "./fuzz_${t}.fuzz" "-fuzz=${FUZZ_SMOKE_SECS}" \
      -seed=1 "-artifact=crash-${t}-" "corpus/${t}")
done

# Opt-in chaos soak (production drills, ISSUE 18): DRILL_SOAK_SECS=N
# runs the two-phase selfsoak — lossless chaos (read/write delays,
# short writes), then lossy (conn kills, handshake drops) — each
# ending in a drained-connections check, the declarative ptpu_invar
# conservation gate at quiesce (r20: PTPU_INVAR_FATAL=1 hard-gates
# every server Stop(), and invar_assert replaces the hand-written
# ledger arithmetic), and client-vs-server cross-checks. Off by
# default: it needs the Python serving stack, not just csrc.
if [[ -n "${DRILL_SOAK_SECS:-}" ]]; then
  step "drill soak: ${DRILL_SOAK_SECS}s two-phase chaos reconciliation"
  JAX_PLATFORMS=cpu python3 tools/drill_replay.py selfsoak \
      --secs "$DRILL_SOAK_SECS"
fi

printf '\nrun_checks: ALL GREEN\n'
