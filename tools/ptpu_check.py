#!/usr/bin/env python3
"""ptpu_check — repo-specific static analysis for the native runtime's
cross-language seams (reference: the `tools/` checkers the upstream
project gates CI on — `check_api_compatible.py`, op-registry
consistency scripts, `enforce.h` discipline).

The repro carries four hand-maintained contracts between C, Python and
Go that the compiler cannot see across:

  abi        exported `ptpu_*` symbols in csrc  ==  the ABI_SYMBOLS
             manifest in paddle_tpu/core/native.py  ==  the
             declarations in csrc/ptpu_inference_api.h  ==  the
             `C.ptpu_*` calls in goapi/predictor.go
  wire       frame tags / protocol version / fixed field offsets in
             csrc (ptpu_ps_server.cc, ptpu_serving.cc)  ==  their
             Python twins (distributed/ps/wire.py, inference/serving.py)
  stats      counter names the C JSON renderers emit  ==  the names the
             Python twin registry (profiler/stats.py call sites in
             distributed/ps/table.py) maintains; histogram layout
             (kHistBuckets) identical on both sides
  locks      condvar discipline in csrc: every wait has a predicate (or
             sits in a re-check loop), no bare pthread_* / __sync_* /
             __atomic_* primitives (std:: only — TSan-visible and
             portable)
  net        epoll-core discipline: every fd registered with epoll is
             provably nonblocking, every epoll_wait loop handles
             EPOLLERR/EPOLLHUP, and the two wire servers never regrow
             a direct accept() loop or per-connection threads
             (csrc/ptpu_net.cc is the one place that owns sockets)
  nullcheck  every extern-C ABI entry taking an opaque handle guards
             NULL before dereferencing (ctypes/cgo can always hand one
             back after a failed create or a teardown race)
  sync       every mutex/shared-mutex/condvar in csrc lives behind
             the ptpu_sync.h wrappers (ptpu::Mutex / SharedMutex /
             CondVar) and every lock class is declared with a literal
             rank — raw primitives are invisible to ptpu_lockdep
             (ISSUE 11)
  fuzz       every untrusted-byte surface parsed in C maps to a fuzz
             harness + checked-in corpus entry: wire tags (PS +
             serving planes), HTTP telemetry routes, ONNX node ops
             (csrc/fuzz, ISSUE 11)
  sched      model-checker coverage (ISSUE 15): every production
             PTPU_LOCK_CLASS name maps to a scenario in
             csrc/ptpu_schedck_coverage.txt, every mapped scenario
             exists in the selftest registry, scenario TUs never
             spawn raw std::thread (invisible to the exploration),
             and PTPU_SCHED_POINT only appears with its self-gating
             header included
  invar      counter-conservation manifest (ISSUE 20): every counter
             csrc/ptpu_invar.h binds to a conservation law has a bump
             site in its declared TU(s), `pair`ed error-path counters
             move together per function body, no production TU bumps
             a bound counter the manifest doesn't account for, law
             terms resolve to bound paths whose leaves a C renderer
             actually emits, and the Python twin manifest
             (profiler/stats.py INVAR_MANIFEST) stays token-identical
             with the C one — the static half of the ptpu_invar gate
  trace      request-tracing seam (ISSUE 10): the traced v2 frame
             extension (version byte, 8-byte trace-id insert, read and
             echo offsets) in csrc (ptpu_ps_server.cc, ptpu_serving.cc)
             == the Python twins (wire.py, serving.py), and the C span
             recorder's kind-name table (csrc/ptpu_trace.cc)  ==  the
             timeline name map (profiler/timeline.py SPAN_KIND_NAMES)

No clang, no compilation: regex/AST over the sources, so the suite runs
in milliseconds and anywhere. Exit 0 == no findings. Each checker is
unit-tested against fixture trees with deliberately seeded violations
in tests/test_static_checks.py.

Usage:
  python tools/ptpu_check.py                 # all checkers, repo root
  python tools/ptpu_check.py --check wire    # one checker
  python tools/ptpu_check.py --root DIR      # another tree (fixtures)
  python tools/ptpu_check.py --json          # machine-readable output
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import re
import struct
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Finding:
    def __init__(self, checker: str, path: str, line: int, message: str):
        self.checker = checker
        self.path = path
        self.line = line
        self.message = message

    def to_dict(self) -> dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "message": self.message}

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.checker}] {self.message}"


def _read(root: str, rel: str) -> Optional[str]:
    p = os.path.join(root, rel)
    if not os.path.exists(p):
        return None
    with open(p, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def _require(root: str, rel: str, checker: str,
             findings: List[Finding]) -> Optional[str]:
    src = _read(root, rel)
    if src is None:
        findings.append(Finding(checker, rel, 0,
                                f"file missing (contract file for the "
                                f"'{checker}' checker)"))
    return src


def strip_c_comments(src: str, keep_strings: bool = False) -> str:
    """Blank out // and /* */ comments — and, unless `keep_strings`,
    string literals too — preserving line structure so reported line
    numbers stay valid."""
    out = []
    i, n = 0, len(src)
    mode = None  # None | 'line' | 'block' | 'str' | 'chr'
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode is None:
            if c == "/" and nxt == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                mode = "block"
                out.append("  ")
                i += 2
                continue
            if c == '"':
                mode = "str"
                out.append('"')
                i += 1
                continue
            if c == "'":
                mode = "chr"
                out.append("'")
                i += 1
                continue
            out.append(c)
        elif mode == "line":
            if c == "\n":
                mode = None
                out.append("\n")
            else:
                out.append(" ")
        elif mode == "block":
            if c == "*" and nxt == "/":
                mode = None
                out.append("  ")
                i += 2
                continue
            out.append("\n" if c == "\n" else " ")
        elif mode in ("str", "chr"):
            quote = '"' if mode == "str" else "'"
            if c == "\\":
                out.append(src[i:i + 2] if keep_strings else "  ")
                i += 2
                continue
            if c == quote:
                mode = None
                out.append(quote)
            else:
                out.append(c if keep_strings else " ")
        i += 1
    return "".join(out)


def _lineno(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


# ---------------------------------------------------------------------------
# checker: abi
# ---------------------------------------------------------------------------

# csrc definition files per shared object — the unit the manifest keys on
SO_SOURCES = {
    "_native.so": ["csrc/ptpu_runtime.cc"],
    "_native_ps.so": ["csrc/ptpu_ps_table.cc", "csrc/ptpu_ps_server.cc",
                      "csrc/ptpu_net.cc", "csrc/ptpu_trace.cc",
                      "csrc/ptpu_invar.cc"],
    "_native_predictor.so": ["csrc/ptpu_predictor.cc",
                             "csrc/ptpu_serving.cc", "csrc/ptpu_tune.cc",
                             "csrc/ptpu_net.cc", "csrc/ptpu_trace.cc",
                             "csrc/ptpu_invar.cc"],
}

_EXPORT_RES = [
    re.compile(r"\bPTPU_EXPORT\b[^(;{]*?\b(ptpu_\w+)\s*\("),
    re.compile(r"\bPTPU_PS_EXPORT\b[^(;{]*?\b(ptpu_\w+)\s*\("),
    re.compile(r'__attribute__\(\(visibility\("default"\)\)\)\s*'
               r"[^(;{]*?\b(ptpu_\w+)\s*\(", re.S),
]


def c_exported_symbols(src: str) -> Dict[str, int]:
    """name -> line of every exported ptpu_* definition in a csrc TU."""
    clean = strip_c_comments(src)
    # comment-stripping blanks the string inside visibility("default");
    # recover it so the attribute regex still matches
    clean = clean.replace('visibility("       ")', 'visibility("default")')
    out: Dict[str, int] = {}
    for rx in _EXPORT_RES:
        for m in rx.finditer(clean):
            out.setdefault(m.group(1), _lineno(clean, m.start(1)))
    return out


def manifest_symbols(native_py: str, rel: str,
                     findings: List[Finding]) -> Dict[str, Set[str]]:
    """ABI_SYMBOLS from core/native.py, parsed statically via ast."""
    try:
        tree = ast.parse(native_py)
    except SyntaxError as e:
        findings.append(Finding("abi", rel, e.lineno or 0,
                                f"cannot parse: {e.msg}"))
        return {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "ABI_SYMBOLS":
                    try:
                        val = ast.literal_eval(node.value)
                        return {k: set(v) for k, v in val.items()}
                    except (ValueError, TypeError):
                        findings.append(Finding(
                            "abi", rel, node.lineno,
                            "ABI_SYMBOLS is not a literal dict"))
                        return {}
    findings.append(Finding("abi", rel, 0, "ABI_SYMBOLS manifest not found"))
    return {}


def header_decls(header: str) -> Dict[str, int]:
    clean = strip_c_comments(header)
    out: Dict[str, int] = {}
    for m in re.finditer(r"\b(ptpu_\w+)\s*\(", clean):
        out.setdefault(m.group(1), _lineno(clean, m.start(1)))
    return out


def check_abi(root: str) -> List[Finding]:
    f: List[Finding] = []
    native_rel = "paddle_tpu/core/native.py"
    native_py = _require(root, native_rel, "abi", f)
    manifest = manifest_symbols(native_py, native_rel, f) if native_py else {}

    exported: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for so, rels in SO_SOURCES.items():
        exported[so] = {}
        for rel in rels:
            src = _require(root, rel, "abi", f)
            if src is None:
                continue
            for name, line in c_exported_symbols(src).items():
                exported[so][name] = (rel, line)

    # 1) three-way: exported-in-C <-> listed-in-manifest, per .so
    for so in SO_SOURCES:
        c_syms = set(exported.get(so, {}))
        m_syms = manifest.get(so, set())
        if not manifest:
            break
        for name in sorted(c_syms - m_syms):
            rel, line = exported[so][name]
            f.append(Finding("abi", rel, line,
                             f"{name} is exported by {so} sources but "
                             f"missing from ABI_SYMBOLS['{so}'] in "
                             f"core/native.py"))
        for name in sorted(m_syms - c_syms):
            f.append(Finding("abi", native_rel, 0,
                             f"ABI_SYMBOLS['{so}'] lists {name} but no "
                             f"csrc TU of {so} exports it"))

    # 2) public C header <-> predictor TU exports + manifest
    hdr_rel = "csrc/ptpu_inference_api.h"
    hdr = _require(root, hdr_rel, "abi", f)
    if hdr is not None:
        decls = header_decls(hdr)
        pred_syms = set(exported.get("_native_predictor.so", {}))
        pred_manifest = manifest.get("_native_predictor.so", set())
        for name, line in sorted(decls.items()):
            if pred_syms and name not in pred_syms:
                f.append(Finding("abi", hdr_rel, line,
                                 f"{name} is declared in the public C "
                                 f"header but not exported by the "
                                 f"predictor/serving TUs"))
            if manifest and name not in pred_manifest:
                f.append(Finding("abi", hdr_rel, line,
                                 f"{name} is declared in the public C "
                                 f"header but missing from ABI_SYMBOLS"
                                 f"['_native_predictor.so']"))

    # 3) Go binding <-> public C header
    go_rel = "goapi/predictor.go"
    go = _require(root, go_rel, "abi", f)
    if go is not None and hdr is not None:
        decls = header_decls(hdr)
        for m in re.finditer(r"\bC\.(ptpu_\w+)\b", go):
            name = m.group(1)
            if name not in decls:
                f.append(Finding("abi", go_rel, _lineno(go, m.start()),
                                 f"goapi calls C.{name} but "
                                 f"ptpu_inference_api.h does not declare "
                                 f"it"))
    return f


# ---------------------------------------------------------------------------
# checker: wire
# ---------------------------------------------------------------------------

def py_int_constants(src: str, rel: str, checker: str,
                     findings: List[Finding]) -> Dict[str, int]:
    """Top-level NAME = <int literal> assignments (0x.. included)."""
    out: Dict[str, int] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        findings.append(Finding(checker, rel, e.lineno or 0,
                                f"cannot parse: {e.msg}"))
        return out
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            try:
                v = ast.literal_eval(node.value)
            except (ValueError, TypeError):
                continue
            if isinstance(v, int) and not isinstance(v, bool):
                out[node.targets[0].id] = v
    return out


def c_u8_constants(src: str) -> Dict[str, Tuple[int, int]]:
    """constexpr uint8_t kName = 0x..;  ->  name: (value, line)."""
    clean = strip_c_comments(src)
    out: Dict[str, Tuple[int, int]] = {}
    for m in re.finditer(
            r"constexpr\s+uint8_t\s+(k\w+)\s*=\s*(0x[0-9a-fA-F]+|\d+)\s*;",
            clean):
        out[m.group(1)] = (int(m.group(2), 0), _lineno(clean, m.start()))
    return out


# canonical tag names: C constant -> Python constant, per protocol
PS_TAGS = {"kTagPullReq": "TAG_PULL_REQ", "kTagPullRep": "TAG_PULL_REP",
           "kTagPushReq": "TAG_PUSH_REQ", "kTagOk": "TAG_OK",
           "kTagErr": "TAG_ERR"}
SV_TAGS = {"kTagInferReq": "TAG_INFER_REQ", "kTagInferRep": "TAG_INFER_REP",
           "kTagInferErr": "TAG_INFER_ERR", "kTagMetaReq": "TAG_META_REQ",
           "kTagMetaRep": "TAG_META_REP",
           # KV-decode ops (r9): sessions/steps over 0x65..0x69
           "kTagDecodeOpen": "TAG_DECODE_OPEN",
           "kTagDecodeSess": "TAG_DECODE_SESS",
           "kTagDecodeStep": "TAG_DECODE_STEP",
           "kTagDecodeRep": "TAG_DECODE_REP",
           "kTagDecodeClose": "TAG_DECODE_CLOSE",
           # paged-engine ops (r12): prompt prefill + COW fork
           "kTagDecodeOpen2": "TAG_DECODE_OPEN2",
           "kTagDecodeOpenRep": "TAG_DECODE_OPEN_REP",
           "kTagDecodeFork": "TAG_DECODE_FORK",
           # speculative decoding (r13): draft/verify rounds over
           # 0x6d..0x6f
           "kTagDecodeSpecOpen": "TAG_DECODE_SPEC_OPEN",
           "kTagDecodeSpecStep": "TAG_DECODE_SPEC_STEP",
           "kTagDecodeSpecRep": "TAG_DECODE_SPEC_REP"}


def _py_struct_size(src: str, var: str) -> Optional[int]:
    """Size of `var = struct.Struct("<fmt>")` defined in the module."""
    m = re.search(rf'^{re.escape(var)}\s*=\s*struct\.Struct\("([^"]+)"\)',
                  src, re.M)
    return struct.calcsize(m.group(1)) if m else None


def _tag_parity(c_rel: str, c_consts, py_rel: str, py_consts, tag_map,
                c_ver_name: str, findings: List[Finding]) -> None:
    for c_name, py_name in tag_map.items():
        if c_name not in c_consts:
            findings.append(Finding("wire", c_rel, 0,
                                    f"tag constant {c_name} not found"))
            continue
        if py_name not in py_consts:
            findings.append(Finding("wire", py_rel, 0,
                                    f"tag constant {py_name} not found"))
            continue
        cv, line = c_consts[c_name]
        pv = py_consts[py_name]
        if cv != pv:
            findings.append(Finding(
                "wire", c_rel, line,
                f"{c_name} = {cv:#x} in C but {py_name} = {pv:#x} in "
                f"{py_rel} — wire tag drift"))
    if c_ver_name in c_consts and "WIRE_VERSION" in py_consts:
        cv, line = c_consts[c_ver_name]
        if cv != py_consts["WIRE_VERSION"]:
            findings.append(Finding(
                "wire", c_rel, line,
                f"{c_ver_name} = {cv} in C but WIRE_VERSION = "
                f"{py_consts['WIRE_VERSION']} in {py_rel}"))


def check_wire(root: str) -> List[Finding]:
    f: List[Finding] = []
    ps_rel, sv_rel = "csrc/ptpu_ps_server.cc", "csrc/ptpu_serving.cc"
    pyw_rel, pys_rel = ("paddle_tpu/distributed/ps/wire.py",
                       "paddle_tpu/inference/serving.py")
    ps_c = _require(root, ps_rel, "wire", f)
    sv_c = _require(root, sv_rel, "wire", f)
    pyw = _require(root, pyw_rel, "wire", f)
    pys = _require(root, pys_rel, "wire", f)

    # ---- PS data-plane tags + version
    if ps_c is not None and pyw is not None:
        c_consts = c_u8_constants(ps_c)
        py_consts = py_int_constants(pyw, pyw_rel, "wire", f)
        _tag_parity(ps_rel, c_consts, pyw_rel, py_consts, PS_TAGS,
                    "kWireVersion", f)

        # layout probe: PULL_REP header is [ver][tag][u32 n][u32 dim] =
        # 10 payload bytes (+`ho` == the 8-byte trace-id echo for v2
        # frames). Python: _PULL_REP_HDR = 2 + Struct("<II"); C: the
        # reply writes its frame length as 10 + ho + body and the
        # gather body at rep.data() + 14 + ho (4B length prefix + 10).
        u32x2 = _py_struct_size(pyw, "_U32x2")
        if u32x2 is None:
            f.append(Finding("wire", pyw_rel, 0,
                             "_U32x2 struct definition not found"))
        else:
            py_hdr = 2 + u32x2
            clean = strip_c_comments(ps_c)
            m = re.search(r"PutU32\(rep\.data\(\),\s*uint32_t\((\d+)\s*\+"
                          r"\s*ho\s*\+\s*body\)\)", clean)
            if not m:
                f.append(Finding("wire", ps_rel, 0,
                                 "PULL_REP frame-length expression not "
                                 "found (layout probe)"))
            elif int(m.group(1)) != py_hdr:
                f.append(Finding(
                    "wire", ps_rel, _lineno(clean, m.start()),
                    f"PULL_REP header is {m.group(1)} bytes in C but "
                    f"_PULL_REP_HDR = {py_hdr} in wire.py"))
            m = re.search(r"rep\.data\(\)\s*\+\s*(\d+)\s*\+\s*ho;",
                          clean)
            if m and int(m.group(1)) != py_hdr + 4:
                f.append(Finding(
                    "wire", ps_rel, _lineno(clean, m.start()),
                    f"PULL_REP body lands at +{m.group(1)}+ho in the C "
                    f"reply buffer; expected 4-byte length prefix + "
                    f"{py_hdr}"))
            # PUSH_REQ fixed block after the table name:
            # [u8 flags][u32 n][u32 dim] = 1 + 8 = 9 bytes
            want = 1 + u32x2
            if not re.search(rf"n\s*<\s*off\s*\+\s*{want}\b", clean):
                f.append(Finding(
                    "wire", ps_rel, 0,
                    f"PUSH_REQ fixed-header size check (off + {want} "
                    f"for flags+n+dim, per wire.py) not found in the C "
                    f"parser — layout drift or probe went stale"))

    # ---- serving tags + version
    if sv_c is not None and pys is not None:
        c_consts = c_u8_constants(sv_c)
        py_consts = py_int_constants(pys, pys_rel, "wire", f)
        _tag_parity(sv_rel, c_consts, pys_rel, py_consts, SV_TAGS,
                    "kSvWireVersion", f)

        # layout probe: INFER frames lead with [ver][tag](+trace id)
        # [u64 req_id][u16 count] — the C parser enforces
        # n >= 2 + ext + 8 + 2 (ext == 0 for v1, 8 for traced v2) and
        # Python unpacks the count at offset 10 + base.
        clean = strip_c_comments(sv_c)
        if not re.search(r"n\s*<\s*2\s*\+\s*ext\s*\+\s*8\s*\+\s*2",
                         clean):
            f.append(Finding("wire", sv_rel, 0,
                             "INFER_REQ minimum-size check (2 + ext + "
                             "8 + 2) not found (layout probe)"))
        if not re.search(r'unpack_from\(\s*f,\s*10\s*\)|"<H",\s*f,\s*10',
                         pys):
            f.append(Finding("wire", pys_rel, 0,
                             "INFER reply count at payload offset 10 "
                             "not found (layout probe)"))

        # Zero-copy wire path probes (ISSUE 17). The INFER parser
        # pins the conn's reassembly buffer and borrows views into
        # it; the INFER_REP writer owns only the head — [4B len][ver]
        # [tag](+tid)[u64 id][u16 n_outputs @ho+8] + output 0's
        # metadata — and ships payload rows as SendScatter iovecs
        # pointing into the pinned predictor outputs. A rewrite back
        # to copied frames (or a moved count offset) drops a probe.
        if not re.search(r"PinInbuf\(req,\s*n\)", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "INFER parse does not pin the reassembly "
                             "buffer (PinInbuf) — in-place ingestion "
                             "probe"))
        m = re.search(r"memcpy\(head\.data\(\)\s*\+\s*ho\s*\+\s*(\d+),"
                      r"\s*&no16,\s*2\)", clean)
        if m is None:
            f.append(Finding("wire", sv_rel, 0,
                             "INFER_REP n_outputs write into the "
                             "scatter head not found (layout probe)"))
        elif int(m.group(1)) != 8:
            f.append(Finding(
                "wire", sv_rel, _lineno(clean, m.start()),
                f"INFER_REP n_outputs lands at head ho+{m.group(1)}; "
                f"expected ho + 8 (== payload 10, where the Python "
                f"client unpacks it)"))
        if not re.search(r"SendScatter\(std::move\(head\)", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "INFER_REP scatter send not found — "
                             "replies must ship predictor output rows "
                             "as pinned iovec segments (SendScatter), "
                             "not copied frames (zero-copy probe)"))

        # DECODE layout probes (r9, traced offsets r10). STEP payload
        # is [ver][tag](+trace id)[u64 req_id][u64 session][i64 token]
        # = 26 + ext bytes — the C parser must pin exactly that. The
        # REP payload carries [u32 n_logits] at offset 18 + base and
        # the f32 body at 22 + base; the C writer addresses them at
        # ho + 16 / ho + 20 in the length-prefixed reply buffer, where
        # ho == RepHdr's return (6 untraced == 4B length + [ver][tag]).
        if not re.search(r"n\s*!=\s*2\s*\+\s*ext\s*\+\s*8\s*\+\s*8"
                         r"\s*\+\s*8", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_STEP exact-size check (2 + ext + "
                             "8 + 8 + 8) not found (layout probe)"))
        # two writers share the pattern since r12: DECODE_REP puts
        # n_logits at ho+16, DECODE_OPEN_REP at ho+20 (after adopted)
        logit_offs = {int(mm) for mm in re.findall(
            r"PutU32\(f\.data\(\)\s*\+\s*ho\s*\+\s*(\d+),\s*"
            r"uint32_t\(dec_logit_elems\)\)", clean)}
        if not logit_offs:
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_REP n_logits write not found "
                             "(layout probe)"))
        elif 16 not in logit_offs:
            f.append(Finding(
                "wire", sv_rel, 0,
                f"DECODE_REP n_logits writes land at ho+"
                f"{sorted(logit_offs)}; expected one at ho + 16 "
                f"(== payload 18 for v1 frames)"))
        # the untraced reply header must stay [4B len][ver][tag] == 6
        if not re.search(r"RepHdr\([^)]*\)\s*\{.*?return\s+6;\s*\}",
                         clean, re.S):
            f.append(Finding("wire", sv_rel, 0,
                             "RepHdr untraced base (return 6) not "
                             "found (layout probe)"))
        if not re.search(r"unpack_from\(\s*f,\s*18\s*\+\s*base\s*\)",
                         pys.split("_decode_rep_logits", 1)[-1][:400]):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_REP n_logits at payload offset "
                             "18 + base not found (layout probe)"))
        if not re.search(r"np\.frombuffer\(\s*f,\s*np\.float32,\s*n,"
                         r"\s*22\s*\+\s*base\s*\)", pys):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_REP f32 body at payload offset "
                             "22 + base not found (layout probe)"))

        # Paged-engine layout probes (r12). OPEN2 payload is
        # [ver][tag](+tid)[u64 req_id][u32 n_tokens @10][u32 flags
        # @14][n x i64 @18]: the C parser must pin the exact frame
        # size and read tokens from offset 18 + ext. OPEN_REP carries
        # [u32 adopted][u32 n_logits][f32 body] at reply-buffer
        # offsets ho+16 / ho+20 / ho+24 (payload 18/22/26 + base),
        # which the Python client unpacks at exactly those offsets.
        if not re.search(r"2\s*\+\s*ext\s*\+\s*8\s*\+\s*4\s*\+\s*4"
                         r"\s*\+\s*8ull\s*\*\s*ntok", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_OPEN2 exact-size check (2 + ext "
                             "+ 8 + 4 + 4 + 8*n_tokens) not found "
                             "(layout probe)"))
        if not re.search(r"GetI64\(req\s*\+\s*18\s*\+\s*ext", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_OPEN2 token read at payload "
                             "offset 18 + ext not found (layout "
                             "probe)"))
        m = re.search(r"PutU32\(f\.data\(\)\s*\+\s*ho\s*\+\s*(\d+),\s*"
                      r"uint32_t\(adopted\)\)", clean)
        if m is None:
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_OPEN_REP adopted-tokens write "
                             "not found (layout probe)"))
        elif int(m.group(1)) != 16:
            f.append(Finding(
                "wire", sv_rel, _lineno(clean, m.start()),
                f"DECODE_OPEN_REP adopted lands at ho+{m.group(1)}; "
                f"expected ho + 16 (== payload 18 for v1 frames)"))
        if logit_offs and 20 not in logit_offs:
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_OPEN_REP n_logits write at "
                             "ho + 20 not found (layout probe)"))
        if not re.search(r"memcpy\(f\.data\(\)\s*\+\s*ho\s*\+\s*24,",
                         clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_OPEN_REP logits body at ho + 24 "
                             "not found (layout probe)"))
        if not re.search(r"_U32\.unpack_from\(f,\s*18\s*\+\s*base\)\s*"
                         r"\n?.*_U32\.unpack_from\(f,\s*22\s*\+\s*base"
                         r"\)", pys, re.S):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_OPEN_REP adopted/n_logits at "
                             "payload offsets 18/22 + base not found "
                             "(layout probe)"))
        if not re.search(r"np\.frombuffer\(\s*f,\s*np\.float32,\s*n,"
                         r"\s*26\s*\+\s*base\s*\)", pys):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_OPEN_REP f32 body at payload "
                             "offset 26 + base not found (layout "
                             "probe)"))

        # Speculative-decoding layout probes (r13). SPEC_OPEN payload
        # is [ver][tag](+tid)[u64 req_id][u32 n_tokens @10][u32 flags
        # @14][u64 seed @18][n x i64 @26]: the C parser must pin the
        # exact frame size and read tokens from 26 + ext. SPEC_REP
        # carries [u32 accepted][u32 n_tokens][n x i64] at
        # reply-buffer offsets ho+16 / ho+20 / ho+24 (payload
        # 18/22/26 + base), which _spec_rep_parse unpacks at exactly
        # those offsets.
        if not re.search(r"2\s*\+\s*ext\s*\+\s*8\s*\+\s*4\s*\+\s*4"
                         r"\s*\+\s*8\s*\+\s*8ull\s*\*\s*ntok", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_SPEC_OPEN exact-size check (2 + "
                             "ext + 8 + 4 + 4 + 8 + 8*n_tokens) not "
                             "found (layout probe)"))
        if not re.search(r"GetI64\(req\s*\+\s*26\s*\+\s*ext", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_SPEC_OPEN token read at payload "
                             "offset 26 + ext not found (layout "
                             "probe)"))
        if not re.search(r"PutU32\(f\.data\(\)\s*\+\s*ho\s*\+\s*16,"
                         r"\s*accepted\)", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_SPEC_REP accepted-count write at "
                             "ho + 16 not found (layout probe)"))
        if not re.search(r"PutI64\(f\.data\(\)\s*\+\s*ho\s*\+\s*24"
                         r"\s*\+\s*8\s*\*\s*size_t\(k\)", clean):
            f.append(Finding("wire", sv_rel, 0,
                             "DECODE_SPEC_REP token body at ho + 24 "
                             "not found (layout probe)"))
        spec_py = pys.split("def _spec_rep_parse", 1)[-1][:600]
        if not re.search(r"_U32\.unpack_from\(f,\s*18\s*\+\s*base\)"
                         r"[^#]*?_U32\.unpack_from\(f,\s*22\s*\+\s*"
                         r"base\)", spec_py, re.S):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_SPEC_REP accepted/n_tokens at "
                             "payload offsets 18/22 + base not found "
                             "(layout probe)"))
        if not re.search(r"_I64\.unpack_from\(f,\s*26\s*\+\s*base"
                         r"\s*\+\s*8\s*\*\s*k\)", spec_py):
            f.append(Finding("wire", pys_rel, 0,
                             "DECODE_SPEC_REP token body at payload "
                             "offset 26 + base not found (layout "
                             "probe)"))

    # ---- capture-file format (ISSUE 18). Drill capture files are a
    # two-sided wire: csrc/ptpu_capture.h writes+parses them in C,
    # tools/drill_replay.py re-parses them for replay (and writes them
    # back via `fetch`). The six layout constants must match, and the
    # Python struct formats must pack to exactly the C byte counts —
    # otherwise a capture taken on one side is rejected (or worse,
    # mis-framed) by the other.
    cap_rel, dr_rel = "csrc/ptpu_capture.h", "tools/drill_replay.py"
    cap = _require(root, cap_rel, "wire", f)
    dr = _require(root, dr_rel, "wire", f)
    if cap is not None and dr is not None:
        clean = strip_c_comments(cap)
        dr_consts = py_int_constants(dr, dr_rel, "wire", f)
        c_vals: Dict[str, int] = {}
        for cn, pn in (("kCaptureMagic", "CAPTURE_MAGIC"),
                       ("kCaptureVersion", "CAPTURE_VERSION"),
                       ("kCaptureHeaderBytes", "CAPTURE_HEADER_BYTES"),
                       ("kCaptureRecBytes", "CAPTURE_REC_BYTES"),
                       ("kCaptureMaxRecPayload",
                        "CAPTURE_MAX_REC_PAYLOAD"),
                       ("kCaptureMaxRecords", "CAPTURE_MAX_RECORDS")):
            m = re.search(rf"\b{cn}\s*=\s*(0x[0-9a-fA-F]+|\d+)", clean)
            if m is None:
                f.append(Finding("wire", cap_rel, 0,
                                 f"{cn} not found (capture layout "
                                 f"probe)"))
                continue
            c_vals[cn] = int(m.group(1), 0)
            if pn not in dr_consts:
                f.append(Finding("wire", dr_rel, 0,
                                 f"{pn} not found (capture layout "
                                 f"probe)"))
            elif dr_consts[pn] != c_vals[cn]:
                f.append(Finding(
                    "wire", cap_rel, _lineno(clean, m.start()),
                    f"{cn} = {c_vals[cn]} in C but {pn} = "
                    f"{dr_consts[pn]} in drill_replay.py — capture "
                    f"files written by one side would be rejected by "
                    f"the other"))
        for var, want_key in (("_HDR", "kCaptureHeaderBytes"),
                              ("_REC", "kCaptureRecBytes")):
            size = _py_struct_size(dr, var)
            if size is None:
                f.append(Finding("wire", dr_rel, 0,
                                 f"{var} struct definition not found "
                                 f"(capture layout probe)"))
            elif want_key in c_vals and size != c_vals[want_key]:
                f.append(Finding(
                    "wire", dr_rel, 0,
                    f"{var} packs to {size} bytes but {want_key} = "
                    f"{c_vals[want_key]} in ptpu_capture.h — capture "
                    f"record layout drift"))
    return f


# ---------------------------------------------------------------------------
# checker: stats
# ---------------------------------------------------------------------------

def c_json_names(src: str) -> Dict[str, int]:
    """Counter/histogram names a C renderer emits: AppendJsonU64/Hist
    first-arg literals plus the {"name", &stat} table initializers.
    Scans comment-stripped source (string literals kept — they ARE the
    names), so a commented-out renderer line is not collected as a live
    name."""
    src = strip_c_comments(src, keep_strings=True)
    out: Dict[str, int] = {}
    for m in re.finditer(r'AppendJson(?:U64|Hist)\(\s*&?\w+,\s*"(\w+)"',
                         src):
        out.setdefault(m.group(1), _lineno(src, m.start()))
    for m in re.finditer(r'\{"(\w+)",\s*&', src):
        out.setdefault(m.group(1), _lineno(src, m.start()))
    return out


def py_stat_names(src: str) -> Set[str]:
    return set(re.findall(r'\.(?:counter|histogram)\("(\w+)"\)', src))


# C-only wire counters: the Python control-plane has no handshake (the
# multiprocessing listener authenticates internally) and tracks
# connection lifetime differently; the epoll net-core counters
# (csrc/ptpu_net.h Stats) have no Python plane at all — the fallback
# serve loop is thread-per-connection multiprocessing.connection.
# Additions here must be justified.
PS_SERVER_C_ONLY = {"handshake_fails", "conns_accepted", "conns_active",
                    "conns_closed",
                    "conns_shed", "handshake_timeouts", "idle_closes",
                    "epoll_wakeups", "partial_write_flushes",
                    "http_reqs",
                    # event-thread CPU time per plane (ISSUE 17): a
                    # CLOCK_THREAD_CPUTIME_ID aggregate only the native
                    # server can measure
                    "cpu_us",
                    # injected-fault counters (PTPU_CHAOS drills):
                    # fault injection lives in the epoll net core
                    # only — the Python fallback loop has no chaos
                    # mode to count
                    "chaos_conn_kills", "chaos_read_delays",
                    "chaos_write_delays", "chaos_short_writes",
                    "chaos_handshake_drops"}


def check_stats(root: str) -> List[Finding]:
    f: List[Finding] = []
    tbl_rel, srv_rel = "csrc/ptpu_ps_table.cc", "csrc/ptpu_ps_server.cc"
    py_rel = "paddle_tpu/distributed/ps/table.py"
    stats_rel = "paddle_tpu/profiler/stats.py"
    hdr_rel = "csrc/ptpu_stats.h"
    tbl = _require(root, tbl_rel, "stats", f)
    srv = _require(root, srv_rel, "stats", f)
    py = _require(root, py_rel, "stats", f)
    pystats = _require(root, stats_rel, "stats", f)
    hdr = _require(root, hdr_rel, "stats", f)

    py_names = py_stat_names(py) if py is not None else set()

    # storage twin: the C table's counter set must be maintained
    # verbatim by the numpy fallback shard (snapshots merge by name)
    if tbl is not None and py is not None:
        for name, line in sorted(c_json_names(tbl).items()):
            if name not in py_names:
                f.append(Finding(
                    "stats", tbl_rel, line,
                    f"C table renderer emits '{name}' but "
                    f"distributed/ps/table.py never maintains a stat "
                    f"of that name — twin-registry drift"))

    # wire twin: every server counter must exist Python-side unless it
    # is on the documented C-only list
    if srv is not None and py is not None:
        for name, line in sorted(c_json_names(srv).items()):
            if name not in py_names and name not in PS_SERVER_C_ONLY:
                f.append(Finding(
                    "stats", srv_rel, line,
                    f"C PS-server renderer emits '{name}' but "
                    f"distributed/ps/table.py never maintains it and it "
                    f"is not on the documented C-only list"))

    # histogram layout: bucket count and dict shape must match
    if hdr is not None and pystats is not None:
        m = re.search(r"kHistBuckets\s*=\s*(\d+)", hdr)
        pyb = py_int_constants(pystats, stats_rel, "stats",
                               f).get("HIST_BUCKETS")
        if m is None:
            f.append(Finding("stats", hdr_rel, 0,
                             "kHistBuckets not found"))
        elif pyb is None:
            f.append(Finding("stats", stats_rel, 0,
                             "HIST_BUCKETS not found"))
        elif int(m.group(1)) != pyb:
            f.append(Finding(
                "stats", hdr_rel, _lineno(hdr, m.start()),
                f"kHistBuckets = {m.group(1)} but profiler/stats.py "
                f"HIST_BUCKETS = {pyb} — snapshots no longer merge "
                f"bucket-for-bucket"))
        for key in ("count", "sum", "buckets"):
            if f'"{key}"' not in hdr:
                f.append(Finding("stats", hdr_rel, 0,
                                 f"C histogram JSON lacks the '{key}' "
                                 f"field profiler/stats.py renders"))

    # decode-view twin (ISSUE 19): every key tools/ps_stats.py reads
    # out of the serving snapshot's "decode" object must actually be
    # rendered by the C decode stats block in ptpu_serving.cc — a
    # renamed counter would silently flatline the --watch columns
    sv_rel = "csrc/ptpu_serving.cc"
    pstool_rel = "tools/ps_stats.py"
    sv = _require(root, sv_rel, "stats", f)
    pstool = _require(root, pstool_rel, "stats", f)
    if sv is not None and pstool is not None:
        sv_names = set(c_json_names(sv))
        reads: Dict[str, int] = {}
        for m in re.finditer(
                r'(?:\bdd\(|cur\[[\'"]decode[\'"]\]\.get\()'
                r'[\'"](\w+)[\'"]', pstool):
            reads.setdefault(m.group(1), _lineno(pstool, m.start()))
        for name, line in sorted(reads.items()):
            if name not in sv_names:
                f.append(Finding(
                    "stats", pstool_rel, line,
                    f"ps_stats.py reads decode['{name}'] but "
                    f"ptpu_serving.cc's decode renderer never emits "
                    f"it — --watch column would flatline"))
    return f


# ---------------------------------------------------------------------------
# checker: locks
# ---------------------------------------------------------------------------

# ptpu_sync.h IS the sanctioned wrapper around the raw timed waits (it
# exists to reroute them under TSan), so the wait rules skip it.
# ptpu_lockdep_selftest.cc: the seeded-violation fixture suite — its
# deliberately predicate-free waits ARE the fixtures
# ptpu_schedck.cc: the engine's scheduling gate (cv.wait under its own
# raw mutex) re-checks `running == tid` in its wake loop; the selftest
# deliberately exercises un-predicated timed waits to test the model's
# timeout-as-wake semantics — both are schedck-internal, like the
# seeded fixtures in ptpu_lockdep_selftest.cc.
LOCK_EXEMPT_FILES = {"ptpu_sync.h", "ptpu_lockdep_selftest.cc",
                     "ptpu_schedck.cc", "ptpu_schedck_selftest.cc"}


def _top_level_arg_count(clean: str, open_paren: int) -> int:
    """Number of comma-separated args of the call whose '(' is at
    open_paren. Returns -1 on unbalanced input."""
    depth, args, i, n = 0, 0, open_paren, len(clean)
    saw_token = False
    while i < n:
        c = clean[i]
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
            if depth == 0:
                return args + 1 if saw_token else 0
        elif depth == 1:
            if c == ",":
                args += 1
            elif not c.isspace():
                saw_token = True
        i += 1
    return -1


def check_locks(root: str) -> List[Finding]:
    f: List[Finding] = []
    csrc = os.path.join(root, "csrc")
    if not os.path.isdir(csrc):
        f.append(Finding("locks", "csrc", 0, "csrc directory missing"))
        return f
    for fname in sorted(os.listdir(csrc)):
        if not (fname.endswith(".cc") or fname.endswith(".h")):
            continue
        rel = f"csrc/{fname}"
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        lines = clean.split("\n")

        if fname not in LOCK_EXEMPT_FILES:
            # condvar wait must carry a predicate: a bare wait(lock)
            # returns on spurious wakeups with no recheck
            for m in re.finditer(r"\.\s*wait\s*(\()", clean):
                if _top_level_arg_count(clean, m.start(1)) == 1:
                    f.append(Finding(
                        "locks", rel, _lineno(clean, m.start()),
                        "condition_variable wait() without a predicate "
                        "— spurious wakeups return with the condition "
                        "unchecked; pass a predicate lambda"))
            # timed waits without a predicate are only sound inside an
            # explicit re-check loop. Covers the raw wait_for/wait_until
            # forms AND the sanctioned ptpu::CvWaitForUs wrapper
            # (ptpu_sync.h): its 3-arg form (cv, lock, usec) has no
            # predicate; the 4-arg form rechecks internally.
            for m in re.finditer(
                    r"\b(\w*[Ww]ait_(?:for|until)\w*|CvWaitForUs)"
                    r"\s*(\()", clean):
                argc = _top_level_arg_count(clean, m.start(2))
                predicated = argc == 4 if m.group(1) == "CvWaitForUs" \
                    else argc != 2
                if predicated:
                    continue  # predicated form rechecks internally
                ln = _lineno(clean, m.start())
                ctx = "\n".join(lines[max(0, ln - 7):ln])
                if not re.search(r"\bwhile\s*\(|\bfor\s*\(\s*;\s*;", ctx):
                    f.append(Finding(
                        "locks", rel, ln,
                        f"{m.group(1)} without predicate is not inside "
                        f"a visible re-check loop (checked 6 lines up) "
                        f"— wrap it in while(pred) or use the "
                        f"predicated overload"))

        # std:: primitives only: raw pthread_/__sync_/__atomic_ calls
        # bypass RAII and the TSan interceptor story the tree relies on
        for m in re.finditer(r"\b(pthread_\w+|__sync_\w+|__atomic_\w+)"
                             r"\s*\(", clean):
            f.append(Finding(
                "locks", rel, _lineno(clean, m.start()),
                f"raw {m.group(1)}() call — use the std:: concurrency "
                f"primitives (RAII, TSan-visible)"))
    return f


# ---------------------------------------------------------------------------
# checker: net
# ---------------------------------------------------------------------------

# The two wire servers ride the shared epoll core (csrc/ptpu_net.cc).
# This checker keeps the C10K refactor from regressing: no direct
# accept() loops or per-connection thread bookkeeping may reappear in
# the server TUs, every fd an event loop registers must be provably
# nonblocking, and every epoll_wait loop must handle EPOLLERR/EPOLLHUP
# (an unhandled error event spins a level-triggered loop at 100% CPU).
NET_SERVER_FILES = ["csrc/ptpu_ps_server.cc", "csrc/ptpu_serving.cc"]

_EPOLL_ADD_RE = re.compile(
    r"epoll_ctl\s*\([^,]+,\s*EPOLL_CTL_ADD\s*,\s*([A-Za-z_]\w*"
    r"(?:(?:->|\.)\w+)*)")


def check_net(root: str) -> List[Finding]:
    f: List[Finding] = []
    csrc = os.path.join(root, "csrc")
    if not os.path.isdir(csrc):
        f.append(Finding("net", "csrc", 0, "csrc directory missing"))
        return f
    for fname in sorted(os.listdir(csrc)):
        if not (fname.endswith(".cc") or fname.endswith(".h")):
            continue
        rel = f"csrc/{fname}"
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        # 1) every fd entering an epoll set must be set nonblocking —
        #    a blocking fd in a level-triggered loop stalls EVERY
        #    connection that loop owns. Accepted proofs, per fd
        #    expression: a SetNonBlocking(fd) call, or creation with
        #    EFD_NONBLOCK / SOCK_NONBLOCK.
        for m in _EPOLL_ADD_RE.finditer(clean):
            fd = m.group(1)
            fd_re = re.escape(fd)
            proven = (
                re.search(rf"SetNonBlocking\s*\(\s*{fd_re}\s*\)", clean)
                or re.search(rf"{fd_re}\s*=[^;]*EFD_NONBLOCK", clean)
                or re.search(rf"{fd_re}\s*=[^;]*SOCK_NONBLOCK", clean))
            if not proven:
                f.append(Finding(
                    "net", rel, _lineno(clean, m.start()),
                    f"fd '{fd}' is registered with EPOLL_CTL_ADD but "
                    f"never provably set nonblocking (SetNonBlocking / "
                    f"EFD_NONBLOCK / SOCK_NONBLOCK) — a blocking fd "
                    f"stalls the whole event loop"))
        # 2) every event loop must handle error/hangup events
        if re.search(r"\bepoll_wait\s*\(", clean):
            for flag in ("EPOLLERR", "EPOLLHUP"):
                if not re.search(rf"\b{flag}\b", clean):
                    f.append(Finding(
                        "net", rel, 0,
                        f"file calls epoll_wait but never handles "
                        f"{flag} — an errored fd spins a "
                        f"level-triggered loop forever"))
    # 3) the servers must stay on the shared core: no direct accept()
    #    and no per-connection thread bookkeeping (the r7-era
    #    conn_threads pattern) may reappear
    for rel in NET_SERVER_FILES:
        src = _require(root, rel, "net", f)
        if src is None:
            continue
        clean = strip_c_comments(src)
        for m in re.finditer(r"\baccept\s*\(", clean):
            f.append(Finding(
                "net", rel, _lineno(clean, m.start()),
                "direct accept() call — connection accept/dispatch "
                "belongs to the shared epoll core (csrc/ptpu_net.cc); "
                "register a frame handler instead"))
        for m in re.finditer(r"\bconn_threads?\b", clean):
            f.append(Finding(
                "net", rel, _lineno(clean, m.start()),
                "per-connection thread bookkeeping reappeared — the "
                "thread-per-connection pattern is banned in the wire "
                "servers (C10K: connections cost fds, not threads)"))
    # 4) zero-copy hot path (ISSUE 17): frame handlers parse payloads
    #    in place in the conn's reassembly buffer — a whole-payload
    #    copy out of `req` into staging storage is banned. Two shapes
    #    are caught: a range .assign(req ...) and a memcpy sourcing
    #    req with a runtime payload-size identifier (fixed header
    #    reads pass — their size is a literal or a bounded-ndim
    #    expression). The ONE allowed staging copy is the dynamic
    #    fallback for unpinnable (Detached) conns, proven by a
    #    PinInbuf()/.pin guard in the immediately preceding context.
    for rel in NET_SERVER_FILES:
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        hits = [(m.start(), "range-assign")
                for m in re.finditer(r"\.assign\(\s*req\b", clean)]
        hits += [(m.start(), "memcpy")
                 for m in re.finditer(
                     r"memcpy\([^;()]*,\s*req\s*\+[^;()]*,\s*"
                     r"[A-Za-z_]\w*\s*\)", clean)]
        for pos, kind in sorted(hits):
            ctx = clean[max(0, pos - 600):pos]
            if "PinInbuf" in ctx or re.search(r"\.pin\b", ctx):
                continue  # dynamic fallback for unpinnable conns
            f.append(Finding(
                "net", rel, _lineno(clean, pos),
                f"whole-payload {kind} from the reassembly buffer "
                f"into staging on a frame-handler hot path — parse "
                f"in place (PinInbuf + borrowed views); only the "
                f"pin-guarded Detached-conn fallback may copy"))
    return f


# ---------------------------------------------------------------------------
# checker: nullcheck
# ---------------------------------------------------------------------------

HANDLE_PARAM = re.compile(
    r"^(?:void|PTPU_Predictor)\s*\*\s*(\w+)\s*$")


def _c_functions(clean: str):
    """Yield (name, params, body, line) for ptpu_* function DEFINITIONS."""
    for m in re.finditer(r"\b(ptpu_\w+)\s*\(([^;{)]*)\)\s*\{", clean):
        name, params = m.group(1), m.group(2)
        # walk to the matching close brace
        depth, i, n = 1, m.end(), len(clean)
        while i < n and depth:
            if clean[i] == "{":
                depth += 1
            elif clean[i] == "}":
                depth -= 1
            i += 1
        yield name, params, clean[m.end():i], _lineno(clean, m.start())


def check_nullcheck(root: str) -> List[Finding]:
    f: List[Finding] = []
    csrc = os.path.join(root, "csrc")
    if not os.path.isdir(csrc):
        f.append(Finding("nullcheck", "csrc", 0, "csrc directory missing"))
        return f
    for fname in sorted(os.listdir(csrc)):
        if not fname.endswith(".cc"):
            continue
        rel = f"csrc/{fname}"
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        for name, params, body, line in _c_functions(clean):
            first = params.split(",")[0].strip() if params.strip() else ""
            pm = HANDLE_PARAM.match(first)
            if not pm:
                continue  # not a handle-taking ABI entry
            h = pm.group(1)
            head = body[:400]
            # the idiomatic bodies first cast the handle into a typed
            # local and guard THAT: accept guards on any alias of h
            names = {h}
            for am in re.finditer(
                    rf"(\w+)\s*=\s*(?:static_cast<[^>]*>\s*\(\s*{h}\s*\)"
                    rf"|\(\s*\w+\s*\*\s*\)\s*{h}\b)", head):
                names.add(am.group(1))
            alias = "|".join(sorted(names))
            guarded = (
                re.search(rf"if\s*\(\s*!\s*(?:{alias})\b", head) or
                re.search(rf"if\s*\(\s*(?:{alias})\s*==\s*(?:nullptr|NULL)",
                          head) or
                re.search(rf"\b(?:{alias})\s*\?", head) or    # t ? x : y
                # delegation: the entry forwards the handle verbatim as
                # the first argument (the callee carries the guard —
                # e.g. set_input_int, ptpu_ps_table_push_raw)
                re.search(rf"return\s+\w+\(\s*{h}\b", head))
            if not guarded:
                f.append(Finding(
                    "nullcheck", rel, line,
                    f"C ABI entry {name}() dereferences handle "
                    f"'{h}' without a NULL guard (first statements) — "
                    f"ctypes/cgo callers can pass NULL after a failed "
                    f"create or a teardown race"))
    return f


# ---------------------------------------------------------------------------
# checker: trace
# ---------------------------------------------------------------------------

# The request-tracing seam (ISSUE 10) spans four hand-maintained
# contracts: the v2 traced-frame extension (version byte + 8-byte
# trace-id insert) between each C server and its Python wire twin, the
# trace-id read/echo offsets, and the span-kind name table the C
# recorder emits vs the Python timeline map that renders it.

# C version constant -> (python twin file, python constant)
TRACE_VERSIONS = {
    "csrc/ptpu_serving.cc": ("kSvWireVersionTraced",
                             "paddle_tpu/inference/serving.py"),
    "csrc/ptpu_ps_server.cc": ("kWireVersionTraced",
                               "paddle_tpu/distributed/ps/wire.py"),
}

# files that must agree on the 8-byte trace-id extension width
TRACE_EXT_PY = ["paddle_tpu/inference/serving.py",
                "paddle_tpu/distributed/ps/wire.py"]


def _py_dict_literal(src: str, name: str, rel: str, checker: str,
                     findings: List[Finding]):
    """Top-level `name = {literal dict}` via ast, or None."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        findings.append(Finding(checker, rel, e.lineno or 0,
                                f"cannot parse: {e.msg}"))
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name:
            try:
                return ast.literal_eval(node.value)
            except (ValueError, TypeError):
                findings.append(Finding(checker, rel, node.lineno,
                                        f"{name} is not a literal"))
                return None
    findings.append(Finding(checker, rel, 0, f"{name} not found"))
    return None


def check_trace(root: str) -> List[Finding]:
    f: List[Finding] = []
    hdr_rel, cc_rel = "csrc/ptpu_trace.h", "csrc/ptpu_trace.cc"
    tl_rel = "paddle_tpu/profiler/timeline.py"
    hdr = _require(root, hdr_rel, "trace", f)
    cc = _require(root, cc_rel, "trace", f)
    tl = _require(root, tl_rel, "trace", f)

    # 1) span-kind names: the C table (index == wire value in /tracez)
    #    must equal the Python timeline map rendering those spans
    if cc is not None and tl is not None:
        clean = strip_c_comments(cc, keep_strings=True)
        m = re.search(r"kSpanKindNames\s*\[[^\]]*\]\s*=\s*\{(.*?)\};",
                      clean, re.S)
        py_map = _py_dict_literal(tl, "SPAN_KIND_NAMES", tl_rel,
                                  "trace", f)
        if m is None:
            f.append(Finding("trace", cc_rel, 0,
                             "kSpanKindNames table not found"))
        elif py_map is not None:
            c_names = re.findall(r'"([^"]*)"', m.group(1))
            line = _lineno(clean, m.start())
            if sorted(py_map) != list(range(len(c_names))):
                f.append(Finding(
                    "trace", tl_rel, 0,
                    f"SPAN_KIND_NAMES keys {sorted(py_map)} are not "
                    f"dense 0..{len(c_names) - 1} — kind values are "
                    f"array indices in C"))
            else:
                for i, cn in enumerate(c_names):
                    if py_map.get(i) != cn:
                        f.append(Finding(
                            "trace", cc_rel, line,
                            f"span kind {i} is '{cn}' in C but "
                            f"'{py_map.get(i)}' in timeline.py "
                            f"SPAN_KIND_NAMES — /tracez names would "
                            f"render wrong"))

    # 2) trace-id extension width: C kTraceExt == every Python
    #    TRACE_EXT (the v2 body shift)
    c_ext = None
    if hdr is not None:
        m = re.search(r"kTraceExt\s*=\s*(\d+)", hdr)
        if m is None:
            f.append(Finding("trace", hdr_rel, 0,
                             "kTraceExt not found"))
        else:
            c_ext = int(m.group(1))
    for rel in TRACE_EXT_PY:
        src = _require(root, rel, "trace", f)
        if src is None:
            continue
        pyv = py_int_constants(src, rel, "trace", f).get("TRACE_EXT")
        if pyv is None:
            f.append(Finding("trace", rel, 0, "TRACE_EXT not found"))
        elif c_ext is not None and pyv != c_ext:
            f.append(Finding(
                "trace", rel, 0,
                f"TRACE_EXT = {pyv} but csrc/ptpu_trace.h kTraceExt = "
                f"{c_ext} — traced-frame offsets drift"))

    # 3) traced version bytes + trace-id offset probes per server
    for c_rel, (c_name, py_rel) in sorted(TRACE_VERSIONS.items()):
        c_src = _require(root, c_rel, "trace", f)
        py_src = _require(root, py_rel, "trace", f)
        if c_src is None or py_src is None:
            continue
        c_consts = c_u8_constants(c_src)
        py_consts = py_int_constants(py_src, py_rel, "trace", f)
        if c_name not in c_consts:
            f.append(Finding("trace", c_rel, 0,
                             f"{c_name} not found"))
        elif "WIRE_VERSION_TRACED" not in py_consts:
            f.append(Finding("trace", py_rel, 0,
                             "WIRE_VERSION_TRACED not found"))
        else:
            cv, line = c_consts[c_name]
            pv = py_consts["WIRE_VERSION_TRACED"]
            if cv != pv:
                f.append(Finding(
                    "trace", c_rel, line,
                    f"{c_name} = {cv} in C but WIRE_VERSION_TRACED = "
                    f"{pv} in {py_rel} — traced-frame version drift"))
        clean = strip_c_comments(c_src)
        # the trace id sits at payload offset 2 ([ver][tag][u64 id])
        if not re.search(r"GetU64\(req\s*\+\s*2\)", clean):
            f.append(Finding(
                "trace", c_rel, 0,
                "traced-frame id read GetU64(req + 2) not found "
                "(layout probe: [ver][tag][u64 trace id])"))
        # replies echo it right after [4B len][ver][tag]
        if not re.search(r"PutU64\(\w+\.data\(\)\s*\+\s*6,", clean):
            f.append(Finding(
                "trace", c_rel, 0,
                "trace-id echo write at reply offset 6 not found "
                "(layout probe: [len][ver][tag][u64 trace id])"))
    # Python reads the id at the same payload offset 2
    pys = _read(root, "paddle_tpu/inference/serving.py")
    if pys is not None and \
            not re.search(r"def _frame_trace_id[^#]*?unpack_from\(\s*f,"
                          r"\s*2\s*\)", pys, re.S):
        f.append(Finding("trace", "paddle_tpu/inference/serving.py", 0,
                         "_frame_trace_id must read the id at payload "
                         "offset 2 (layout probe)"))
    pyw = _read(root, "paddle_tpu/distributed/ps/wire.py")
    if pyw is not None and \
            not re.search(r"def trace_id_of[^#]*?unpack_from\(\s*data,"
                          r"\s*2\s*\)", pyw, re.S):
        f.append(Finding("trace", "paddle_tpu/distributed/ps/wire.py",
                         0,
                         "trace_id_of must read the id at payload "
                         "offset 2 (layout probe)"))

    # 4) drill telemetry route twins (ISSUE 18): each observability
    #    route the drill harness depends on must be SERVED by its C
    #    plane and CONSUMED by tools/drill_replay.py — a renamed or
    #    dropped route on either side breaks capture fetch / shadow
    #    reporting silently, so both halves are pinned here.
    dr_rel = "tools/drill_replay.py"
    dr = _require(root, dr_rel, "trace", f)
    consumer_checked: Set[str] = set()
    for route, c_rel in (("/capturez", "csrc/ptpu_net.cc"),
                         ("/shadowz", "csrc/ptpu_serving.cc"),
                         # the conservation-law verdict route (ISSUE
                         # 20): each plane serves it, the drill
                         # harness polls it at soak quiesce
                         ("/invarz", "csrc/ptpu_serving.cc"),
                         ("/invarz", "csrc/ptpu_ps_server.cc")):
        c_src = _require(root, c_rel, "trace", f)
        if c_src is not None and \
                f'"{route}"' not in strip_c_comments(
                    c_src, keep_strings=True):
            f.append(Finding(
                "trace", c_rel, 0,
                f"route {route} is not served (no \"{route}\" "
                f"literal) — the drill harness consumes it "
                f"(tools/drill_replay.py)"))
        if dr is not None and route not in consumer_checked and \
                f'"{route}' not in dr:
            f.append(Finding(
                "trace", dr_rel, 0,
                f"no consumer for route {route} — drill_replay.py "
                f"must fetch it (route twin)"))
        consumer_checked.add(route)
    return f


# ---------------------------------------------------------------------------
# checker: sync
# ---------------------------------------------------------------------------

# ISSUE 11: every mutex/condvar in csrc lives behind the ptpu_sync.h
# wrappers (ptpu::Mutex / SharedMutex / CondVar) so ptpu_lockdep sees
# every acquisition — a raw std:: primitive is invisible to the rank
# checks and the acquisition-order graph. Exempt: ptpu_sync.h (it IS
# the wrapper) and ptpu_schedck.cc (the model-checker engine runs
# BENEATH the wrappers — its one raw mutex/cv pair serializes the
# managed threads and must not recurse into its own instrumentation).
SYNC_EXEMPT_FILES = {"ptpu_sync.h", "ptpu_schedck.cc"}
SYNC_BANNED = [
    "std::mutex", "std::shared_mutex", "std::recursive_mutex",
    "std::timed_mutex", "std::condition_variable", "pthread_mutex_t",
    "pthread_cond_t",
]

_LOCK_CLASS_DECL = re.compile(
    r"PTPU_LOCK_CLASS\s*\(\s*(\w+)\s*,\s*\"([^\"]*)\"\s*,([^)]*)\)")
_LOCK_WRAPPER_CTOR = re.compile(
    r"\b(?:ptpu::)?(Mutex|SharedMutex)\b\s+(\w+)\s*[({]\s*(\w+)")


def _csrc_sources(root: str):
    """Yield (rel, fname) for every .cc/.h under csrc/, one level of
    subdirectories included (csrc/fuzz harnesses are in scope)."""
    csrc = os.path.join(root, "csrc")
    for dirpath, _dirs, files in os.walk(csrc):
        for fname in sorted(files):
            if not (fname.endswith(".cc") or fname.endswith(".h")):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fname), root)
            yield rel.replace(os.sep, "/"), fname


def check_sync(root: str) -> List[Finding]:
    f: List[Finding] = []
    if not os.path.isdir(os.path.join(root, "csrc")):
        f.append(Finding("sync", "csrc", 0, "csrc directory missing"))
        return f
    classes: Dict[str, Tuple[str, int, int]] = {}  # var -> (name, rank, line)
    names_seen: Dict[str, Tuple[str, str]] = {}    # class name -> (rank str, rel)
    sources = []
    for rel, fname in _csrc_sources(root):
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        # class declarations carry their name in a string literal:
        # parse them from a strings-kept strip
        decls = strip_c_comments(src, keep_strings=True)
        sources.append((rel, fname, clean))
        for m in _LOCK_CLASS_DECL.finditer(decls):
            var, cname, tail = m.group(1), m.group(2), m.group(3)
            ln = _lineno(clean, m.start())
            rank_m = re.match(r"\s*(\d+)\s*(?:,|$)", tail)
            if rank_m is None:
                f.append(Finding(
                    "sync", rel, ln,
                    f"lock class {var} (\"{cname}\") declared without "
                    f"a literal numeric rank — every class carries its "
                    f"place in the global acquisition order (README "
                    f"rank table)"))
                continue
            rank = rank_m.group(1)
            prev = names_seen.get(cname)
            if prev is not None and prev[0] != rank:
                f.append(Finding(
                    "sync", rel, ln,
                    f"lock class \"{cname}\" declared with rank {rank} "
                    f"here but rank {prev[0]} in {prev[1]} — one class, "
                    f"one rank"))
            names_seen[cname] = (rank, rel)
            classes[var] = (cname, int(rank), ln)
    for rel, fname, clean in sources:
        if fname in SYNC_EXEMPT_FILES:
            continue
        for tok in SYNC_BANNED:
            for m in re.finditer(re.escape(tok) + r"\b", clean):
                f.append(Finding(
                    "sync", rel, _lineno(clean, m.start()),
                    f"raw {tok} outside csrc/ptpu_sync.h — use the "
                    f"ptpu::Mutex/SharedMutex/CondVar wrappers so "
                    f"ptpu_lockdep sees the acquisition"))
        for m in _LOCK_WRAPPER_CTOR.finditer(clean):
            kind, var, cls = m.group(1), m.group(2), m.group(3)
            if cls in classes:
                continue
            f.append(Finding(
                "sync", rel, _lineno(clean, m.start()),
                f"ptpu::{kind} {var} constructed from '{cls}', which "
                f"is not a PTPU_LOCK_CLASS declaration visible in "
                f"csrc — every lock names a ranked class"))
    return f


# ---------------------------------------------------------------------------
# checker: fuzz
# ---------------------------------------------------------------------------

# ISSUE 11: every untrusted-byte surface parsed in C maps to a fuzz
# harness with a checked-in corpus: each wire tag a server TU declares
# must appear as the tag byte of a corpus frame, each HTTP telemetry
# route must appear in the http corpus, and each ONNX op the predictor
# dispatches must appear (as op_type bytes) in the onnx corpus — so a
# new tag/route/op CANNOT land without seed coverage (regen via
# csrc/fuzz/gen_seeds.py).
FUZZ_TARGET_SOURCES = {
    "wire_ps": "csrc/ptpu_ps_server.cc",
    "wire_serving": "csrc/ptpu_serving.cc",
    "http": "csrc/ptpu_net.cc",
    "onnx": "csrc/ptpu_predictor.cc",
    "json": "csrc/ptpu_trace.cc",
    "frames": "csrc/ptpu_net.cc",
    "tune": "csrc/ptpu_tune.h",
    "capture": "csrc/ptpu_capture.h",
    "spill": "csrc/ptpu_spill.h",
}


def _onnx_ops_parsed(src: str) -> Set[str]:
    """Op names csrc/ptpu_predictor.cc dispatches on (the extraction
    csrc/fuzz/gen_seeds.py mirrors for the all-ops seed)."""
    clean = strip_c_comments(src, keep_strings=True)
    ops = set(re.findall(r'\bop == "([A-Z][A-Za-z0-9]*)"', clean))
    ops |= set(re.findall(r'\.op == "([A-Z][A-Za-z0-9]*)"', clean))
    ops |= set(re.findall(
        r'\{"([A-Z][A-Za-z0-9]*)",\s*[BU]_[A-Z0-9_]+\}', clean))
    return ops


def _corpus_blobs(root: str, target: str) -> List[bytes]:
    d = os.path.join(root, "csrc", "fuzz", "corpus", target)
    blobs = []
    if os.path.isdir(d):
        for fname in sorted(os.listdir(d)):
            p = os.path.join(d, fname)
            if os.path.isfile(p):
                with open(p, "rb") as fh:
                    blobs.append(fh.read())
    return blobs


def check_fuzz(root: str) -> List[Finding]:
    f: List[Finding] = []
    fuzz_dir = os.path.join(root, "csrc", "fuzz")
    if not os.path.isdir(fuzz_dir):
        f.append(Finding("fuzz", "csrc/fuzz", 0,
                         "csrc/fuzz directory missing"))
        return f

    # 1) each target has a harness, a Makefile build entry, and a
    #    non-empty checked-in corpus
    mk = _require(root, "csrc/Makefile", "fuzz", f) or ""
    mk_targets = set(re.findall(r"\bfuzz_(\w+)\b",
                                "".join(re.findall(
                                    r"FUZZ_TARGETS\s*:=((?:[^\n]*\\\n)*[^\n]*)",
                                    mk))))
    for target in sorted(FUZZ_TARGET_SOURCES):
        harness = f"csrc/fuzz/fuzz_{target}.cc"
        if _read(root, harness) is None:
            f.append(Finding("fuzz", harness, 0,
                             f"fuzz harness for '{target}' missing"))
        if target not in mk_targets:
            f.append(Finding(
                "fuzz", "csrc/Makefile", 0,
                f"fuzz_{target} not listed in FUZZ_TARGETS — `make "
                f"fuzz` would not build it"))
        if not _corpus_blobs(root, target):
            f.append(Finding(
                "fuzz", f"csrc/fuzz/corpus/{target}", 0,
                f"no checked-in corpus for '{target}' (run "
                f"csrc/fuzz/gen_seeds.py)"))

    # 2) every wire tag a server TU declares appears as the tag byte
    #    of at least one corpus frame for its plane
    for target, rel in (("wire_ps", "csrc/ptpu_ps_server.cc"),
                        ("wire_serving", "csrc/ptpu_serving.cc")):
        src = _require(root, rel, "fuzz", f)
        if src is None:
            continue
        clean = strip_c_comments(src)
        blobs = _corpus_blobs(root, target)
        for m in re.finditer(
                r"constexpr\s+uint8_t\s+(kTag\w+)\s*=\s*0x([0-9a-fA-F]+)\s*;",
                clean):
            name, val = m.group(1), int(m.group(2), 16)
            covered = any(len(b) >= 2 and b[0] in (1, 2) and b[1] == val
                          for b in blobs)
            if not covered:
                f.append(Finding(
                    "fuzz", rel, _lineno(clean, m.start()),
                    f"wire tag {name} (0x{val:02x}) has no corpus "
                    f"frame in csrc/fuzz/corpus/{target} — add a seed "
                    f"(gen_seeds.py) so the fuzzer starts from it"))

    # 3) every HTTP telemetry route appears in the http corpus
    net = _require(root, "csrc/ptpu_net.cc", "fuzz", f)
    if net is not None:
        clean = strip_c_comments(net, keep_strings=True)
        routes = set(re.findall(r'path == "(/\w+)"', clean))
        blobs = _corpus_blobs(root, "http")
        for route in sorted(routes):
            if not any(route.encode() in b for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/ptpu_net.cc", 0,
                    f"HTTP route {route} has no request in "
                    f"csrc/fuzz/corpus/http — add a seed "
                    f"(gen_seeds.py)"))

    # 4) every ONNX op the predictor parses appears in the onnx corpus
    pred = _require(root, "csrc/ptpu_predictor.cc", "fuzz", f)
    if pred is not None:
        blobs = _corpus_blobs(root, "onnx")
        for opname in sorted(_onnx_ops_parsed(pred)):
            if not any(opname.encode() in b for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/ptpu_predictor.cc", 0,
                    f"ONNX op '{opname}' is parsed but appears in no "
                    f"csrc/fuzz/corpus/onnx seed — regen the all-ops "
                    f"seed (gen_seeds.py)"))

    # 5) tuning cache (ISSUE 16): the corpus must seed BOTH sides of
    #    the magic check (well-formed caches reach the record parser,
    #    alien bytes reach the reject path), and gen_seeds.py's twin
    #    magic constant must track the parser's
    tune_rel = "csrc/ptpu_tune.h"
    tune_hdr = _require(root, tune_rel, "fuzz", f)
    if tune_hdr is not None:
        clean = strip_c_comments(tune_hdr)
        m = re.search(r"\bkTuneMagic\s*=\s*0x([0-9a-fA-F]+)", clean)
        if m is None:
            f.append(Finding(
                "fuzz", tune_rel, 0,
                "kTuneMagic literal not found — the fuzz checker keys "
                "the tune corpus on it"))
        else:
            magic = int(m.group(1), 16)
            magic_le = magic.to_bytes(4, "little")
            blobs = _corpus_blobs(root, "tune")
            if not any(b[:4] == magic_le for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/tune", 0,
                    "no tune corpus seed starts with the PTUN magic — "
                    "the fuzzer never starts inside the record parser "
                    "(regen via gen_seeds.py)"))
            if not any(len(b) >= 4 and b[:4] != magic_le for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/tune", 0,
                    "no tune corpus seed with a non-PTUN magic — the "
                    "alien-file reject path is unseeded (gen_seeds.py)"))
            gen = _require(root, "csrc/fuzz/gen_seeds.py", "fuzz", f)
            if gen is not None:
                gm = re.search(r"\bTUNE_MAGIC\s*=\s*0x([0-9a-fA-F]+)", gen)
                if gm is None or int(gm.group(1), 16) != magic:
                    f.append(Finding(
                        "fuzz", "csrc/fuzz/gen_seeds.py", 0,
                        "TUNE_MAGIC does not match kTuneMagic in "
                        "csrc/ptpu_tune.h — regenerated seeds would "
                        "miss the parser"))

    # 6) capture files (ISSUE 18): same two-sided seeding contract as
    #    the tune cache — the corpus must reach the record parser
    #    (PCAP magic) AND the alien-bytes reject path, and the seed
    #    generator's twin magic must track the header's
    cap_rel = "csrc/ptpu_capture.h"
    cap_hdr = _require(root, cap_rel, "fuzz", f)
    if cap_hdr is not None:
        clean = strip_c_comments(cap_hdr)
        m = re.search(r"\bkCaptureMagic\s*=\s*0x([0-9a-fA-F]+)", clean)
        if m is None:
            f.append(Finding(
                "fuzz", cap_rel, 0,
                "kCaptureMagic literal not found — the fuzz checker "
                "keys the capture corpus on it"))
        else:
            magic = int(m.group(1), 16)
            magic_le = magic.to_bytes(4, "little")
            blobs = _corpus_blobs(root, "capture")
            if not any(b[:4] == magic_le for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/capture", 0,
                    "no capture corpus seed starts with the PCAP "
                    "magic — the fuzzer never starts inside the "
                    "record parser (regen via gen_seeds.py)"))
            if not any(len(b) >= 4 and b[:4] != magic_le
                       for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/capture", 0,
                    "no capture corpus seed with a non-PCAP magic — "
                    "the alien-file reject path is unseeded "
                    "(gen_seeds.py)"))
            gen = _require(root, "csrc/fuzz/gen_seeds.py", "fuzz", f)
            if gen is not None:
                gm = re.search(r"\bCAPTURE_MAGIC\s*=\s*0x([0-9a-fA-F]+)",
                               gen)
                if gm is None or int(gm.group(1), 16) != magic:
                    f.append(Finding(
                        "fuzz", "csrc/fuzz/gen_seeds.py", 0,
                        "CAPTURE_MAGIC does not match kCaptureMagic "
                        "in csrc/ptpu_capture.h — regenerated seeds "
                        "would miss the parser"))

    # 7) KV spill tier (ISSUE 19): three formats share one corpus
    #    (spill header / hibernation record / prefix-persist file).
    #    Each magic needs the same two-sided seeding contract as the
    #    tune cache, and gen_seeds.py's twins must track the header's.
    spill_rel = "csrc/ptpu_spill.h"
    spill_hdr = _require(root, spill_rel, "fuzz", f)
    if spill_hdr is not None:
        clean = strip_c_comments(spill_hdr)
        gen = _require(root, "csrc/fuzz/gen_seeds.py", "fuzz", f)
        for cn, pn, nick in (("kSpillMagic", "SPILL_MAGIC", "PSPL"),
                             ("kHibMagic", "HIB_MAGIC", "PHIB"),
                             ("kPrefixMagic", "PREFIX_MAGIC", "PPFX")):
            m = re.search(r"\b%s\s*=\s*0x([0-9a-fA-F]+)" % cn, clean)
            if m is None:
                f.append(Finding(
                    "fuzz", spill_rel, 0,
                    f"{cn} literal not found — the fuzz checker keys "
                    f"the spill corpus on it"))
                continue
            magic = int(m.group(1), 16)
            magic_le = magic.to_bytes(4, "little")
            blobs = _corpus_blobs(root, "spill")
            if not any(b[:4] == magic_le for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/spill", 0,
                    f"no spill corpus seed starts with the {nick} "
                    f"magic — the fuzzer never starts inside that "
                    f"parser (regen via gen_seeds.py)"))
            if not any(len(b) >= 4 and b[:4] != magic_le
                       for b in blobs):
                f.append(Finding(
                    "fuzz", "csrc/fuzz/corpus/spill", 0,
                    f"no spill corpus seed with a non-{nick} magic — "
                    f"the alien-file reject path is unseeded "
                    f"(gen_seeds.py)"))
            if gen is not None:
                gm = re.search(r"\b%s\s*=\s*0x([0-9a-fA-F]+)" % pn,
                               gen)
                if gm is None or int(gm.group(1), 16) != magic:
                    f.append(Finding(
                        "fuzz", "csrc/fuzz/gen_seeds.py", 0,
                        f"{pn} does not match {cn} in "
                        f"csrc/ptpu_spill.h — regenerated seeds "
                        f"would miss the parser"))
    return f


# ---------------------------------------------------------------------------
# checker: sched
# ---------------------------------------------------------------------------

# ISSUE 15: the concurrency model checker (csrc/ptpu_schedck.h) only
# proves what its scenarios model, so coverage is a checked contract:
# every production PTPU_LOCK_CLASS name must map to at least one
# scenario in the manifest (csrc/ptpu_schedck_coverage.txt), every
# scenario the manifest names must exist in the selftest's registry,
# scenario TUs must spawn threads through the scheduler's wrapper
# (a raw std::thread is invisible to the exploration), and any TU
# using PTPU_SCHED_POINT must include ptpu_schedck.h (whose no-op
# fallback keeps production builds clean).

SCHED_MANIFEST = "csrc/ptpu_schedck_coverage.txt"
SCHED_SCENARIO_TU = "csrc/ptpu_schedck_selftest.cc"
# TUs whose lock classes mirror production ones (or are test-only):
# exempt from manifest coverage, subject to the std::thread ban
_SCHED_TEST_TU = re.compile(
    r"(?:_selftest\.cc|_fixture_\w+\.cc)$|^fuzz_")
# the engine TU owns the real threads behind the model — exempt
SCHED_ENGINE_FILES = {"ptpu_schedck.cc", "ptpu_schedck.h"}


def check_sched(root: str) -> List[Finding]:
    f: List[Finding] = []
    manifest = _require(root, SCHED_MANIFEST, "sched", f)
    selftest = _require(root, SCHED_SCENARIO_TU, "sched", f)
    if manifest is None or selftest is None:
        return f

    # manifest rows: <lock-class-name> <scenario> [<scenario>...]
    covered: Dict[str, List[str]] = {}
    for i, raw in enumerate(manifest.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) < 2:
            f.append(Finding(
                "sched", SCHED_MANIFEST, i,
                f"manifest row '{line}' names no scenario — format is "
                f"<lock-class-name> <scenario> [<scenario>...]"))
            continue
        covered[parts[0]] = parts[1:]

    # scenario registry: the {"name", ...} rows of the selftest suite
    registry = set(re.findall(
        r'\{\s*"([a-z][a-z0-9_]*)"\s*,',
        strip_c_comments(selftest, keep_strings=True)))
    for cname, scenarios in sorted(covered.items()):
        for sc in scenarios:
            if sc not in registry:
                f.append(Finding(
                    "sched", SCHED_MANIFEST, 0,
                    f"manifest maps \"{cname}\" to scenario '{sc}', "
                    f"which does not exist in the "
                    f"{SCHED_SCENARIO_TU} scenario registry"))

    prod_classes: Set[str] = set()
    for rel, fname in _csrc_sources(root):
        src = _read(root, rel)
        if src is None:
            continue
        clean = strip_c_comments(src)
        decls = strip_c_comments(src, keep_strings=True)
        test_tu = bool(_SCHED_TEST_TU.search(fname))

        # 1) production lock classes need a scenario mapping
        if not test_tu and fname not in SCHED_ENGINE_FILES:
            for m in _LOCK_CLASS_DECL.finditer(decls):
                cname = m.group(2)
                prod_classes.add(cname)
                if cname not in covered:
                    f.append(Finding(
                        "sched", rel, _lineno(clean, m.start()),
                        f"lock class \"{cname}\" has no row in "
                        f"{SCHED_MANIFEST} — model its protocol in a "
                        f"schedck scenario (csrc/"
                        f"ptpu_schedck_selftest.cc) and map it"))

        # 2) scenario TUs spawn threads only through the scheduler
        if (fname.startswith("ptpu_schedck_")
                and fname not in SCHED_ENGINE_FILES):
            for m in re.finditer(r"\bstd::thread\b", clean):
                f.append(Finding(
                    "sched", rel, _lineno(clean, m.start()),
                    "raw std::thread in a schedck scenario TU — use "
                    "ptpu::schedck::Thread so the exploration owns "
                    "the thread"))

        # 3) PTPU_SCHED_POINT only with the self-gating header
        if fname != "ptpu_schedck.h":
            uses = [m for m in re.finditer(r"\bPTPU_SCHED_POINT\b",
                                           clean)]
            if uses and '#include "ptpu_schedck.h"' not in decls:
                f.append(Finding(
                    "sched", rel, _lineno(clean, uses[0].start()),
                    "PTPU_SCHED_POINT used without including "
                    "ptpu_schedck.h — only its #ifdef PTPU_SCHEDCK "
                    "wrapper makes the macro a production no-op"))

    # stale manifest rows: class no longer declared in production
    for cname in sorted(covered):
        if cname not in prod_classes:
            f.append(Finding(
                "sched", SCHED_MANIFEST, 0,
                f"manifest row \"{cname}\" matches no PTPU_LOCK_CLASS "
                f"declared in production csrc — remove the stale row"))
    return f


# ---------------------------------------------------------------------------
# checker: invar
# ---------------------------------------------------------------------------

# ISSUE 20: the counter-conservation manifest (csrc/ptpu_invar.h)
# declares the laws both runtime gates evaluate AND binds every
# participating counter to the C++ member expression that bumps it and
# the TU(s) allowed to bump it. The runtime gate can only prove laws
# over whatever the counters actually accumulated — these rules prove
# the FLOW side statically:
#   A  every bound counter has at least one bump site in its declared
#      TU(s) (a deleted bump site compiles fine and the runtime law
#      only trips once traffic hits the dead path);
#   B  `pair` rows: any function body bumping the first expression
#      also touches the second (the nullcheck-style path rule — an
#      error path that bumps one side of a law without its twin);
#   C  no bound expression is bumped in a production TU outside the
#      union of its declared files (a new bump site must be declared,
#      or the law silently changes meaning);
#   D  no stale names: law terms resolve to bound paths, bound leaves
#      are actually rendered by some C snapshot renderer, gauge
#      expressions still exist in their TU, and the Python twin
#      manifest (profiler/stats.py INVAR_MANIFEST) is token-identical
#      to the C one — the two evaluators must read the same algebra.

INVAR_HEADER = "csrc/ptpu_invar.h"
INVAR_PY_TWIN = "paddle_tpu/profiler/stats.py"

# selftests, schedck fixtures and fuzz harnesses #include production
# TUs and doctor snapshots, but never bump production counters
# themselves — out of scope for the undeclared-bump scan
_INVAR_TEST_TU = re.compile(
    r"(?:_selftest\.cc|_fixture_\w+\.cc)$|^fuzz_|^gen_seeds")

# accepted bump forms for a counter expression: ptpu::Counter's
# .Add(n), and the raw-integer idioms the KV-pool ledger uses under
# its own mutex (++x / x++ / x += n)
def _invar_bump_re(expr: str) -> "re.Pattern[str]":
    e = re.escape(expr)
    return re.compile(
        rf"(?:\+\+\s*{e}\b|\b{e}\s*\+\+|\b{e}\s*\+=|\b{e}\s*\.\s*Add\s*\()")


def _invar_manifest_text(hdr: str, findings: List[Finding]) -> str:
    m = re.search(r'R"INV\((.*?)\)INV"', hdr, re.S)
    if m is None:
        findings.append(Finding(
            "invar", INVAR_HEADER, 0,
            'manifest raw string R"INV(...)INV" not found'))
        return ""
    return m.group(1)


def _invar_parse(text: str, findings: List[Finding]):
    """Manifest rows -> (bindings, laws, pairs). Grammar errors become
    findings (the manifest is itself a checked artifact)."""
    bindings, laws, pairs = [], [], []
    for i, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        tok = line.split()
        kind = tok[0]
        if kind in ("counter", "gauge"):
            if len(tok) != 5:
                findings.append(Finding(
                    "invar", INVAR_HEADER, i,
                    f"malformed {kind} row '{line}' — format is "
                    f"{kind} <planes> <path> <file,...> <expr>"))
                continue
            bindings.append({"kind": kind, "line": i,
                             "planes": tok[1].split(","),
                             "path": tok[2],
                             "files": tok[3].split(","),
                             "expr": tok[4]})
        elif kind == "invar":
            if len(tok) < 6 or tok[4] not in ("==", ">=") or \
                    tok[6::2] != ["+"] * len(tok[6::2]):
                findings.append(Finding(
                    "invar", INVAR_HEADER, i,
                    f"malformed invar row '{line}' — format is invar "
                    f"<planes> <name> <path> ==|>= <path> [+ <path>...]"))
                continue
            laws.append({"line": i, "planes": tok[1].split(","),
                         "name": tok[2], "lhs": tok[3], "op": tok[4],
                         "rhs": tok[5::2]})
        elif kind == "pair":
            if len(tok) != 4:
                findings.append(Finding(
                    "invar", INVAR_HEADER, i,
                    f"malformed pair row '{line}' — format is pair "
                    f"<file> <exprA> <exprB>"))
                continue
            pairs.append({"line": i, "file": tok[1],
                          "a": tok[2], "b": tok[3]})
        else:
            findings.append(Finding(
                "invar", INVAR_HEADER, i,
                f"unknown manifest keyword '{kind}'"))
    return bindings, laws, pairs


_INVAR_CTRL_KEYWORDS = {"if", "for", "while", "switch", "catch",
                        "return", "sizeof", "alignof", "defined"}


def _c_function_bodies(clean: str):
    """Yield (name, body, line) for every plausible function
    DEFINITION in comment-stripped C++ (any name, unlike
    _c_functions' ptpu_* ABI filter). Bodies found inside other
    bodies (local lambdas) are attributed to the enclosing match."""
    for m in re.finditer(r"\b([A-Za-z_]\w*)\s*\(([^;{})]*)\)\s*"
                         r"(?:const\s*|noexcept\s*|override\s*)*\{",
                         clean):
        name = m.group(1)
        if name in _INVAR_CTRL_KEYWORDS:
            continue
        depth, i, n = 1, m.end(), len(clean)
        while i < n and depth:
            if clean[i] == "{":
                depth += 1
            elif clean[i] == "}":
                depth -= 1
            i += 1
        yield name, clean[m.end():i], _lineno(clean, m.start())


def check_invar(root: str) -> List[Finding]:
    f: List[Finding] = []
    hdr = _require(root, INVAR_HEADER, "invar", f)
    if hdr is None:
        return f
    text = _invar_manifest_text(hdr, f)
    bindings, laws, pairs = _invar_parse(text, f)

    # production TU cache (comment-stripped, both with and without
    # string literals) for the rules below
    prod: Dict[str, str] = {}
    prod_strs: Dict[str, str] = {}
    for rel, fname in _csrc_sources(root):
        if _INVAR_TEST_TU.search(fname) or fname == "ptpu_invar.h":
            continue
        src = _read(root, rel)
        if src is None:
            continue
        prod[rel] = strip_c_comments(src)
        prod_strs[rel] = strip_c_comments(src, keep_strings=True)

    # ---- rule A: every counter binding has a bump site; gauges must
    # at least still mention their expression (levels are computed or
    # +/- adjusted, so no bump-form requirement)
    for b in bindings:
        rx = _invar_bump_re(b["expr"])
        missing = [rel for rel in b["files"] if rel not in prod]
        for rel in missing:
            f.append(Finding(
                "invar", INVAR_HEADER, b["line"],
                f"binding for {b['path']} names {rel}, which is not a "
                f"production csrc TU"))
        have = [rel for rel in b["files"] if rel in prod]
        if not have:
            continue
        if b["kind"] == "counter":
            if not any(rx.search(prod[rel]) for rel in have):
                using = "/".join(law["name"] for law in laws
                                 if b["path"] in [law["lhs"]] +
                                 law["rhs"]) or "declared"
                f.append(Finding(
                    "invar", INVAR_HEADER, b["line"],
                    f"counter {b['path']} is bound to '{b['expr']}' in "
                    f"{','.join(b['files'])} but no bump site "
                    f"(.Add/++/+=) exists there — the {using} law "
                    f"can no longer move"))
        else:
            if not any(b["expr"] in prod_strs[rel] for rel in have):
                f.append(Finding(
                    "invar", INVAR_HEADER, b["line"],
                    f"gauge {b['path']} is bound to '{b['expr']}' in "
                    f"{','.join(b['files'])} but the expression no "
                    f"longer appears there — stale binding"))

    # ---- rule B: pair discipline, per function body
    for p in pairs:
        src = prod.get(p["file"])
        if src is None:
            f.append(Finding(
                "invar", INVAR_HEADER, p["line"],
                f"pair row names {p['file']}, which is not a "
                f"production csrc TU"))
            continue
        rx_a = _invar_bump_re(p["a"])
        b_pat = re.compile(re.escape(p["b"]))
        bumped_somewhere = False
        for name, body, line in _c_function_bodies(src):
            am = rx_a.search(body)
            if not am:
                continue
            bumped_somewhere = True
            if not b_pat.search(body):
                f.append(Finding(
                    "invar", p["file"],
                    line + body[:am.start()].count("\n"),
                    f"{name}() bumps {p['a']} without touching its "
                    f"paired counter {p['b']} (pair rule, "
                    f"{INVAR_HEADER}:{p['line']}) — an error path "
                    f"moving one side of a conservation law"))
        if not bumped_somewhere:
            f.append(Finding(
                "invar", INVAR_HEADER, p["line"],
                f"pair row ({p['a']}, {p['b']}) matches no function "
                f"in {p['file']} that bumps {p['a']} — stale pair"))

    # ---- rule C: no undeclared bump site of a bound counter
    # expression anywhere in production csrc (union of declared files
    # across ALL bindings of that expression — e.g. stats.err_frames
    # is legitimately bumped by both wire servers)
    allowed: Dict[str, Set[str]] = {}
    for b in bindings:
        if b["kind"] == "counter":
            allowed.setdefault(b["expr"], set()).update(b["files"])
    for expr, files in sorted(allowed.items()):
        rx = _invar_bump_re(expr)
        for rel, clean in sorted(prod.items()):
            if rel in files:
                continue
            m = rx.search(clean)
            if m:
                f.append(Finding(
                    "invar", rel, _lineno(clean, m.start()),
                    f"bump site for manifest-bound counter '{expr}' "
                    f"in a TU the manifest does not declare "
                    f"(declared: {','.join(sorted(files))}) — declare "
                    f"it in {INVAR_HEADER} or the law silently "
                    f"changes meaning"))

    # ---- rule D: stale names
    bound_paths: Dict[str, Set[str]] = {}
    for b in bindings:
        bound_paths.setdefault(b["path"], set()).update(b["planes"])
    for law in laws:
        for term in [law["lhs"]] + law["rhs"]:
            planes = bound_paths.get(term)
            if planes is None:
                f.append(Finding(
                    "invar", INVAR_HEADER, law["line"],
                    f"law {law['name']} references {term}, which no "
                    f"counter/gauge row binds"))
            else:
                for pl in law["planes"]:
                    if pl not in planes:
                        f.append(Finding(
                            "invar", INVAR_HEADER, law["line"],
                            f"law {law['name']} runs on plane '{pl}' "
                            f"but {term} is only bound for "
                            f"{','.join(sorted(planes))}"))
    rendered: Set[str] = set()
    for rel, clean in prod_strs.items():
        if rel.endswith(".cc"):
            rendered |= set(c_json_names(clean))
    for b in bindings:
        leaf = b["path"].rsplit(".", 1)[-1]
        if leaf not in rendered:
            f.append(Finding(
                "invar", INVAR_HEADER, b["line"],
                f"manifest binds {b['path']} but no C snapshot "
                f"renderer emits '{leaf}' — stale manifest name (the "
                f"runtime gate would skip or fail the law)"))

    # the Python twin evaluates the SAME algebra without a csrc
    # checkout: token-identical or the two gates diverge
    py = _require(root, INVAR_PY_TWIN, "invar", f)
    if py is not None and text:
        twin = None
        try:
            tree = ast.parse(py)
        except SyntaxError as e:
            f.append(Finding("invar", INVAR_PY_TWIN, e.lineno or 0,
                             f"cannot parse: {e.msg}"))
            tree = None
        if tree is not None:
            for node in tree.body:
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        node.targets[0].id == "INVAR_MANIFEST":
                    try:
                        twin = ast.literal_eval(node.value)
                    except (ValueError, TypeError):
                        f.append(Finding(
                            "invar", INVAR_PY_TWIN, node.lineno,
                            "INVAR_MANIFEST is not a literal string"))
                    break
            if twin is None:
                f.append(Finding(
                    "invar", INVAR_PY_TWIN, 0,
                    "INVAR_MANIFEST twin string not found"))
            elif not isinstance(twin, str):
                f.append(Finding(
                    "invar", INVAR_PY_TWIN, 0,
                    "INVAR_MANIFEST twin is not a string"))
            else:
                ct, pt = text.split(), twin.split()
                if ct != pt:
                    idx = next((i for i, (a, bb) in
                                enumerate(zip(ct, pt)) if a != bb),
                               min(len(ct), len(pt)))
                    ctok = ct[idx] if idx < len(ct) else "<end>"
                    ptok = pt[idx] if idx < len(pt) else "<end>"
                    f.append(Finding(
                        "invar", INVAR_PY_TWIN, 0,
                        f"INVAR_MANIFEST drifts from the C manifest "
                        f"at token {idx}: C has '{ctok}', Python has "
                        f"'{ptok}' — the two runtime gates would "
                        f"evaluate different algebras"))
    return f


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

CHECKERS = {
    "abi": check_abi,
    "wire": check_wire,
    "stats": check_stats,
    "locks": check_locks,
    "net": check_net,
    "nullcheck": check_nullcheck,
    "trace": check_trace,
    "sync": check_sync,
    "fuzz": check_fuzz,
    "sched": check_sched,
    "invar": check_invar,
}


def run(root: str, names: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for name in names:
        findings.extend(CHECKERS[name](root))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default=REPO,
                    help="tree to check (default: this repo)")
    ap.add_argument("--check", action="append", choices=sorted(CHECKERS),
                    help="run only the named checker(s)")
    ap.add_argument("--list", action="store_true",
                    help="list checkers and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(CHECKERS):
            print(name)
        return 0

    names = args.check or sorted(CHECKERS)
    findings = run(os.path.abspath(args.root), names)
    if args.json:
        print(json.dumps([x.to_dict() for x in findings], indent=2))
    else:
        for x in findings:
            print(x)
        print(f"ptpu_check: {len(findings)} finding(s) from "
              f"{len(names)} checker(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
