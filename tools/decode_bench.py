#!/usr/bin/env python
"""Paged-KV continuous-batching generation engine bench (ISSUE r12;
r9 legs kept as guards).

The generation workload for the native serving stack: GPT-tiny decode
artifacts served through the C runtime's DECODE wire ops over the
paged KV engine (csrc/ptpu_predictor.cc KvPool + PtpuPagedAttention,
csrc/ptpu_serving.cc step-bucket ladder + chunked prefill + prefix
cache).

Legs:
  recompute   greedy generation via the FULL-SEQUENCE artifact — every
              token re-runs the whole fixed-shape [1, S] graph;
  kv_serving  greedy generation for N concurrent sessions over the
              wire (r01 GUARD leg: tokens/s within 10% of
              BENCH_DECODE_r01.json);
  parity      (a) one session's teacher-forced logits vs the full-seq
              graph, allclose at every position (r01 gate), and
              (b) NEW: the paged engine vs the r9 fixed-slot engine at
              every ladder step batch, EXACT (bit-identical) at every
              position;
  ramp        ≥ --ramp-sessions (default 1,000) CONCURRENT sessions
              held on one paged pool sized to the r9 fixed-slot
              engine's EXACT RAM envelope (64 slots x context), with
              aggregate tokens/s measured against that engine serving
              its 64-session maximum on the same artifact — the
              "≥3x at equal RAM" acceptance, plus peak-RSS and
              per-session-memory columns;
  prefix_ab   M server-side prefills of ONE shared prompt vs M
              distinct prompts: the shared-prompt wall time must be
              measurably lower (prefix-cache hit);
  int4        (ISSUE 16) fp32 vs PTPU_INT4=1 on one TRAINED decode
              artifact, alternating rounds: batch-1 GEMV decode
              tokens/s gated >= 1.5x with a measured QUALITY bound
              (max logits-delta + argmax agreement) instead of
              bitwise parity — int4 is lossy by design;
  tune        (ISSUE 16) persisted autotuning A/B in ctypes-only
              subprocesses (PTPU_TUNE latches per process): tuned
              configs gated >= 1.10x static tiles on a skinny-M MLP,
              and a warm tuning cache must make the second load's
              probe count/cost exactly zero. --int4-out persists
              these rows separately (BENCH_INT4_r01.json);
  kvtier      (ISSUE 19) KV tiering + session hibernation: (a) park
              --kvtier-sessions (default 100,000) conversations
              through the mmap'd spill tier while the pool's page
              gauges stay pinned (bounded-RSS claim, gauge-verified);
              (b) hibernate->restore logits EXACT vs an uninterrupted
              twin; (c) timed pool restores, p50/p99 resume latency;
              (d) restart-warm prefix cache — a fresh server's FIRST
              open must adopt at least as many prompt tokens as the
              old server's steady state; (e) tiering-OFF guard:
              attaching the spill tier must cost < 10% on the
              un-tiered decode path (interleaved rounds, r10 noise
              methodology). --kvtier-out persists these rows
              separately (BENCH_KVTIER_r01.json).

Run: python tools/decode_bench.py [--out BENCH_DECODE_rNN.json] [...]
(CPU-only; forces jax to CPU; uses the shipped .so.)
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# bench server Stop() doubles as a hard conservation gate (ISSUE 20)
os.environ.setdefault("PTPU_INVAR_FATAL", "1")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from drill_replay import host_meta  # noqa: E402  (one fingerprint impl)

RESULTS = []


def emit(rec):
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def rss_mb():
    """Current resident set (MB) — the server lives in-process."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return round(int(line.split()[1]) / 1024.0, 1)
    return -1.0


def peak_rss_mb():
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss /
                 1024.0, 1)


# ctypes-only one-shot predictor timer for the autotune A/B legs:
# PTPU_TUNE latches once per process, so every leg is its own
# subprocess — and skipping the jax import keeps a leg at process
# cost, not interpreter-warmup cost.
_TUNE_RUNNER = '''\
import ctypes, json, sys, time
import numpy as np

so, model, xpath, reps = sys.argv[1], sys.argv[2], sys.argv[3], \
    int(sys.argv[4])
lib = ctypes.CDLL(so)
c = ctypes
lib.ptpu_predictor_create.restype = c.c_void_p
lib.ptpu_predictor_create.argtypes = [c.c_char_p, c.c_char_p, c.c_int]
lib.ptpu_predictor_input_name.restype = c.c_char_p
lib.ptpu_predictor_input_name.argtypes = [c.c_void_p, c.c_int]
lib.ptpu_predictor_set_input.argtypes = [
    c.c_void_p, c.c_char_p, c.POINTER(c.c_float),
    c.POINTER(c.c_int64), c.c_int, c.c_char_p, c.c_int]
lib.ptpu_predictor_run.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
lib.ptpu_predictor_destroy.argtypes = [c.c_void_p]
lib.ptpu_tune_stats_json.restype = c.c_char_p

err = ctypes.create_string_buffer(512)
t0 = time.perf_counter()
h = lib.ptpu_predictor_create(model.encode(), err, 512)
create_s = time.perf_counter() - t0
assert h, err.value.decode()
x = np.load(xpath)
dims = (c.c_int64 * x.ndim)(*x.shape)

def once():
    rc = lib.ptpu_predictor_set_input(
        h, lib.ptpu_predictor_input_name(h, 0),
        x.ctypes.data_as(c.POINTER(c.c_float)), dims, x.ndim, err, 512)
    assert rc == 0, err.value.decode()
    rc = lib.ptpu_predictor_run(h, err, 512)
    assert rc == 0, err.value.decode()

for _ in range(3):
    once()
t0 = time.perf_counter()
for _ in range(reps):
    once()
run_ms = (time.perf_counter() - t0) / reps * 1e3
stats = json.loads(lib.ptpu_tune_stats_json().decode())
lib.ptpu_predictor_destroy(h)
print(json.dumps({"create_s": round(create_s, 4),
                  "run_ms_mean": round(run_ms, 4), "stats": stats}))
'''


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out")
    ap.add_argument("--sessions", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=48)
    ap.add_argument("--context", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    # ramp (equal-RAM A/B) leg
    ap.add_argument("--ramp-sessions", type=int, default=1000)
    ap.add_argument("--ramp-context", type=int, default=256)
    ap.add_argument("--ramp-batch", type=int, default=64)
    ap.add_argument("--ramp-rounds", type=int, default=8,
                    help="generated tokens per ramp session")
    ap.add_argument("--ramp-fixed-sessions", type=int, default=64,
                    help="the r9 engine's slot count (RAM envelope)")
    ap.add_argument("--prefix-opens", type=int, default=48)
    ap.add_argument("--prefix-prompt", type=int, default=48)
    ap.add_argument("--skip-ramp", action="store_true")
    # speculative-decoding A/B leg (r13)
    ap.add_argument("--spec-k", type=int,
                    default=int(os.environ.get("PTPU_SPEC_K", "4")),
                    help="draft proposals per round (verify width is "
                         "k+1); $PTPU_SPEC_K is the exporter-side twin")
    ap.add_argument("--spec-tokens", type=int, default=96,
                    help="greedy tokens generated per measured leg")
    ap.add_argument("--spec-rounds", type=int, default=4,
                    help="alternating A/B rounds (r10 noise "
                         "methodology: both legs per round, order "
                         "flipped each round, means reported)")
    ap.add_argument("--spec-train-steps", type=int, default=300,
                    help="Adam steps teaching target AND draft the "
                         "synthetic next-token rule (speculation "
                         "needs models that agree; random weights "
                         "would bench the disagreement path)")
    ap.add_argument("--spec-sample-opens", type=int, default=300,
                    help="seeded sampling draws for the distribution "
                         "gate")
    ap.add_argument("--skip-spec", action="store_true")
    # weight-only int4 + persisted-autotuning A/B legs (ISSUE 16)
    ap.add_argument("--int4-tokens", type=int, default=64,
                    help="greedy tokens per measured int4/fp32 leg")
    ap.add_argument("--int4-rounds", type=int, default=4,
                    help="alternating A/B rounds per leg pair (r10 "
                         "noise methodology)")
    ap.add_argument("--tune-reps", type=int, default=30,
                    help="timed predictor runs inside each autotune "
                         "subprocess leg")
    ap.add_argument("--int4-out",
                    help="persist the int4/autotune measurements to "
                         "this JSON (e.g. BENCH_INT4_r01.json)")
    ap.add_argument("--skip-int4", action="store_true")
    # KV tiering + session hibernation legs (ISSUE 19)
    ap.add_argument("--kvtier-sessions", type=int, default=100_000,
                    help="open conversations parked through the spill "
                         "tier in the bounded-RSS leg (smoke clamps "
                         "to 1,500)")
    ap.add_argument("--kvtier-resume-samples", type=int, default=512,
                    help="timed pool restores for the resume-latency "
                         "p50/p99 leg")
    ap.add_argument("--kvtier-ab-tokens", type=int, default=32,
                    help="greedy tokens per tiering-ON/OFF guard leg")
    ap.add_argument("--kvtier-ab-rounds", type=int, default=4,
                    help="alternating tier-ON/OFF rounds (r10 noise "
                         "methodology)")
    ap.add_argument("--kvtier-out",
                    help="persist the kvtier measurements to this "
                         "JSON (e.g. BENCH_KVTIER_r01.json)")
    ap.add_argument("--skip-kvtier", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="shrunken-config run: record everything, "
                         "never fail throughput gates (correctness "
                         "gates still fail the run)")
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import inference
    from paddle_tpu.core.native import NativePredictor
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       export_gpt_decode, gpt_tiny)
    from paddle_tpu.onnx.converter import trace_to_onnx

    assert args.tokens <= args.context

    pt.seed(0)
    cfg = gpt_tiny(dtype=jnp.float32, dropout=0.0)
    model = GPTForPretraining(cfg)
    model.eval()
    h, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    kv_row_bytes = 2 * cfg.num_layers * h * hd * 4  # k+v, all layers

    ok = True
    with tempfile.TemporaryDirectory() as tmp:
        dec_path = export_gpt_decode(model, os.path.join(tmp, "dec"),
                                     batch=args.batch,
                                     context=args.context)
        S = args.tokens  # full-seq artifact sized to the generation
        full_bytes = trace_to_onnx(lambda ids: model(ids),
                                   (jnp.zeros((1, S), jnp.int32),))
        full_path = os.path.join(tmp, "full.onnx")
        with open(full_path, "wb") as f:
            f.write(full_bytes)

        prompt = 7  # fixed prompt token; everything after is greedy

        # ---- leg 1: full-prefix recompute baseline -----------------
        def recompute_generate(steps):
            toks = np.zeros((1, S), np.int32)
            toks[0, 0] = prompt
            out = [prompt]
            with NativePredictor(full_path) as p:
                name = p.input_name(0)
                p.set_input(name, toks)
                p.run()  # warmup/load
                t0 = time.perf_counter()
                for t in range(steps - 1):
                    p.set_input(name, toks)
                    p.run()
                    lg = p.output(0)[0, t]
                    nxt = int(np.argmax(lg))
                    out.append(nxt)
                    toks[0, t + 1] = nxt
                dt = time.perf_counter() - t0
            return out, (steps - 1) / dt

        rc_tokens, rc_tps = recompute_generate(args.tokens)
        emit({"metric": "recompute_tokens_per_s",
              "value": round(rc_tps, 1), "unit": "tokens/s",
              "seq": S, "note": "full [1,S] graph re-run per token"})

        # ---- leg 2: KV decode through the serving wire (r01 guard) -
        srv = inference.create_server(
            full_path, max_batch=2, instances=1,
            decode_model=dec_path, kv_sessions=args.sessions + 2)
        cli = srv.client()
        meta = srv.config()
        assert meta["decode"]["batch"] == args.batch
        assert meta["decode"]["paged"] == 1
        sess = [cli.decode_open() for _ in range(args.sessions)]
        cur = [prompt] * args.sessions
        t0 = time.perf_counter()
        for _ in range(args.tokens - 1):
            outs = cli.decode_step_many(
                [(sess[i], cur[i]) for i in range(args.sessions)])
            for i in range(args.sessions):
                cur[i] = int(np.argmax(outs[i]))
        dt = time.perf_counter() - t0
        kv_steps = args.sessions * (args.tokens - 1)
        kv_tps = kv_steps / dt
        st = srv.stats()["decode"]
        emit({"metric": "kv_decode_tokens_per_s",
              "value": round(kv_tps, 1), "unit": "tokens/s",
              "sessions": args.sessions, "batch": args.batch,
              "context": args.context, "engine": "paged+direct",
              "direct": meta["decode"]["direct"],
              "step_buckets": meta["decode"]["step_buckets"],
              "batches": st["batches"],
              "mean_fill": round(kv_steps / max(st["batches"], 1), 2)})

        # session/page ledger balance (opens == closes + evictions +
        # live, page conservation, ...) is the declarative invar
        # gate's job; the bench keeps client-vs-server cross-checks
        from paddle_tpu.profiler.stats import invar_assert
        invar_assert(srv.stats(), "decode_bench_kv_leg")
        counters_exact = (st["steps"] == kv_steps and
                          st["replies"] == kv_steps and
                          st["opens"] == args.sessions and
                          st["evictions"] == 0)
        emit({"metric": "decode_counters_exact",
              "value": bool(counters_exact),
              "server": {k: st[k] for k in
                         ("steps", "replies", "opens", "evictions")},
              "client_steps": kv_steps})

        # ---- parity (a): teacher-forced vs full-seq, allclose ------
        ps = cli.decode_open()
        kv_logits = [np.asarray(cli.decode_step(ps, rc_tokens[t]))
                     for t in range(args.tokens - 1)]
        cli.decode_close(ps)
        with NativePredictor(full_path) as p:
            name = p.input_name(0)
            toks = np.zeros((1, S), np.int32)
            toks[0, :len(rc_tokens)] = rc_tokens
            p.set_input(name, toks)
            p.run()
            full_logits = p.output(0)[0]
        logits_close = all(
            bool(np.allclose(kv_logits[t], full_logits[t],
                             rtol=2e-3, atol=2e-4))
            for t in range(args.tokens - 1))
        emit({"metric": "decode_parity",
              "value": bool(logits_close),
              "teacher_forced_steps": args.tokens - 1})

        for s in sess:
            cli.decode_close(s)
        cli.close()
        srv.stop()

        # ---- parity (b): paged vs fixed-slot, EXACT per bucket -----
        from paddle_tpu.core.native import KvPool
        exact_all = True
        bucket = 1
        while bucket <= args.batch and exact_all:
            pool = KvPool(pool_tokens=4 * args.batch * args.context,
                          page_tokens=16, max_sessions=64)
            kwa = {} if bucket == args.batch else \
                {"batch_override": bucket}
            pg = NativePredictor(dec_path, **kwa)
            pg.kv_attach(pool)
            up = NativePredictor(dec_path, **kwa)
            up.kv_plan(args.batch)
            psd = [pool.open() for _ in range(bucket)]
            usd = [up.kv_open() for _ in range(bucket)]
            rng = np.random.RandomState(bucket)
            for t in range(args.tokens - 1):
                tk = rng.randint(0, cfg.vocab_size, size=bucket)
                lp = pg.decode_step(psd, tk)
                lu = up.decode_step(usd, tk)
                if not np.array_equal(lp, lu):
                    exact_all = False
                    break
            pool.close()
            bucket *= 2
        emit({"metric": "decode_parity_exact_paged_vs_fixed",
              "value": bool(exact_all),
              "note": "bit-identical logits at every teacher-forced "
                      "position, every ladder step batch"})

        # ---- leg 3: equal-RAM ramp A/B -----------------------------
        ramp = {}
        if not args.skip_ramp:
            rs, rc_, rb = (args.ramp_sessions, args.ramp_context,
                           args.ramp_batch)
            fixed_n = args.ramp_fixed_sessions
            # the ramp context may exceed gpt_tiny's position table:
            # the ramp model is its own instance with room to spare
            # (the decode artifact is self-contained — the INFER-plane
            # model is unrelated)
            cfg_r = gpt_tiny(dtype=jnp.float32, dropout=0.0,
                             max_position_embeddings=2 * rc_)
            model_r = GPTForPretraining(cfg_r)
            model_r.eval()
            dec64 = export_gpt_decode(model_r,
                                      os.path.join(tmp, "dec64"),
                                      batch=rb, context=rc_)
            rounds = args.ramp_rounds
            # one full shared page + one token: adoption covers the
            # page (the LAST prompt token must always be stepped), so
            # a warm open computes exactly one step
            sys_prompt = list(range(11, 11 + 17))

            def drive(n_sessions, env, label):
                for k, v in env.items():
                    os.environ[k] = v
                try:
                    sv = inference.create_server(
                        full_path, max_batch=2, instances=1,
                        decode_model=dec64)
                finally:
                    for k in env:
                        del os.environ[k]
                c = sv.client()
                m = sv.config()["decode"]
                rss0 = rss_mb()
                # one seed open publishes the shared prompt page, the
                # rest prefill CONCURRENTLY (pipelined OPEN2): warm
                # opens adopt the page and compute one step each
                t_open0 = time.perf_counter()
                seed, _, _ = c.decode_open(prompt=sys_prompt,
                                           timeout=300.0)
                opened = c.decode_open_many(
                    [sys_prompt] * (n_sessions - 1), timeout=300.0)
                ss = [seed] + [o[0] for o in opened]
                t_open = time.perf_counter() - t_open0
                cur = [3] * n_sessions
                # steady-state: every session generates `rounds`
                # tokens, steps pipelined across all sessions
                t0 = time.perf_counter()
                done = 0
                for _ in range(rounds):
                    outs = c.decode_step_many(
                        [(ss[i], cur[i]) for i in range(n_sessions)],
                        return_exceptions=True)
                    for i, o in enumerate(outs):
                        if isinstance(o, Exception):
                            continue
                        cur[i] = int(np.argmax(o))
                        done += 1
                dt = time.perf_counter() - t0
                std = sv.stats()["decode"]
                pool_st = std.get("pool", {})
                held = pool_st.get("sessions_active", len(ss))
                kv_bytes = (pool_st.get("pages_in_use", 0) *
                            pool_st.get("page_tokens", 0) *
                            kv_row_bytes)
                if not pool_st:   # fixed-slot engine: the whole slab
                    kv_bytes = (fixed_n * rc_ * kv_row_bytes)
                serviced = done + len(sys_prompt) * n_sessions
                rec = {
                    "engine": label,
                    "sessions_held": int(held),
                    "tokens_per_s": round(done / dt, 1),
                    "tokens_generated": done,
                    # end-to-end: prompt tokens serviced (computed or
                    # adopted from the prefix cache) + generated, over
                    # the full open+generate wall — the generation-
                    # engine throughput a client actually observes
                    "tokens_serviced": serviced,
                    "serviced_tokens_per_s": round(
                        serviced / (t_open + dt), 1),
                    "open_prefill_s": round(t_open, 2),
                    "steady_s": round(dt, 2),
                    "step_buckets": m["step_buckets"],
                    "kv_ram_mb": round(kv_bytes / 1e6, 1),
                    "kv_ram_budget_mb": round(
                        fixed_n * rc_ * kv_row_bytes / 1e6, 1),
                    "rss_before_mb": rss0,
                    "rss_after_mb": rss_mb(),
                    "per_session_kv_bytes": int(kv_bytes /
                                                max(held, 1)),
                    "pool": pool_st,
                    "exhausted": std.get("pool_exhausted", 0),
                }
                for s in ss:
                    try:
                        c.decode_close(s)
                    except Exception:
                        pass
                c.close()
                sv.stop()
                return rec

            # r9 fixed-slot engine at its 64-session max (the RAM
            # envelope both legs share: 64 slots x full context)
            ramp_fixed = drive(
                fixed_n,
                {"PTPU_KV_PAGED": "0",
                 "PTPU_KV_SESSIONS": str(fixed_n)},
                "fixed64")
            emit({"metric": "ramp_fixed_engine", **ramp_fixed})
            # paged engine: SAME RAM in pages, >= 1,000 sessions
            ramp_paged = drive(
                rs,
                {"PTPU_KV_POOL_TOKENS": str(fixed_n * rc_),
                 "PTPU_KV_SESSIONS": str(rs + 8)},
                "paged")
            emit({"metric": "ramp_paged_engine", **ramp_paged})
            gen_ratio = (ramp_paged["tokens_per_s"] /
                         max(ramp_fixed["tokens_per_s"], 1e-9))
            e2e_ratio = (ramp_paged["serviced_tokens_per_s"] /
                         max(ramp_fixed["serviced_tokens_per_s"],
                             1e-9))
            ramp = {
                "sessions_held": ramp_paged["sessions_held"],
                "ratio": round(e2e_ratio, 2),
                "steady_ratio": round(gen_ratio, 2),
                "equal_ram": ramp_paged["kv_ram_mb"] <=
                ramp_paged["kv_ram_budget_mb"] * 1.01,
                "peak_rss_mb": peak_rss_mb(),
            }
            emit({"metric": "ramp_paged_over_fixed_equal_ram",
                  "value": ramp["ratio"], "unit": "x",
                  "note": "end-to-end serviced tokens/s (prompt "
                          "prefill incl. prefix-cache hits + "
                          "generation); steady_ratio is the "
                          "generation-only phase",
                  "steady_ratio": ramp["steady_ratio"],
                  "acceptance_gate": 3.0,
                  "sessions_gate": rs,
                  "sessions_held": ramp["sessions_held"],
                  "equal_ram": ramp["equal_ram"],
                  "peak_rss_mb": ramp["peak_rss_mb"],
                  "within_gate": bool(
                      ramp["ratio"] >= 3.0 and
                      ramp["sessions_held"] >= rs and
                      ramp["equal_ram"])})
            ok = ok and ramp["ratio"] >= 3.0 and \
                ramp["sessions_held"] >= rs and ramp["equal_ram"]

        # ---- leg 4: prefix-cache A/B (shared vs distinct prompts) --
        srv = inference.create_server(
            full_path, max_batch=2, instances=1, decode_model=dec_path,
            kv_sessions=4 * args.prefix_opens)
        cli = srv.client()
        plen = min(args.prefix_prompt, args.context - 2)
        shared = list(range(5, 5 + plen))
        rng = np.random.RandomState(7)
        t0 = time.perf_counter()
        warm = cli.decode_open(prompt=shared, timeout=120.0)  # seed
        t_seed = time.perf_counter() - t0
        ss = []
        t0 = time.perf_counter()
        for _ in range(args.prefix_opens):
            s, _, ad = cli.decode_open(prompt=shared, timeout=120.0)
            ss.append(s)
        t_shared = time.perf_counter() - t0
        st = srv.stats()["decode"]
        shared_adopted = st["prefill_adopted"]
        for s in ss + [warm[0]]:
            cli.decode_close(s)
        ss = []
        t0 = time.perf_counter()
        for _ in range(args.prefix_opens):
            p_i = rng.randint(0, cfg.vocab_size, size=plen).tolist()
            s, _, _ = cli.decode_open(prompt=p_i, timeout=120.0)
            ss.append(s)
        t_distinct = time.perf_counter() - t0
        for s in ss:
            cli.decode_close(s)
        cli.close()
        srv.stop()
        speedup = t_distinct / max(t_shared, 1e-9)
        prefix_ok = t_shared < t_distinct and shared_adopted > 0
        emit({"metric": "prefix_cache_ab",
              "shared_open_s": round(t_shared, 3),
              "distinct_open_s": round(t_distinct, 3),
              "seed_open_s": round(t_seed, 3),
              "opens": args.prefix_opens, "prompt_tokens": plen,
              "adopted_tokens_shared": int(shared_adopted),
              "value": round(speedup, 2), "unit": "x",
              "within_gate": bool(prefix_ok)})
        ok = ok and prefix_ok

        # ---- leg 5: speculative decoding A/B (ISSUE 13) ------------
        # Draft/verify speculation vs plain decode on the SAME target
        # export, one server serving both planes, interleaved
        # alternating rounds (the r10 noise methodology). Both models
        # are first TRAINED to a synthetic affine next-token rule
        # (next = (5x + 7) % V) — a pure unigram relation even the
        # 1-layer draft memorizes — because speculation pays off
        # exactly when draft and target agree; random weights would
        # bench the rejection path.
        # The spec AND int4 legs share one trained target: quality
        # metrics (acceptance rate, logits agreement) are meaningless
        # on random weights, whose near-flat logits make every argmax
        # a coin flip.
        tgt, loss_t = None, None
        if not (args.skip_spec and args.skip_int4):
            import jax
            from paddle_tpu.nn.layer import (functional_call,
                                             load_state,
                                             trainable_state)

            V = cfg.vocab_size

            def make_batch(rs, bsz, seq):
                arr = np.empty((bsz, seq + 1), np.int64)
                arr[:, 0] = rs.randint(0, V, size=bsz)
                for t in range(seq):
                    arr[:, t + 1] = (5 * arr[:, t] + 7) % V
                return (arr[:, :-1].astype(np.int32),
                        arr[:, 1:].astype(np.int32))

            def train(model_t, steps, seed):
                params = trainable_state(model_t)

                def loss_fn(p, ids, labels):
                    out, _ = functional_call(model_t, p, ids, labels)
                    return out

                vg = jax.jit(jax.value_and_grad(loss_fn))
                lr, b1, b2, eps = 2e-3, 0.9, 0.999, 1e-8

                @jax.jit
                def adam(p, m, v, g, t):
                    m = jax.tree.map(
                        lambda a, b: b1 * a + (1 - b1) * b, m, g)
                    v = jax.tree.map(
                        lambda a, b: b2 * a + (1 - b2) * b * b, v, g)
                    p = jax.tree.map(
                        lambda a, x, y: a - lr * (x / (1 - b1 ** t)) /
                        (jnp.sqrt(y / (1 - b2 ** t)) + eps),
                        p, m, v)
                    return p, m, v

                m = jax.tree.map(jnp.zeros_like, params)
                v = jax.tree.map(jnp.zeros_like, params)
                rs = np.random.RandomState(seed)
                loss = None
                for t in range(1, steps + 1):
                    ids, lab = make_batch(rs, 16, 32)
                    loss, g = vg(params, jnp.asarray(ids),
                                 jnp.asarray(lab))
                    params, m, v = adam(params, m, v, g, float(t))
                load_state(model_t, params)
                return float(loss)

            pt.seed(101)
            cfg_s = gpt_tiny(dtype=jnp.float32, dropout=0.0)
            tgt = GPTForPretraining(cfg_s)
            tgt.eval()
            loss_t = train(tgt, args.spec_train_steps, 1)

        if not args.skip_spec:
            k = args.spec_k
            sctx = 120
            N = min(args.spec_tokens, sctx - 8 - k - 2)
            pt.seed(202)
            dcfg = gpt_tiny(dtype=jnp.float32, dropout=0.0,
                            hidden_size=32, num_layers=1, num_heads=2)
            drf_m = GPTForPretraining(dcfg)
            drf_m.eval()
            loss_d = train(drf_m, args.spec_train_steps, 2)
            spec_dec = export_gpt_decode(tgt, os.path.join(tmp, "sdec"),
                                         batch=args.batch, context=sctx)
            spec_ver = export_gpt_decode(tgt, os.path.join(tmp, "sver"),
                                         batch=args.batch, context=sctx,
                                         width=k + 1)
            spec_drf = export_gpt_decode(drf_m,
                                         os.path.join(tmp, "sdrf"),
                                         batch=args.batch, context=sctx)
            srv = inference.create_server(
                full_path, max_batch=2, instances=1,
                decode_model=spec_dec, spec_model=spec_drf,
                spec_verify_model=spec_ver, kv_sessions=64)
            cli = srv.client()
            smeta = srv.config()["decode"]["spec"]
            assert smeta["k"] == k
            prompt = [int(x) for x in (np.arange(4) * 97 + 13) % V]

            def leg_nospec(nsess):
                if nsess == 1:
                    s, lg, _ = cli.decode_open(prompt=prompt)
                    toks = [int(np.argmax(lg))]
                    t0 = time.perf_counter()
                    while len(toks) < N:
                        toks.append(int(np.argmax(
                            cli.decode_step(s, toks[-1]))))
                    dt = time.perf_counter() - t0
                    cli.decode_close(s)
                    return toks[:N], (N - 1) / dt
                opened = cli.decode_open_many([prompt] * nsess,
                                              timeout=120.0)
                ss = [o[0] for o in opened]
                cur = [int(np.argmax(o[1])) for o in opened]
                done = 0
                t0 = time.perf_counter()
                for _ in range(N - 1):
                    outs = cli.decode_step_many(
                        [(ss[i], cur[i]) for i in range(nsess)])
                    for i in range(nsess):
                        cur[i] = int(np.argmax(outs[i]))
                        done += 1
                dt = time.perf_counter() - t0
                for s in ss:
                    cli.decode_close(s)
                return None, done / dt

            def leg_spec(nsess):
                if nsess == 1:
                    s, t1, _ = cli.spec_open(prompt)
                    toks = list(t1)
                    t0 = time.perf_counter()
                    while len(toks) < N:
                        t, _a = cli.spec_step(s)
                        toks.extend(t)
                    dt = time.perf_counter() - t0
                    gen = len(toks) - len(t1)
                    cli.decode_close(s)
                    return toks[:N], gen / dt
                ss = [cli.spec_open(prompt)[0] for _ in range(nsess)]
                need = [N - 1] * nsess
                done = 0
                t0 = time.perf_counter()
                while any(n > 0 for n in need):
                    live = [i for i in range(nsess) if need[i] > 0]
                    outs = cli.spec_step_many([ss[i] for i in live])
                    for i, (t, _a) in zip(live, outs):
                        need[i] -= len(t)
                        done += len(t)
                dt = time.perf_counter() - t0
                for s in ss:
                    cli.decode_close(s)
                return None, done / dt

            # greedy parity: spec tokens byte-identical to plain greedy
            ref_toks, _ = leg_nospec(1)
            spec_toks, _ = leg_spec(1)
            parity = spec_toks == ref_toks
            emit({"metric": "spec_greedy_parity", "value": bool(parity),
                  "tokens": N,
                  "train_loss_target": round(loss_t, 4),
                  "train_loss_draft": round(loss_d, 4)})

            # sorted-set keys: --sessions 1 must not collapse the two
            # legs into one dict slot (double-appending per round)
            ab = {n: {"spec": [], "nospec": []}
                  for n in sorted({1, args.sessions})}
            for rnd in range(args.spec_rounds):
                for nsess in ab:
                    legs = [("spec", leg_spec), ("nospec", leg_nospec)]
                    if rnd % 2:
                        legs.reverse()
                    for name, fn in legs:
                        ab[nsess][name].append(fn(nsess)[1])
            st = srv.stats()["decode"]
            accept_rate = st["spec_accepted"] / max(st["spec_proposed"],
                                                    1)
            tokens_per_round = st["spec_tokens"] / max(st["spec_rounds"],
                                                       1)
            recs = {}
            for nsess, d in ab.items():
                sm = float(np.mean(d["spec"]))
                nm = float(np.mean(d["nospec"]))
                recs[nsess] = (sm, nm)
                emit({"metric": f"spec_ab_tokens_per_s_{nsess}s",
                      "sessions": nsess,
                      "spec_tokens_per_s": round(sm, 1),
                      "nospec_tokens_per_s": round(nm, 1),
                      "value": round(sm / nm, 2), "unit": "x",
                      "spec_rounds_per_leg": args.spec_rounds,
                      "per_round_spec": [round(x, 1)
                                         for x in d["spec"]],
                      "per_round_nospec": [round(x, 1)
                                           for x in d["nospec"]]})
            spec_ratio_1s = recs[1][0] / recs[1][1]
            emit({"metric": "spec_accept_rate",
                  "value": round(accept_rate, 3), "k": k,
                  "tokens_per_round": round(tokens_per_round, 2),
                  "spec_rounds": st["spec_rounds"],
                  "spec_draft_steps": st["spec_draft_steps"],
                  "spec_fallbacks": st["spec_fallbacks"],
                  "acceptance_gate": 0.60,
                  "within_gate": bool(accept_rate >= 0.60)})
            emit({"metric": "spec_speedup_single_session",
                  "value": round(spec_ratio_1s, 2), "unit": "x",
                  "acceptance_gate": 1.8,
                  "within_gate": bool(spec_ratio_1s >= 1.8)})

            # seeded sampling: deterministic per seed, and the
            # empirical first-token distribution over M seeds matches
            # softmax(target logits) — the non-speculative sampler's
            # distribution (TV gate; the modified-rejection rule
            # itself is statistically gated in the C selftest)
            sref, lgp, _ = cli.decode_open(prompt=prompt)
            cli.decode_close(sref)
            lp = np.asarray(lgp, np.float64)
            p = np.exp(lp - lp.max())
            p /= p.sum()
            M = args.spec_sample_opens
            emp = np.zeros_like(p)
            for sd in range(M):
                s, t1, _ = cli.spec_open(prompt, seed=sd + 1,
                                         sample=True)
                emp[t1[0]] += 1.0 / M
                cli.decode_close(s)
            tv = 0.5 * float(np.abs(emp - p).sum())
            det = []
            for _ in range(2):
                s, t1, _ = cli.spec_open(prompt, seed=4242,
                                         sample=True)
                seq = list(t1)
                while len(seq) < 12:
                    seq.extend(cli.spec_step(s)[0])
                cli.decode_close(s)
                det.append(seq[:12])
            # smoke runs barely train the models, so the first-token
            # distribution is broad and M draws cannot pin it: gate
            # determinism only there, the TV distance on full runs
            sampling_ok = det[0] == det[1] and \
                (args.smoke or tv <= 0.15)
            emit({"metric": "spec_sampling_distribution",
                  "tv_distance": round(tv, 4), "opens": M,
                  "deterministic": bool(det[0] == det[1]),
                  "value": bool(sampling_ok), "tv_gate": 0.15})

            cli.close()
            srv.stop()
            ok = ok and parity and sampling_ok
            if not args.smoke:
                ok = ok and spec_ratio_1s >= 1.8 and accept_rate >= 0.60

        # ---- leg 6: weight-only int4 A/B + quality gate (ISSUE 16) -
        # fp32 vs PTPU_INT4=1 on the SAME trained decode artifact,
        # loaded side by side (the knob is read per load), alternating
        # rounds. The headline gate is the batch-1 GEMV decode — the
        # shape where 8x less weight traffic must buy >= 1.5x — and the
        # first NON-BITWISE gate in this repo: int4 is lossy, so the
        # bound is measured quality (max logits-delta + greedy argmax
        # agreement on the trained model), not parity.
        if not args.skip_int4:
            import subprocess as sp

            ictx = 120
            itok = min(args.int4_tokens, ictx - 2)
            iprompt = 7
            # quality runs on the TRAINED gpt_tiny (peaked logits make
            # argmax agreement meaningful); throughput runs on a
            # SERVING-SCALE variant — gpt_tiny's ~0.9 MB of weights
            # live entirely in cache, where the GEMV is never weight-
            # bandwidth-bound and int4's 8x traffic cut can't show.
            # h=256/v=2048 is ~15 MB fp32: past L2, the shape the
            # claim is about. Training it would add nothing (wall
            # time is weight-shape-bound, not value-bound).
            idec1 = export_gpt_decode(tgt, os.path.join(tmp, "i4dec1"),
                                      batch=1, context=ictx)
            pt.seed(44)
            cfg_big = gpt_tiny(dtype=jnp.float32, dropout=0.0,
                               hidden_size=256, vocab_size=2048)
            big = GPTForPretraining(cfg_big)
            big.eval()
            bdec1 = export_gpt_decode(big, os.path.join(tmp, "i4big1"),
                                      batch=1, context=ictx)
            bdecB = export_gpt_decode(big, os.path.join(tmp, "i4bigB"),
                                      batch=args.batch, context=ictx)

            def load_dec(path, slots, int4):
                if int4:
                    os.environ["PTPU_INT4"] = "1"
                try:
                    p = NativePredictor(path)
                finally:
                    os.environ.pop("PTPU_INT4", None)
                p.kv_plan(slots)
                return p

            def gen_tps(p, nsess, steps):
                ss = [p.kv_open() for _ in range(nsess)]
                cur = [iprompt] * nsess
                t0 = time.perf_counter()
                for _ in range(steps):
                    lg = p.decode_step(ss, cur)
                    cur = [int(np.argmax(lg[i])) for i in range(nsess)]
                dt = time.perf_counter() - t0
                for s in ss:
                    p.kv_close(s)
                return nsess * steps / dt

            q32 = load_dec(idec1, 1, False)
            qq = load_dec(idec1, 1, True)
            p32_1 = load_dec(bdec1, 1, False)
            pq_1 = load_dec(bdec1, 1, True)
            p32_B = load_dec(bdecB, args.batch, False)
            pq_B = load_dec(bdecB, args.batch, True)

            # quality: teacher-forced on the fp32 greedy stream
            s32, sq = q32.kv_open(), qq.kv_open()
            toks, agree, mld, lmax = [iprompt], 0, 0.0, 0.0
            for _ in range(itok - 1):
                l32 = q32.decode_step([s32], [toks[-1]])[0]
                lq = qq.decode_step([sq], [toks[-1]])[0]
                mld = max(mld, float(np.max(np.abs(lq - l32))))
                lmax = max(lmax, float(np.max(np.abs(l32))))
                agree += int(np.argmax(lq)) == int(np.argmax(l32))
                toks.append(int(np.argmax(l32)))
            q32.kv_close(s32)
            qq.kv_close(sq)
            q32.close()
            qq.close()
            agreement = agree / (itok - 1)
            rel_delta = mld / max(lmax, 1e-12)
            quality_ok = agreement >= 0.95 and rel_delta <= 0.10
            emit({"metric": "int4_quality_vs_fp32",
                  "argmax_agreement": round(agreement, 4),
                  "max_logits_delta": round(mld, 5),
                  "max_logits_delta_rel": round(rel_delta, 5),
                  "teacher_forced_steps": itok - 1,
                  "train_loss_target": round(loss_t, 4),
                  "agreement_gate": 0.95, "rel_delta_gate": 0.10,
                  "value": bool(quality_ok),
                  "note": "smoke models are barely trained: the gate "
                          "binds only on the full run"})

            # throughput: alternating rounds, order flipped each round
            iab = {"b1": {"fp32": [], "int4": []},
                   "bN": {"fp32": [], "int4": []}}
            sizes = (("b1", 1),) if args.batch == 1 else \
                (("b1", 1), ("bN", args.batch))
            for p in (p32_1, pq_1):
                gen_tps(p, 1, 4)   # warm the lazy first-step paths
            for rnd in range(args.int4_rounds):
                legs = [("int4", pq_1, pq_B), ("fp32", p32_1, p32_B)]
                if rnd % 2:
                    legs.reverse()
                for name, p1, pB in legs:
                    iab["b1"][name].append(gen_tps(p1, 1, itok))
                    if len(sizes) > 1:
                        iab["bN"][name].append(
                            gen_tps(pB, args.batch, itok))
            for p in (p32_1, pq_1, p32_B, pq_B):
                p.close()
            i4_ratio = 0.0
            for lbl, nsess in sizes:
                qm = float(np.mean(iab[lbl]["int4"]))
                fm = float(np.mean(iab[lbl]["fp32"]))
                r = qm / fm
                if lbl == "b1":
                    i4_ratio = r
                emit({"metric": f"int4_ab_tokens_per_s_{nsess}s",
                      "sessions": nsess,
                      "model": "gpt_tiny(h=256,v=2048) ~15MB fp32",
                      "int4_tokens_per_s": round(qm, 1),
                      "fp32_tokens_per_s": round(fm, 1),
                      "value": round(r, 2), "unit": "x",
                      "rounds": args.int4_rounds,
                      "per_round_int4": [round(x, 1)
                                         for x in iab[lbl]["int4"]],
                      "per_round_fp32": [round(x, 1)
                                         for x in iab[lbl]["fp32"]],
                      **({"acceptance_gate": 1.5,
                          "within_gate": bool(r >= 1.5)}
                         if lbl == "b1" else {})})

            # ---- leg 7: persisted autotuning A/B + warm-cache probe
            # cost. PTPU_TUNE is latched once per process, so each leg
            # is a ctypes-only subprocess (no jax import). The shape
            # is chosen where the row-GEMV alt path wins STRUCTURALLY,
            # not by measurement luck: M=2 pads the MR=6 register tile
            # to 3x the useful FMAs, and 320x320 weights (1.6MB for 4
            # layers) stay L2-resident so the GEMM is compute-bound —
            # padding waste is the bill, not memory bandwidth. (A
            # DRAM-bound shape hides the waste entirely: the 15MB
            # int4-leg MLP measures ~1.0x here no matter the config.)
            # Rounds alternate tuned/untuned processes; the warm-cache
            # contract (second load probes NOTHING) is exact and gates
            # even the smoke run.
            pt.seed(33)
            tnet = pt.nn.Sequential(
                pt.nn.Linear(320, 320), pt.nn.ReLU(),
                pt.nn.Linear(320, 320), pt.nn.ReLU(),
                pt.nn.Linear(320, 320), pt.nn.ReLU(),
                pt.nn.Linear(320, 320))
            tnet.eval()
            xt = np.random.RandomState(33).randn(2, 320).astype(
                np.float32)
            tmlp = os.path.join(tmp, "tune_mlp.onnx")
            with open(tmlp, "wb") as f:
                f.write(trace_to_onnx(lambda a: tnet(a),
                                      (jnp.asarray(xt),)))
            xt_path = os.path.join(tmp, "tune_x.npy")
            np.save(xt_path, xt)
            runner = os.path.join(tmp, "tune_runner.py")
            with open(runner, "w") as f:
                f.write(_TUNE_RUNNER)
            so = os.path.join(REPO, "paddle_tpu",
                              "_native_predictor.so")
            cache = os.path.join(tmp, "tune.cache")

            def tune_leg(tuned):
                env = dict(os.environ)
                env.pop("PTPU_TUNE", None)
                if tuned:
                    env.update({"PTPU_TUNE": "1",
                                "PTPU_TUNE_CACHE": cache})
                r = sp.run([sys.executable, runner, so, tmlp, xt_path,
                            str(args.tune_reps)], env=env,
                           capture_output=True, text=True, timeout=300)
                assert r.returncode == 0, r.stderr[-2000:]
                return json.loads(r.stdout.strip().splitlines()[-1])

            cold = tune_leg(True)   # probes fire + cache persists
            base_ms, tuned_ms = [], []
            warm = None
            for rnd in range(args.int4_rounds):
                legs = [("tuned", True), ("base", False)]
                if rnd % 2:
                    legs.reverse()
                for name, tn_on in legs:
                    rec = tune_leg(tn_on)
                    if name == "tuned":
                        tuned_ms.append(rec["run_ms_mean"])
                        warm = rec
                    else:
                        base_ms.append(rec["run_ms_mean"])
            win = float(np.mean(base_ms)) / float(np.mean(tuned_ms))
            emit({"metric": "autotune_gemm_win",
                  "value": round(win, 3), "unit": "x",
                  "shape": "MLP [2,320]x[320,320] x4 layers, "
                           "L2-resident (skinny-M: the 6-row tile "
                           "pads M=2 to 3x the useful FMAs)",
                  "base_ms": round(float(np.mean(base_ms)), 3),
                  "tuned_ms": round(float(np.mean(tuned_ms)), 3),
                  "per_round_base_ms": [round(x, 3) for x in base_ms],
                  "per_round_tuned_ms": [round(x, 3)
                                         for x in tuned_ms],
                  "acceptance_gate": 1.10,
                  "within_gate": bool(win >= 1.10)})
            warm_ok = (warm["stats"]["probes"] == 0 and
                       warm["stats"]["probe_us"] == 0 and
                       warm["stats"]["file_loads"] == 1 and
                       cold["stats"]["probes"] > 0 and
                       cold["stats"]["saves"] >= 1)
            emit({"metric": "tune_warm_cache_probe_cost",
                  "value": bool(warm_ok),
                  "cold_probes": cold["stats"]["probes"],
                  "cold_probe_us": cold["stats"]["probe_us"],
                  "cold_create_s": cold["create_s"],
                  "warm_probes": warm["stats"]["probes"],
                  "warm_probe_us": warm["stats"]["probe_us"],
                  "warm_create_s": warm["create_s"],
                  "warm_file_entries": warm["stats"]["file_entries"],
                  "note": "exact contract: a warm cache skips every "
                          "probe, at any scale"})

            ok = ok and warm_ok
            if not args.smoke:
                ok = ok and quality_ok and i4_ratio >= 1.5 and \
                    win >= 1.10

            if args.int4_out:
                i4_metrics = [m for m in RESULTS
                              if m["metric"].startswith(
                                  ("int4_", "autotune_", "tune_"))]
                with open(args.int4_out, "w") as f:
                    json.dump({"bench": "int4_tune_bench",
                               "host": host_meta(),
                               "config": vars(args),
                               "measurements": i4_metrics}, f,
                              indent=1)
                print(f"# persisted int4 legs to {args.int4_out}",
                      flush=True)

        # ---- leg 8: KV tiering + session hibernation (ISSUE 19) ----
        kvtier_correct = True
        if not args.skip_kvtier:
            n_tier = args.kvtier_sessions
            resume_n = args.kvtier_resume_samples
            ab_rounds = args.kvtier_ab_rounds
            ab_tokens = args.kvtier_ab_tokens
            if args.smoke:
                n_tier = min(n_tier, 1500)
                resume_n = min(resume_n, 64)
                ab_rounds, ab_tokens = (min(ab_rounds, 2),
                                        min(ab_tokens, 12))

            # (a) park n_tier conversations at bounded RSS: cycles of
            # one batched decode step (every session holds REAL kv)
            # then per-session hibernate.  page_tokens=2 keeps a
            # 1-token session at ONE group, so the spill file — not
            # the pool — carries the population; the pool never holds
            # more than 64 groups / 2*batch sessions, and the gauges
            # prove it.
            page = 2
            group_mb = page * kv_row_bytes / 1e6
            pool = KvPool(pool_tokens=64 * page, page_tokens=page,
                          max_sessions=2 * args.batch)
            hp = NativePredictor(dec_path)
            hp.kv_attach(pool)
            pool.spill_attach(os.path.join(tmp, "kvtier_spill.bin"),
                              max_bytes=0)   # unbounded: cap is n_tier
            rng = np.random.RandomState(19)
            b = args.batch
            records = []
            rss0 = rss_mb()
            t0 = time.perf_counter()
            while len(records) < n_tier - b:
                sids = [pool.open() for _ in range(b)]
                hp.decode_step(sids,
                               rng.randint(0, cfg.vocab_size, size=b))
                records.extend(pool.hibernate(s) for s in sids)
            live = [pool.open() for _ in range(b)]
            hp.decode_step(live,
                           rng.randint(0, cfg.vocab_size, size=b))
            t_park = time.perf_counter() - t0
            st = pool.stats()
            open_total = len(records) + b
            rss1 = rss_mb()
            naive_mb = open_total * group_mb  # all-resident, same geom
            pool_mb = st["pages_total"] * page * kv_row_bytes / 1e6
            gauges_exact = (
                st["sessions_hibernated"] == len(records) and
                st["sessions_active"] == b and
                st["pages_total"] == 64 and
                st["spill_slots_in_use"] == len(records) and
                st["hibernates"] == len(records) and
                st["spill_exhausted"] == 0)
            rss_bounded = (rss1 - rss0) <= max(128.0, 0.25 * naive_mb)
            emit({"metric": "kvtier_sessions_parked",
                  "value": open_total,
                  "sessions_resident": int(st["sessions_active"]),
                  "sessions_hibernated":
                      int(st["sessions_hibernated"]),
                  "park_sessions_per_s": round(open_total / t_park, 1),
                  "pool_pages_total": int(st["pages_total"]),
                  "pool_ram_mb": round(pool_mb, 2),
                  "naive_resident_mb": round(naive_mb, 1),
                  "spill_file_mb": round(st["spill_bytes"] / 1e6, 1),
                  "spill_slots_in_use": int(st["spill_slots_in_use"]),
                  "rss_before_mb": rss0, "rss_after_mb": rss1,
                  "rss_growth_mb": round(rss1 - rss0, 1),
                  "gauges_exact": bool(gauges_exact),
                  "rss_bounded": bool(rss_bounded),
                  "note": "pool RAM is the ONLY kv residency (spill "
                          "pages are madvise-dropped after every "
                          "copy); naive_resident_mb is the same "
                          "population held un-tiered",
                  "within_gate": bool(gauges_exact and
                                      (args.smoke or rss_bounded))})

            # (c) resume latency: timed restores of parked sessions
            lat_us = []
            for _ in range(min(resume_n, len(records))):
                rec = records.pop()
                t0 = time.perf_counter()
                sid = pool.restore(rec)
                lat_us.append((time.perf_counter() - t0) * 1e6)
                pool.close_session(sid)
            p50 = float(np.percentile(lat_us, 50))
            p99 = float(np.percentile(lat_us, 99))
            emit({"metric": "kvtier_resume_latency_us",
                  "value": round(p99, 1), "unit": "us (p99)",
                  "p50_us": round(p50, 1), "p99_us": round(p99, 1),
                  "max_us": round(max(lat_us), 1),
                  "samples": len(lat_us),
                  "acceptance_gate": 50_000,
                  "within_gate": bool(p99 < 50_000)})
            pool.close()
            del hp

            # (b) hibernate -> restore logits EXACT vs an
            # uninterrupted twin session, normal page geometry
            pool2 = KvPool(pool_tokens=args.batch * args.context,
                           page_tokens=16, max_sessions=8)
            ex = NativePredictor(dec_path, batch_override=1)
            ex.kv_attach(pool2)
            pool2.spill_attach(os.path.join(tmp, "kvtier_ex.bin"))
            toks = rng.randint(0, cfg.vocab_size, size=24)
            sa, sb = pool2.open(), pool2.open()
            for t in toks[:20]:
                ex.decode_step([sa], [int(t)])
                ex.decode_step([sb], [int(t)])
            sa = pool2.restore(pool2.hibernate(sa))
            hib_exact = True
            for t in toks[20:]:
                la = ex.decode_step([sa], [int(t)]).copy()
                lb = ex.decode_step([sb], [int(t)]).copy()
                hib_exact = hib_exact and bool(np.array_equal(la, lb))
            pool2.close()
            del ex
            emit({"metric": "kvtier_restore_logits_exact",
                  "value": bool(hib_exact),
                  "history_tokens": 20, "compared_steps": 4,
                  "note": "bit-identical logits after a spill-file "
                          "round trip"})

            # (d) restart-warm prefix cache: hit rate of a FRESH
            # server's first open vs the old server's steady state
            persist = os.path.join(tmp, "kvtier_prefix.bin")
            # >= one full 16-token page below the context ceiling, so
            # warm opens have a group to adopt even at smoke scale
            wprompt = list(range(21, 21 + min(36, args.context - 4)))

            def tier_server(env, **kw):
                for k, v in env.items():
                    os.environ[k] = v
                try:
                    return inference.create_server(
                        full_path, max_batch=2, instances=1,
                        decode_model=dec_path, **kw)
                finally:
                    for k in env:
                        del os.environ[k]

            sv1 = tier_server({"PTPU_KV_PREFIX_PERSIST": persist},
                              kv_sessions=16)
            c1 = sv1.client()
            t0 = time.perf_counter()
            s0, _, ad_cold = c1.decode_open(prompt=wprompt,
                                            timeout=120.0)
            t_cold = time.perf_counter() - t0
            s1, _, ad_pre = c1.decode_open(prompt=wprompt,
                                           timeout=120.0)
            for s in (s0, s1):
                c1.decode_close(s)
            c1.close()
            sv1.stop()          # persists the prefix cache
            sv2 = tier_server({"PTPU_KV_PREFIX_PERSIST": persist},
                              kv_sessions=16)
            c2 = sv2.client()
            loaded = sv2.stats()["decode"]["pool"].get(
                "prefix_persist_loaded", 0)
            t0 = time.perf_counter()
            s2, _, ad_post = c2.decode_open(prompt=wprompt,
                                            timeout=120.0)
            t_warm = time.perf_counter() - t0
            c2.decode_close(s2)
            c2.close()
            sv2.stop()
            prefix_warm_ok = (ad_cold == 0 and loaded >= 1 and
                              ad_post >= ad_pre > 0)
            emit({"metric": "kvtier_prefix_restart_warm",
                  "value": bool(prefix_warm_ok),
                  "prompt_tokens": len(wprompt),
                  "adopted_cold_first_open": int(ad_cold),
                  "adopted_pre_restart_warm": int(ad_pre),
                  "adopted_post_restart_first_open": int(ad_post),
                  "hit_rate_pre": round(ad_pre / len(wprompt), 3),
                  "hit_rate_post_restart": round(
                      ad_post / len(wprompt), 3),
                  "prefix_persist_loaded_pages": int(loaded),
                  "cold_open_s": round(t_cold, 4),
                  "warm_open_s": round(t_warm, 4),
                  "within_gate": bool(prefix_warm_ok)})

            # (e) tiering-OFF guard: spill tier attached but idle must
            # not tax the decode path (interleaved rounds)
            def tier_ab_leg(env):
                sv = tier_server(env,
                                 kv_sessions=args.sessions + 2)
                c = sv.client()
                ss = [c.decode_open() for _ in range(args.sessions)]
                cur = [7] * args.sessions  # the leg-2 prompt token
                t0 = time.perf_counter()
                for _ in range(ab_tokens - 1):
                    outs = c.decode_step_many(
                        [(ss[i], cur[i])
                         for i in range(args.sessions)])
                    for i in range(args.sessions):
                        cur[i] = int(np.argmax(outs[i]))
                dt = time.perf_counter() - t0
                std = sv.stats()["decode"]
                hib = std.get("hibernates", 0)
                for s in ss:
                    c.decode_close(s)
                c.close()
                sv.stop()
                return args.sessions * (ab_tokens - 1) / dt, hib
            on_env = {"PTPU_KV_SPILL_PATH":
                      os.path.join(tmp, "kvtier_ab_spill.bin")}
            on_tps, off_tps, idle_hib = [], [], 0
            for r in range(ab_rounds):
                order = [("on", on_env), ("off", {})]
                if r % 2:
                    order.reverse()
                for label, e in order:
                    tps, hib = tier_ab_leg(e)
                    (on_tps if label == "on" else off_tps).append(tps)
                    if label == "on":
                        idle_hib += hib
            tier_tax = (float(np.mean(on_tps)) /
                        max(float(np.mean(off_tps)), 1e-9))
            emit({"metric": "kvtier_tier_off_guard",
                  "value": round(tier_tax, 3), "unit": "x",
                  "tier_on_tokens_per_s":
                      round(float(np.mean(on_tps)), 1),
                  "tier_off_tokens_per_s":
                      round(float(np.mean(off_tps)), 1),
                  "per_round_on": [round(x, 1) for x in on_tps],
                  "per_round_off": [round(x, 1) for x in off_tps],
                  "hibernates_while_attached_idle": int(idle_hib),
                  "rounds": ab_rounds,
                  "acceptance_gate": 0.90,
                  "within_gate": bool(tier_tax >= 0.90)})

            kvtier_correct = (gauges_exact and hib_exact and
                              prefix_warm_ok)
            ok = ok and kvtier_correct
            if not args.smoke:
                ok = ok and rss_bounded and p99 < 50_000 and \
                    tier_tax >= 0.90

            if args.kvtier_out:
                kt = [m for m in RESULTS
                      if m["metric"].startswith("kvtier_")]
                with open(args.kvtier_out, "w") as f:
                    json.dump({"bench": "kvtier_bench",
                               "host": host_meta(),
                               "config": vars(args),
                               "measurements": kt}, f, indent=1)
                print(f"# persisted kvtier legs to {args.kvtier_out}",
                      flush=True)

        # ---- r01 guard + gates -------------------------------------
        ratio = kv_tps / rc_tps
        emit({"metric": "decode_kv_speedup_vs_recompute",
              "value": round(ratio, 2), "unit": "x",
              "acceptance_gate": 5.0,
              "within_gate": bool(ratio >= 5.0)})

        guard = {}
        r01_path = os.path.join(REPO, "BENCH_DECODE_r01.json")
        r01_config = (args.sessions, args.tokens, args.context,
                      args.batch) == (8, 48, 64, 8)
        if os.path.exists(r01_path) and r01_config:
            with open(r01_path) as f:
                r01 = json.load(f)
            base = next((m["value"] for m in r01["measurements"]
                         if m["metric"] == "kv_decode_tokens_per_s"),
                        None)
            if base:
                drift = kv_tps / base - 1.0
                guard = {"metric": "bench_guard_kv_8s_vs_r01",
                         "r01_tokens_per_s": base,
                         "r02_tokens_per_s": round(kv_tps, 1),
                         "drift": round(drift, 4),
                         "within_gate": bool(drift >= -0.10)}
                emit(guard)
                ok = ok and drift >= -0.10

        if args.smoke:
            # correctness only: exactness/parity must hold at any size
            ok = counters_exact and logits_close and exact_all
            if not args.skip_int4:
                ok = ok and warm_ok
            if not args.skip_kvtier:
                ok = ok and kvtier_correct
        else:
            ok = ok and counters_exact and logits_close and exact_all \
                and ratio >= 5.0

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"bench": "decode_bench",
                       "host": host_meta(),
                       "config": vars(args),
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {args.out}", flush=True)
    if not ok:
        sys.exit("decode_bench: acceptance gate FAILED")


if __name__ == "__main__":
    main()
