#!/usr/bin/env python
"""KV-cached autoregressive decode vs full-prefix recompute (ISSUE r9).

The generation workload for the native serving stack: a GPT-tiny
decode-step artifact (models.gpt.export_gpt_decode — per-layer KV cache
inputs, one-token step) served through the C runtime's DECODE wire ops
(csrc/ptpu_serving.cc 0x65..0x69) with per-session KV slots in the
predictor (csrc/ptpu_predictor.cc kv_plan/decode_step) and continuous
batching of steps from different sessions through the micro-batcher.

Three legs:
  recompute  greedy generation via the FULL-SEQUENCE artifact — every
             token re-runs the whole fixed-shape [1, S] graph (what
             this stack had to do before DECODE existed);
  kv_serving greedy generation for N concurrent sessions over the wire,
             steps pipelined so the decode batcher fills;
  parity     one session's greedy token stream must be IDENTICAL
             between the two paths, logits allclose, and the server's
             decode counters must equal the client-observed counts
             EXACTLY.

Gate (acceptance): kv tokens/s >= 5x recompute tokens/s.

Run: python tools/decode_bench.py [--out BENCH_DECODE_rNN.json]
     [--sessions N] [--tokens T] [--context P] [--batch B]
(CPU-only; forces jax to CPU; rebuilds nothing — uses the shipped .so,
whose micro-kernels runtime-dispatch on cpuid.)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESULTS = []


def emit(rec):
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out")
    ap.add_argument("--sessions", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=48)
    ap.add_argument("--context", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import inference
    from paddle_tpu.core.native import NativePredictor
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       export_gpt_decode, gpt_tiny)
    from paddle_tpu.onnx.converter import trace_to_onnx

    assert args.tokens <= args.context

    pt.seed(0)
    cfg = gpt_tiny(dtype=jnp.float32, dropout=0.0)
    model = GPTForPretraining(cfg)
    model.eval()

    with tempfile.TemporaryDirectory() as tmp:
        dec_path = export_gpt_decode(model, os.path.join(tmp, "dec"),
                                     batch=args.batch,
                                     context=args.context)
        S = args.tokens  # full-seq artifact sized to the generation
        full_bytes = trace_to_onnx(lambda ids: model(ids),
                                   (jnp.zeros((1, S), jnp.int32),))
        full_path = os.path.join(tmp, "full.onnx")
        with open(full_path, "wb") as f:
            f.write(full_bytes)

        prompt = 7  # fixed prompt token; everything after is greedy

        # ---- leg 1: full-prefix recompute baseline -----------------
        # step t: run the whole [1, S] graph over the prefix (padded),
        # next token = argmax of the logits at position t
        def recompute_generate(steps):
            toks = np.zeros((1, S), np.int32)
            toks[0, 0] = prompt
            out = [prompt]
            with NativePredictor(full_path) as p:
                name = p.input_name(0)
                p.set_input(name, toks)
                p.run()  # warmup/load
                t0 = time.perf_counter()
                for t in range(steps - 1):
                    p.set_input(name, toks)
                    p.run()
                    lg = p.output(0)[0, t]
                    nxt = int(np.argmax(lg))
                    out.append(nxt)
                    toks[0, t + 1] = nxt
                dt = time.perf_counter() - t0
            return out, (steps - 1) / dt

        rc_tokens, rc_tps = recompute_generate(args.tokens)
        emit({"metric": "recompute_tokens_per_s",
              "value": round(rc_tps, 1), "unit": "tokens/s",
              "seq": S, "note": "full [1,S] graph re-run per token"})

        # ---- leg 2: KV-cached decode through the serving wire ------
        srv = inference.create_server(
            full_path, max_batch=2, instances=1,
            decode_model=dec_path, kv_sessions=args.sessions + 2)
        cli = srv.client()
        meta = srv.config()
        assert meta["decode"]["batch"] == args.batch
        sess = [cli.decode_open() for _ in range(args.sessions)]
        cur = [prompt] * args.sessions
        streams = [[prompt] for _ in range(args.sessions)]
        t0 = time.perf_counter()
        for _ in range(args.tokens - 1):
            outs = cli.decode_step_many(
                [(sess[i], cur[i]) for i in range(args.sessions)])
            for i in range(args.sessions):
                cur[i] = int(np.argmax(outs[i]))
                streams[i].append(cur[i])
        dt = time.perf_counter() - t0
        kv_steps = args.sessions * (args.tokens - 1)
        kv_tps = kv_steps / dt
        st = srv.stats()["decode"]
        emit({"metric": "kv_decode_tokens_per_s",
              "value": round(kv_tps, 1), "unit": "tokens/s",
              "sessions": args.sessions, "batch": args.batch,
              "context": args.context,
              "batches": st["batches"],
              "mean_fill": round(kv_steps / max(st["batches"], 1), 2)})

        # ---- counter exactness: server == client-observed ----------
        counters_exact = (st["steps"] == kv_steps and
                          st["replies"] == kv_steps and
                          st["opens"] == args.sessions and
                          st["evictions"] == 0)
        emit({"metric": "decode_counters_exact",
              "value": bool(counters_exact),
              "server": {k: st[k] for k in
                         ("steps", "replies", "opens", "evictions")},
              "client_steps": kv_steps})

        # ---- parity: teacher-forced logits match the full-seq graph
        # at EVERY position (argmax streams on an UNTRAINED model are
        # ulp-unstable across compute paths, so the check is on logits,
        # not on greedy choices)
        ps = cli.decode_open()
        kv_logits = [np.asarray(cli.decode_step(ps, rc_tokens[t]))
                     for t in range(args.tokens - 1)]
        cli.decode_close(ps)
        with NativePredictor(full_path) as p:
            name = p.input_name(0)
            toks = np.zeros((1, S), np.int32)
            toks[0, :len(rc_tokens)] = rc_tokens
            p.set_input(name, toks)
            p.run()
            full_logits = p.output(0)[0]
        per_step_close = [bool(np.allclose(kv_logits[t], full_logits[t],
                                           rtol=2e-3, atol=2e-4))
                          for t in range(args.tokens - 1)]
        logits_close = all(per_step_close)
        emit({"metric": "decode_parity",
              "value": bool(logits_close),
              "teacher_forced_steps": args.tokens - 1,
              "all_positions_allclose": logits_close})
        del streams  # greedy streams only drive the throughput leg

        for s in sess:
            cli.decode_close(s)
        cli.close()
        srv.stop()

        # ---- the gate ----------------------------------------------
        ratio = kv_tps / rc_tps
        emit({"metric": "decode_kv_speedup_vs_recompute",
              "value": round(ratio, 2), "unit": "x",
              "acceptance_gate": 5.0,
              "within_gate": bool(ratio >= 5.0)})

        ok = counters_exact and logits_close and ratio >= 5.0

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"bench": "decode_bench",
                       "config": {"sessions": args.sessions,
                                  "tokens": args.tokens,
                                  "context": args.context,
                                  "batch": args.batch},
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {args.out}", flush=True)
    if not ok:
        sys.exit("decode_bench: acceptance gate FAILED")


if __name__ == "__main__":
    main()
