#!/usr/bin/env python
"""Poll a LIVE PS node's observability snapshot (ISSUE 3 tentpole).

Connects to the node's control plane (the multiprocessing.connection
listener `distributed/ps/table.py` serves) and issues the `"stats"`
op — the reference analogue of curling a brpc server's /vars page.
Works against any running TableService: a training job, a
`tools/ps_bench.py` server mid-run, or the shrunken test config.

Output modes:
  (default)      pretty JSON snapshot
  --prom         Prometheus exposition text (profiler/stats.py
                 prometheus_text) — pipe to a file node_exporter-style
                 or serve it from a sidecar
  --watch SEC    poll every SEC seconds; prints pull/push ops/s and
                 MB/s deltas between polls plus the snapshot
  --reset        zero the node's counters ("stats_reset" op) and exit

Addressing mirrors the launcher env contract: the control port of rank
R is MASTER_PORT + 200 + R and the connection authkey derives from
MASTER_PORT (same derivation as the service itself).

Run: python tools/ps_stats.py [--master-port 8476] [--rank 0]
         [--host 127.0.0.1] [--prom | --watch 2 | --reset]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fetch_stats(master_port: int, rank: int = 0,
                host: str = "127.0.0.1", op: str = "stats",
                timeout_s: float = 10.0):
    """One control-plane round trip; returns the decoded snapshot (or
    b"ok" for "stats_reset"). Importable — the tests and ps_bench use
    this instead of shelling out."""
    from multiprocessing.connection import Client

    from paddle_tpu.distributed.ps import table as T
    from paddle_tpu.distributed.ps.wire import recv_msg, send_msg

    authkey = T._AUTHKEY_BASE + str(master_port).encode()
    port = master_port + T._PORT_OFFSET + rank
    deadline = time.time() + timeout_s
    while True:
        try:
            conn = Client((host, port), authkey=authkey)
            break
        except (ConnectionRefusedError, OSError):
            if time.time() > deadline:
                raise
            time.sleep(0.1)
    try:
        send_msg(conn, (op, "", None))
        return recv_msg(conn)
    finally:
        conn.close()


def _rates(prev: dict, cur: dict, dt: float) -> str:
    def d(key):
        return (cur.get("wire", {}).get(key, 0) -
                prev.get("wire", {}).get(key, 0))
    mb = (d("bytes_in") + d("bytes_out")) / dt / 1e6
    # live connection view from the epoll net core (C data plane)
    conns = cur.get("wire", {}).get("conns_active", 0)
    shed = cur.get("wire", {}).get("conns_shed", 0)
    return (f"pull {d('pull_ops') / dt:,.0f} ops/s "
            f"({d('pull_rows') / dt:,.0f} rows/s) | "
            f"push {d('push_ops') / dt:,.0f} ops/s "
            f"({d('push_rows') / dt:,.0f} rows/s) | "
            f"{mb:,.1f} MB/s | conns {conns}"
            + (f" (shed {shed})" if shed else ""))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="poll a live PS node's stats snapshot")
    ap.add_argument("--master-port", type=int,
                    default=int(os.environ.get("MASTER_PORT", "8476")))
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--prom", action="store_true",
                    help="Prometheus exposition format")
    ap.add_argument("--watch", type=float, default=None, metavar="SEC",
                    help="poll every SEC seconds with ops/s deltas")
    ap.add_argument("--reset", action="store_true",
                    help="zero the node's counters and exit")
    a = ap.parse_args(argv)

    if a.reset:
        fetch_stats(a.master_port, a.rank, a.host, op="stats_reset")
        print(f"rank {a.rank} stats reset")
        return

    from paddle_tpu.profiler.stats import prometheus_text

    def render(snap):
        if a.prom:
            return prometheus_text(
                snap, prefix="ptpu_ps",
                labels={"rank": str(snap.get("rank", a.rank))})
        return json.dumps(snap, indent=1, sort_keys=True)

    snap = fetch_stats(a.master_port, a.rank, a.host)
    last = time.time()
    print(render(snap), flush=True)
    if a.watch is None:
        return
    while True:
        time.sleep(a.watch)
        nxt = fetch_stats(a.master_port, a.rank, a.host)
        now = time.time()
        print(f"# {time.strftime('%H:%M:%S')} "
              f"{_rates(snap, nxt, max(1e-9, now - last))}",
              flush=True)
        print(render(nxt), flush=True)
        snap, last = nxt, now


if __name__ == "__main__":
    main()
