#!/usr/bin/env python
"""Poll a LIVE node's observability snapshot (ISSUE 3 tentpole; grown
into the shared stats CLI by ISSUE 10).

Three addressing modes:

  (default)          a PS node's CONTROL plane (the multiprocessing.
                     connection listener `distributed/ps/table.py`
                     serves): the `"stats"` op — the reference
                     analogue of curling a brpc server's /vars page.
  --http HOST:PORT   the telemetry HTTP endpoint either C server
                     (PS data plane or serving runtime) exposes on the
                     epoll net core (ISSUE 10): GET /statsz (JSON) or
                     GET /metrics (--prom, served byte-identical to
                     the local renderer).
  --serving HOST:PORT  alias of --http for a serving runtime — same
                     fetch; the --watch delta line shows infer/decode
                     ops/s instead of pull/push.

Output modes:
  (default)      pretty JSON snapshot
  --prom         Prometheus exposition text (profiler/stats.py
                 prometheus_text; over --http the server's C-rendered
                 /metrics bytes) — pipe to a file node_exporter-style
                 or serve it from a sidecar
  --watch SEC    poll every SEC seconds; prints ops/s and MB/s deltas
                 between polls plus the snapshot (pull/push for a PS
                 snapshot, infer/decode for a serving one — detected
                 from the snapshot shape)
  --reset        zero the node's counters ("stats_reset" op; control
                 plane only) and exit

Addressing for the default mode mirrors the launcher env contract: the
control port of rank R is MASTER_PORT + 200 + R and the connection
authkey derives from MASTER_PORT (same derivation as the service).

Run: python tools/ps_stats.py [--master-port 8476] [--rank 0]
         [--host 127.0.0.1] [--http H:P | --serving H:P]
         [--prom | --watch 2 | --reset]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fetch_stats(master_port: int, rank: int = 0,
                host: str = "127.0.0.1", op: str = "stats",
                timeout_s: float = 10.0):
    """One control-plane round trip; returns the decoded snapshot (or
    b"ok" for "stats_reset"). Importable — the tests and ps_bench use
    this instead of shelling out."""
    from multiprocessing.connection import Client

    from paddle_tpu.distributed.ps import table as T
    from paddle_tpu.distributed.ps.wire import recv_msg, send_msg

    authkey = T._AUTHKEY_BASE + str(master_port).encode()
    port = master_port + T._PORT_OFFSET + rank
    deadline = time.time() + timeout_s
    while True:
        try:
            conn = Client((host, port), authkey=authkey)
            break
        except (ConnectionRefusedError, OSError):
            if time.time() > deadline:
                raise
            time.sleep(0.1)
    try:
        send_msg(conn, (op, "", None))
        return recv_msg(conn)
    finally:
        conn.close()


def http_get(hostport: str, path: str, timeout_s: float = 10.0):
    """GET one telemetry path off a C server's HTTP endpoint; returns
    the body bytes. Raises RuntimeError on a non-200 status."""
    from urllib.error import HTTPError
    from urllib.request import urlopen

    url = f"http://{hostport}{path}"
    try:
        with urlopen(url, timeout=timeout_s) as r:
            return r.read()
    except HTTPError as e:   # 503 draining etc: surface the status
        raise RuntimeError(
            f"GET {url} -> {e.code} {e.reason}") from e


def fetch_http_stats(hostport: str, timeout_s: float = 10.0) -> dict:
    """GET /statsz of a PS data-plane or serving telemetry endpoint."""
    return json.loads(http_get(hostport, "/statsz", timeout_s))


def _is_serving(snap: dict) -> bool:
    """A serving runtime snapshot carries the batcher section; a PS
    node's carries pull/push counters."""
    return "batcher" in snap


def _rates(prev: dict, cur: dict, dt: float) -> str:
    """One ops/s + MB/s delta line between two polls; the counter set
    is picked from the snapshot shape (PS vs serving)."""
    if _is_serving(cur):
        def d(key):
            return (cur.get("server", {}).get(key, 0) -
                    prev.get("server", {}).get(key, 0))

        def dd(key):
            return (cur.get("decode", {}).get(key, 0) -
                    prev.get("decode", {}).get(key, 0))
        mb = (d("bytes_in") + d("bytes_out")) / dt / 1e6
        conns = cur.get("server", {}).get("conns_active", 0)
        line = (f"infer {d('requests') / dt:,.0f} req/s "
                f"({d('replies') / dt:,.0f} rep/s, "
                f"{d('req_errors') / dt:,.0f} err/s)")
        if "decode" in cur:
            line += (f" | decode {dd('steps') / dt:,.0f} steps/s "
                     f"({cur['decode'].get('sessions_resident', 0)} "
                     f"res/{cur['decode'].get('sessions_hibernated', 0)}"
                     f" hib)")
            # KV tiering (r19): restores/s only when the spill tier is
            # live — a flat 0 column on untired deployments is noise
            if cur["decode"].get("sessions_hibernated", 0) or dd("restores"):
                line += f" | restore {dd('restores') / dt:,.0f}/s"
        return line + f" | {mb:,.1f} MB/s | conns {conns}"
    # PS planes: the control-plane snapshot nests wire counters under
    # "wire"; the HTTP /statsz one keeps them under "server"
    sec = "wire" if "wire" in cur else "server"

    def d(key):
        return (cur.get(sec, {}).get(key, 0) -
                prev.get(sec, {}).get(key, 0))
    mb = (d("bytes_in") + d("bytes_out")) / dt / 1e6
    # live connection view from the epoll net core (C data plane)
    conns = cur.get(sec, {}).get("conns_active", 0)
    shed = cur.get(sec, {}).get("conns_shed", 0)
    return (f"pull {d('pull_ops') / dt:,.0f} ops/s "
            f"({d('pull_rows') / dt:,.0f} rows/s) | "
            f"push {d('push_ops') / dt:,.0f} ops/s "
            f"({d('push_rows') / dt:,.0f} rows/s) | "
            f"{mb:,.1f} MB/s | conns {conns}"
            + (f" (shed {shed})" if shed else ""))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="poll a live PS / serving node's stats snapshot")
    ap.add_argument("--master-port", type=int,
                    default=int(os.environ.get("MASTER_PORT", "8476")))
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--http", default=None, metavar="HOST:PORT",
                    help="poll a C server's telemetry HTTP endpoint "
                         "(GET /statsz, /metrics) instead of the "
                         "control plane")
    ap.add_argument("--serving", default=None, metavar="HOST:PORT",
                    help="poll a serving runtime's telemetry endpoint "
                         "(same as --http; --watch shows infer/decode "
                         "deltas)")
    ap.add_argument("--prom", action="store_true",
                    help="Prometheus exposition format")
    ap.add_argument("--invar", action="store_true",
                    help="conservation-law verdict instead of raw "
                         "counters: GET /invarz over --http/--serving "
                         "(the server's own C evaluator), or the "
                         "profiler/stats.py twin over a control-plane "
                         "snapshot; with --watch, polls")
    ap.add_argument("--watch", type=float, default=None, metavar="SEC",
                    help="poll every SEC seconds with ops/s deltas")
    ap.add_argument("--reset", action="store_true",
                    help="zero the node's counters and exit "
                         "(control-plane mode only)")
    a = ap.parse_args(argv)
    endpoint = a.serving or a.http

    if a.reset:
        if endpoint:
            sys.exit("--reset needs the control plane (the HTTP "
                     "endpoint is read-only)")
        fetch_stats(a.master_port, a.rank, a.host, op="stats_reset")
        print(f"rank {a.rank} stats reset")
        return

    from paddle_tpu.profiler.stats import prometheus_text

    def fetch():
        if endpoint:
            return fetch_http_stats(endpoint)
        return fetch_stats(a.master_port, a.rank, a.host)

    if a.invar:
        # one verdict per poll; `==` laws are authoritative only at
        # quiesce (csrc/ptpu_invar.h), so a violation while traffic
        # flows is informational — watch for one that PERSISTS
        from paddle_tpu.profiler.stats import invar_check

        def verdict():
            if endpoint:
                return json.loads(http_get(endpoint, "/invarz"))
            snap = fetch()
            if "server" not in snap and "wire" in snap:
                # control-plane snapshots nest the C wire counters
                # under "wire"; rehome them so law paths resolve
                snap = dict(snap, server=snap["wire"])
            return invar_check(snap)
        while True:
            rep = verdict()
            tag = "OK" if not rep.get("violations") else "VIOLATED"
            print(f"# {time.strftime('%H:%M:%S')} invar {tag} "
                  f"(checked {rep.get('checked', 0)}, skipped "
                  f"{rep.get('skipped', 0)})", flush=True)
            print(json.dumps(rep, indent=1, sort_keys=True),
                  flush=True)
            if a.watch is None:
                return
            time.sleep(a.watch)

    def render(snap):
        if a.prom:
            if endpoint:
                # the server's own C renderer — byte-identical to
                # prometheus_text over /statsz, and one fetch fresher
                return http_get(endpoint, "/metrics").decode()
            return prometheus_text(
                snap, prefix="ptpu_ps",
                labels={"rank": str(snap.get("rank", a.rank))})
        return json.dumps(snap, indent=1, sort_keys=True)

    snap = fetch()
    last = time.time()
    print(render(snap), flush=True)
    if a.watch is None:
        return
    while True:
        time.sleep(a.watch)
        nxt = fetch()
        now = time.time()
        print(f"# {time.strftime('%H:%M:%S')} "
              f"{_rates(snap, nxt, max(1e-9, now - last))}",
              flush=True)
        print(render(nxt), flush=True)
        snap, last = nxt, now


if __name__ == "__main__":
    main()
