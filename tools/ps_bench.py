#!/usr/bin/env python
"""PS wire throughput micro-bench (VERDICT r4 item 7 acceptance).

Two processes, one table: rank 1 hammers pull and push RPCs against
rank 0's shard over the binary wire (`distributed/ps/wire.py`) and
reports ops/s and effective MB/s. Run: python tools/ps_bench.py
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

VOCAB, DIM, BATCH, OPS = 100_000, 64, 512, 300


def _worker(rank, port, q):
    os.environ["MASTER_PORT"] = str(port)
    import numpy as np
    from paddle_tpu.distributed.ps.table import TableService

    svc = TableService(rank, 2, port)
    svc.register("emb", VOCAB, DIM, lr=0.1, seed=0)
    rs = np.random.RandomState(rank)
    # all ids on the PEER's shard -> every op is a real network RPC
    lo = 0 if rank == 1 else VOCAB // 2
    ids = rs.randint(lo, lo + VOCAB // 2 - 1, BATCH)
    grads = rs.randn(BATCH, DIM).astype(np.float32)

    if rank == 1:
        svc.pull("emb", ids)                      # connect + warm
        t0 = time.perf_counter()
        for _ in range(OPS):
            svc.pull("emb", ids)
        dt_pull = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(OPS):
            svc.push("emb", ids, grads, sync=True)
        dt_push = time.perf_counter() - t0
        row_bytes = BATCH * DIM * 4
        q.put({
            "pull_ops_s": round(OPS / dt_pull, 1),
            "pull_MB_s": round(OPS * row_bytes / dt_pull / 1e6, 1),
            "push_ops_s": round(OPS / dt_push, 1),
            "push_MB_s": round(OPS * row_bytes / dt_push / 1e6, 1),
            "batch": BATCH, "dim": DIM,
        })
        svc.barrier("psbench")
    else:
        svc.barrier("psbench")
    svc.shutdown()


def main():
    port = 29650
    q: "mp.Queue" = mp.Queue()
    ps = [mp.Process(target=_worker, args=(r, port, q)) for r in (0, 1)]
    for p in ps:
        p.start()
    res = q.get(timeout=120)
    for p in ps:
        p.join(timeout=30)
    print(json.dumps({"metric": "ps_wire_pull_ops_per_s",
                      "value": res["pull_ops_s"], "unit": "ops/s",
                      **{k: v for k, v in res.items()
                         if k != "pull_ops_s"}}))


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
