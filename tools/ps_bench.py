#!/usr/bin/env python
"""PS service throughput bench (VERDICT r4 item 7 / r5 "Next round" 9).

One server rank hosts a shard (C-hosted native table when the library
is present); NCLIENTS client processes hammer it over the binary wire
(`distributed/ps/wire.py` fast frames). Measured phases:

  1. sync pull        — one client, one request in flight (the r5
                        configuration: latency-bound, comparable to the
                        2.7k ops/s r5 headline);
  2. pipelined pull   — every client keeps DEPTH pulls in flight
                        (`TableService.pull_many`); the aggregate is
                        the service-throughput headline;
  3. sync push        — one client;
  4. async push       — every client, server-side coalescing + drain.

A native-vs-numpy parity check (byte-identical pull, allclose push
update for sgd/adagrad/adam) runs in-process and is recorded with the
measurements. `--out FILE.json` persists every row
(BENCH_PS_rNN.json style, same shape as tools/predictor_bench.py).

Config via env: PTPU_PSBENCH_{VOCAB,DIM,BATCH,OPS,CLIENTS,DEPTH}
(tests/test_ps_bench_persist.py runs a shrunken 2-proc config).
Run: python tools/ps_bench.py [--out BENCH_PS_rNN.json]
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

VOCAB = int(os.environ.get("PTPU_PSBENCH_VOCAB", 100_000))
DIM = int(os.environ.get("PTPU_PSBENCH_DIM", 64))
BATCH = int(os.environ.get("PTPU_PSBENCH_BATCH", 512))
OPS = int(os.environ.get("PTPU_PSBENCH_OPS", 1000))
# service throughput needs enough concurrent clients to cover request
# latency; leave headroom for the server + OS on small boxes
NCLIENTS = int(os.environ.get(
    "PTPU_PSBENCH_CLIENTS",
    max(2, min(20, (os.cpu_count() or 8) * 5 // 6))))
DEPTH = int(os.environ.get("PTPU_PSBENCH_DEPTH", 6))
# wider request merging than the library default: the bench hammers one
# table, exactly the shape merging amortizes
os.environ.setdefault("PTPU_PS_MERGE_ROWS", "8192")
# the native PS server's Stop() runs the counter-conservation gate
# (csrc/ptpu_invar.h); under the bench a violation is fatal, so every
# worker teardown is itself a ledger check
os.environ.setdefault("PTPU_INVAR_FATAL", "1")

RESULTS: list = []


def emit(row: dict):
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _worker(rank, world, port, q):
    os.environ["MASTER_PORT"] = str(port)
    import numpy as np
    from paddle_tpu.distributed.ps.table import TableService

    svc = TableService(rank, world, port)
    svc.register("emb", VOCAB, DIM, lr=0.1, seed=0)
    # every rank has registered before the first pull can arrive
    svc.barrier("psbench-reg", timeout_s=600)
    block = svc._shards["emb"].block
    rs = np.random.RandomState(rank)
    # every id on rank 0's shard -> every client op is a real wire RPC
    # against the ONE server under test
    ids = rs.randint(0, block, BATCH).astype(np.int64)
    grads = rs.randn(BATCH, DIM).astype(np.float32)
    row_bytes = BATCH * DIM * 4

    if rank == 0:
        # the server participates in every phase barrier the clients
        # synchronize on, then just serves. After each barrier opens it
        # takes a stats snapshot: "go" ≈ warm-up traffic only, "pipe" ≈
        # + sync pulls, "push" ≈ + pipelined pulls + sync pushes,
        # "done" = EXACT totals (every client drained before it). The
        # intermediate cuts can trail the barrier by a beat; only
        # "done" is exact.
        snaps = {}
        for name in ("psbench-go", "psbench-pipe", "psbench-push",
                     "psbench-done"):
            svc.barrier(name, timeout_s=900)
            snaps[name.split("-", 1)[1]] = svc.stats_snapshot()
        # the live-poll proof: fetch the same totals over the control
        # plane the way an operator would (tools/ps_stats.py)
        try:
            from tools.ps_stats import fetch_stats
            cli_snap = fetch_stats(port, rank=0, timeout_s=30)
        except Exception as e:  # noqa: BLE001 — keep the bench alive
            cli_snap = {"error": repr(e)}
        q.put({"rank": 0, "native": svc._shards["emb"].native,
               "stats_phases": snaps, "stats_cli": cli_snap})
    else:
        svc.pull("emb", ids)                      # connect + warm
        svc.barrier("psbench-go", timeout_s=900)
        res = {"rank": rank}

        if rank == 1:
            # phase 1: sync pull (one request in flight — r5 config)
            t0 = time.perf_counter()
            for _ in range(OPS):
                svc.pull("emb", ids)
            res["dt_pull_sync"] = time.perf_counter() - t0

        # phase 2: pipelined pulls, all clients simultaneously
        svc.barrier("psbench-pipe", timeout_s=900)
        reqs = [ids] * OPS
        t0 = time.perf_counter()
        svc.pull_many("emb", reqs, depth=DEPTH)
        res["dt_pull_pipe"] = time.perf_counter() - t0

        if rank == 1:
            # phase 3: sync push
            t0 = time.perf_counter()
            for _ in range(OPS):
                svc.push("emb", ids, grads, sync=True)
            res["dt_push_sync"] = time.perf_counter() - t0

        # phase 4: async pushes with server-side coalescing, then drain
        svc.barrier("psbench-push", timeout_s=900)
        ch = svc.open_channel(0, depth=DEPTH)
        t0 = time.perf_counter()
        for _ in range(OPS):
            ch.push_async("emb", ids, grads)
        ch.drain()
        svc._rpc(0, "push_drain", "", None)
        res["dt_push_async"] = time.perf_counter() - t0
        ch.close()

        res["row_bytes"] = row_bytes
        q.put(res)
        svc.barrier("psbench-done", timeout_s=600)
    svc.shutdown()


def _parity_rows():
    """Native vs numpy shard parity, no network (acceptance: byte-
    identical pull, allclose push update)."""
    import numpy as np

    from paddle_tpu.core import native
    from paddle_tpu.distributed.ps.table import _Shard

    if not native.ps_table_available():
        return [{"metric": "ps_native_parity", "value": 0,
                 "unit": "bool", "note": "native table unavailable"}]
    rows = []
    rs = np.random.RandomState(0)
    vocab, dim = 1024, 16
    ids = rs.randint(0, vocab, 256)
    grads = rs.randn(256, dim).astype(np.float32)
    for opt in ("sgd", "adagrad", "adam"):
        nat = _Shard("p", vocab, dim, 0, 1, 0.1, 3, optimizer=opt)
        os.environ["PTPU_PS_NATIVE"] = "0"
        try:
            ref = _Shard("p", vocab, dim, 0, 1, 0.1, 3, optimizer=opt)
        finally:
            del os.environ["PTPU_PS_NATIVE"]
        assert nat.native and not ref.native
        pull_exact = bool(
            nat.pull(ids).tobytes() == ref.pull(ids).tobytes())
        for _ in range(3):
            nat.push(ids, grads)
            ref.push(ids, grads)
        push_close = bool(np.allclose(nat.data, ref.data, rtol=1e-5,
                                      atol=1e-6))
        rows.append({"metric": f"ps_native_parity_{opt}",
                     "value": int(pull_exact and push_close),
                     "unit": "bool", "pull_byte_identical": pull_exact,
                     "push_allclose": push_close})
    return rows


def main():
    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: ps_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    if "--cpr" in sys.argv:
        # interleaved old-vs-new-.so A/B of the PS plane (ISSUE 17):
        # the shared subprocess-leg harness lives in serving_bench;
        # restrict it to the ps pull leg
        os.environ["PTPU_CPRBENCH_PLANES"] = "ps"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from tools import serving_bench
        serving_bench.run_cpr_ab(out_path)
        return

    world = 1 + NCLIENTS
    port = 29650
    q: "mp.Queue" = mp.Queue()
    ps = [mp.Process(target=_worker, args=(r, world, port, q))
          for r in range(world)]
    for p in ps:
        p.start()
    res = {}
    for _ in range(world):
        r = q.get(timeout=600)
        res[r.pop("rank")] = r
    for p in ps:
        p.join(timeout=60)

    row_bytes = res[1]["row_bytes"]
    native_engaged = bool(res[0]["native"])

    def rate(dt, n=OPS):
        return round(n / dt, 1), round(n * row_bytes / dt / 1e6, 1)

    sync_ops, sync_mb = rate(res[1]["dt_pull_sync"])
    emit({"metric": "ps_pull_sync_ops_per_s", "value": sync_ops,
          "unit": "ops/s", "MB_s": sync_mb, "batch": BATCH, "dim": DIM,
          "clients": 1, "native_table": native_engaged})

    # aggregate service throughput: total ops over the longest client
    pipe_total = OPS * NCLIENTS
    pipe_wall = max(res[r]["dt_pull_pipe"] for r in range(1, world))
    pipe_ops = round(pipe_total / pipe_wall, 1)
    emit({"metric": "ps_wire_pull_ops_per_s", "value": pipe_ops,
          "unit": "ops/s",
          "MB_s": round(pipe_total * row_bytes / pipe_wall / 1e6, 1),
          "batch": BATCH, "dim": DIM, "clients": NCLIENTS,
          "depth": DEPTH, "pipelined": True,
          "native_table": native_engaged})

    push_ops, push_mb = rate(res[1]["dt_push_sync"])
    emit({"metric": "ps_push_sync_ops_per_s", "value": push_ops,
          "unit": "ops/s", "MB_s": push_mb, "batch": BATCH, "dim": DIM,
          "clients": 1, "native_table": native_engaged})

    apush_total = OPS * NCLIENTS
    apush_wall = max(res[r]["dt_push_async"] for r in range(1, world))
    emit({"metric": "ps_push_async_ops_per_s",
          "value": round(apush_total / apush_wall, 1), "unit": "ops/s",
          "MB_s": round(apush_total * row_bytes / apush_wall / 1e6, 1),
          "batch": BATCH, "dim": DIM, "clients": NCLIENTS,
          "depth": DEPTH, "coalesced": True,
          "native_table": native_engaged})

    for row in _parity_rows():
        emit(row)

    # server-side observability (ISSUE 3): the "done" snapshot's totals
    # must match the client-side op counts EXACTLY — warm-up pulls
    # (1/client) + sync pulls (OPS) + pipelined pulls (OPS/client), and
    # sync (OPS) + async (OPS/client) pushes, each of BATCH rows. The
    # same totals fetched over the control plane the way
    # tools/ps_stats.py does prove the live-poll path.
    stats_phases = res[0].get("stats_phases") or {}
    final = stats_phases.get("done") or {}
    cli = res[0].get("stats_cli") or {}
    exp_pull_rows = BATCH * (NCLIENTS + OPS + OPS * NCLIENTS)
    exp_push_rows = BATCH * OPS * (1 + NCLIENTS)
    wire = final.get("wire", {})
    cli_wire = cli.get("wire", {})
    emit({"metric": "ps_stats_consistency",
          "value": int(wire.get("pull_rows") == exp_pull_rows and
                       wire.get("push_rows") == exp_push_rows and
                       cli_wire.get("pull_rows") == exp_pull_rows and
                       cli_wire.get("push_rows") == exp_push_rows),
          "unit": "bool",
          "expected_pull_rows": exp_pull_rows,
          "server_pull_rows": wire.get("pull_rows"),
          "cli_pull_rows": cli_wire.get("pull_rows"),
          "expected_push_rows": exp_push_rows,
          "server_push_rows": wire.get("push_rows"),
          "cli_push_rows": cli_wire.get("push_rows"),
          "server_coalesced_dup_rows":
              (final.get("tables", {}).get("emb", {})
                    .get("push_coalesced_rows")),
          "server_async_merged_frames":
              wire.get("async_push_merged_frames", 0)})

    # ISSUE 17 cycles-per-request column: event-thread CPU per wire op
    # from the new cpu_us counter (None on a pre-r17 .so)
    total_wire_ops = wire.get("pull_ops", 0) + wire.get("push_ops", 0)
    cpu = wire.get("cpu_us")
    emit({"metric": "ps_cpu_us_per_op",
          "value": (None if cpu is None or not total_wire_ops
                    else round(cpu / total_wire_ops, 2)),
          "unit": "us/op", "pull_ops": wire.get("pull_ops"),
          "push_ops": wire.get("push_ops"), "cpu_us": cpu,
          "native_table": native_engaged})

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "ps_bench", "vocab": VOCAB, "dim": DIM,
                       "batch": BATCH, "ops": OPS,
                       "clients": NCLIENTS, "depth": DEPTH,
                       "measurements": RESULTS,
                       "server_stats_phases": stats_phases}, f,
                      indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
