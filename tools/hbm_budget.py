#!/usr/bin/env python
"""Analytic peak-HBM model for the hybrid train step + XLA validation.

VERDICT r4 item 3 / weak 6: the tiny-shape multichip dryrun proves every
axis combo compiles, but an OOM-shaped bug (r4's ERNIE single-jit
offload counting the whole optimizer state against peak HBM) is
invisible at hidden=64. This tool closes that hole WITHOUT hardware:

  1. `estimate(cfg, ...)` — closed-form per-chip peak-HBM for
     `models.gpt.build_train_step` (params/grads/slots by zero stage,
     param dtype, offload chunk window; activation residency by remat
     policy; chunked-CE logits).
  2. `validate_scaled()` — compiles the REAL step at a scaled config on
     a virtual 8-device CPU mesh, reads XLA's CompiledMemoryStats, and
     asserts the analytic model is within a factor of 2.5 of XLA's
     number. A residency bug (offloaded slots living on device, remat
     not applied, logits unchunked) shows up as a big ratio break HERE,
     at megabyte scale, before any TPU time is spent.
  3. `main()` — after validation, evaluates the model at ERNIE-10B on
     the intended v5e-16 split and on the single-chip offload ladder
     sizes, asserting each fits its HBM budget. Prints one JSON line
     per verdict.

Run: python tools/hbm_budget.py
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

V5E_HBM = 16e9   # bytes per chip


def param_count(cfg) -> float:
    d, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    ffn = cfg.ffn_hidden
    per_block = 4 * d * d + 2 * d * ffn + 9 * d  # qkv+out, mlps, ln/bias
    emb = V * d + cfg.max_position_embeddings * d
    return L * per_block + emb + 2 * d            # final LN


def estimate(cfg, *, batch: int, seq: int, tp: int = 1, shard: int = 1,
             zero_stage: int = 2, offload: bool = False,
             param_dtype_bytes: int = 4, multi_precision: bool = False,
             remat: str = "full", loss_chunks: int = 8) -> dict:
    """Per-chip peak-HBM breakdown in bytes for one train step.

    Mirrors build_train_step's residency rules (models/gpt.py):
      params rest sharded over tp x (shard if zero3);
      grads mirror params;
      AdamW slots (m, v fp32) + optional fp32 masters shard over
      tp x shard, or rest on HOST under offload (up to ~2 chunks of
      `_OFFLOAD_CHUNK_BYTES` transiently on device — the documented
      in-flight window);
      activations: remat 'full' keeps one [b_local, s, d] residual per
      layer plus one layer's working set; 'dots' additionally keeps the
      weight-matmul outputs (~4 more [b,s,d]-class tensors per layer);
      chunked CE materializes [b_local, s/chunks, V] fp32 logits once.
    """
    from paddle_tpu.models.gpt import _OFFLOAD_CHUNK_BYTES

    P = param_count(cfg)
    d, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    act_bytes = 2 if param_dtype_bytes == 2 or _cfg_bytes(cfg) == 2 else 4
    b_local = max(1, batch)   # caller passes the PER-CHIP batch

    param_shard = tp * (shard if zero_stage >= 3 else 1)
    params = param_dtype_bytes * P / param_shard
    grads = param_dtype_bytes * P / param_shard
    slot_bytes = 8 * P + (4 * P if multi_precision else 0)
    if offload:
        slots = 2 * _OFFLOAD_CHUNK_BYTES      # in-flight chunk window
    else:
        slots = slot_bytes / (tp * shard)

    resid = L * b_local * seq * d * act_bytes            # per-layer saves
    if remat == "dots":
        resid *= 5    # qkv/out/mlp matmul outputs also saved
    working = b_local * seq * (4 * d + 2 * cfg.ffn_hidden) * act_bytes / tp
    logits = b_local * seq * V * 4 / max(loss_chunks, 1) / tp
    total = params + grads + slots + resid + working + logits
    return {"params": params, "grads": grads, "slots": slots,
            "activations": resid + working, "logits": logits,
            "total": total}


def _cfg_bytes(cfg):
    import jax.numpy as jnp
    return 2 if cfg.dtype == jnp.bfloat16 else 4


def _compile_peak(num_layers: int) -> float:
    """XLA per-device peak (args + temps; outputs alias donated args on
    TPU) for the REAL step at a scaled config on 8 virtual CPU devs."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import (GPTConfig, GPTForPretraining,
                                   build_train_step)

    cfg = GPTConfig(vocab_size=4096, hidden_size=256,
                    num_layers=num_layers, num_heads=8,
                    max_position_embeddings=512)
    mesh = build_mesh(sharding=4, mp=2)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4)
    step, state = build_train_step(model, opt, mesh, remat=True,
                                   remat_policy="full", loss_chunks=8,
                                   zero_stage=3)
    B, S = 8, 512
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    ma = step.lower(state, (ids, labels)).compile().memory_analysis()
    return float(ma.argument_size_in_bytes + ma.temp_size_in_bytes)


def validate_scaled():
    """Two-point layer sweep of the REAL compiled step.

    XLA peak is affine in L: a vocab-dependent base (embedding vjp,
    logits chunks, one layer's working set — reused across the scan)
    plus a per-layer slope (params + grads + slots + the remat residual
    save). The SLOPE is what extrapolates to 10B-class sizes, and it is
    exactly where the r4 OOM class lives (slots resident despite
    offload => slope jumps ~3x; remat not applied => slope gains the
    full per-layer activation set). Returns
    (slope_ratio, xla_slope_mb_per_layer, analytic_slope_mb_per_layer).
    """
    p8, p16 = _compile_peak(8), _compile_peak(16)
    xla_slope = (p16 - p8) / 8.0

    from paddle_tpu.models import GPTConfig
    cfg = GPTConfig(vocab_size=4096, hidden_size=256, num_layers=8,
                    num_heads=8, max_position_embeddings=512)
    e8 = estimate(cfg, batch=2, seq=512, tp=2, shard=4, zero_stage=3,
                  remat="full", loss_chunks=8, param_dtype_bytes=4)
    cfg16 = GPTConfig(vocab_size=4096, hidden_size=256, num_layers=16,
                      num_heads=8, max_position_embeddings=512)
    e16 = estimate(cfg16, batch=2, seq=512, tp=2, shard=4, zero_stage=3,
                   remat="full", loss_chunks=8, param_dtype_bytes=4)
    analytic_slope = (e16["total"] - e8["total"]) / 8.0
    return xla_slope / analytic_slope, xla_slope, analytic_slope


def main():
    import jax  # noqa: F401  (forces the CPU platform config below)

    ratio, xla_slope, analytic_slope = validate_scaled()
    ok = 0.6 <= ratio <= 2.0
    print(json.dumps({"metric": "hbm_model_vs_xla_layer_slope_ratio",
                      "value": round(ratio, 3),
                      "xla_mb_per_layer": round(xla_slope / 1e6, 2),
                      "analytic_mb_per_layer":
                          round(analytic_slope / 1e6, 2),
                      "ok": ok}))
    assert ok, (
        f"analytic HBM layer slope diverged from XLA ({ratio:.2f}x) — "
        "a residency bug (slots on device despite offload, remat not "
        "applied) or model drift; fix before trusting the 10B budgets")

    from paddle_tpu.models import ernie_10b, gpt_2p6b
    # intended pod split for config 5: v5e-16, zero3 sharding=8 x tp=2,
    # bf16 params + fp32 masters offloaded to host
    cfg = ernie_10b()
    est = estimate(cfg, batch=1, seq=2048, tp=2, shard=8, zero_stage=3,
                   offload=True, param_dtype_bytes=2,
                   multi_precision=True, remat="full", loss_chunks=16)
    fits = est["total"] <= V5E_HBM
    print(json.dumps({"metric": "ernie10b_v5e16_peak_hbm_gb",
                      "value": round(est["total"] / 1e9, 2),
                      "budget_gb": 16.0, "fits": fits,
                      "breakdown_gb": {k: round(v / 1e9, 2)
                                       for k, v in est.items()}}))
    assert fits, "10B does not fit the v5e-16 split — rethink the plan"

    # single-chip offload ladder point: 2.6B bf16 + host masters
    cfg = gpt_2p6b()
    est = estimate(cfg, batch=1, seq=1024, tp=1, shard=1, zero_stage=2,
                   offload=True, param_dtype_bytes=2,
                   multi_precision=True, remat="full", loss_chunks=8)
    fits = est["total"] <= V5E_HBM
    print(json.dumps({"metric": "ernie2p6b_1chip_offload_peak_hbm_gb",
                      "value": round(est["total"] / 1e9, 2),
                      "budget_gb": 16.0, "fits": fits}))
    assert fits, "2.6B offload exceeds one v5e chip — ladder is wrong"


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    os.environ["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])
    import jax
    jax.config.update("jax_platforms", "cpu")
    main()
