#!/bin/bash
# Round-4 TPU measurement queue. Run when the axon tunnel is healthy.
# Each item is an isolated subprocess with a hard timeout; results
# persist to BENCH_PARTIAL.json via bench.py's checkpointing, and this
# script's log captures everything else. Safe to re-run — bench items
# overwrite their own entries.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_queue.log
echo "=== tpu_queue $(date -u +%FT%TZ) ===" | tee -a "$LOG"

probe() {
  timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

run_item() {
  local name="$1" tmo="$2"; shift 2
  echo "--- $name ($(date -u +%T)) ---" | tee -a "$LOG"
  timeout "$tmo" "$@" >>"$LOG" 2>&1
  local rc=$?
  echo "--- $name rc=$rc ---" | tee -a "$LOG"
  return $rc
}

if ! probe; then
  echo "tunnel down; aborting" | tee -a "$LOG"
  exit 1
fi

# 1. BERT (masked_positions fix) — expect minutes, not a 20-min spill
run_item bert 900 env PTPU_BENCH_ONLY=bert python bench.py

# 2. Config 5 ladder, ASCENDING: bank the known-good 760M number first
# (a bigger size can wedge the tunnel and cost the rest of the window),
# then climb 1.3B -> 2.6B (bf16 + fp32 host masters), probing between
run_item ernie_0p76b 1200 env PTPU_BENCH_ONLY=ernie:0p76b python bench.py
probe || { echo "tunnel died after 0p76b" | tee -a "$LOG"; exit 1; }
if run_item ernie_1p3b 1800 env PTPU_BENCH_ONLY=ernie:1p3b python bench.py; then
  probe || { echo "tunnel died after 1p3b" | tee -a "$LOG"; exit 1; }
  run_item ernie_2p6b 1800 env PTPU_BENCH_ONLY=ernie:2p6b python bench.py
fi

probe || { echo "tunnel died" | tee -a "$LOG"; exit 1; }

# 3. ResNet stems A/B at the two best batches
run_item resnet_s2d_256 900 env PTPU_BENCH_ONLY=resnet:256 python bench.py
run_item resnet_s2d_512 900 env PTPU_BENCH_ONLY=resnet:512 python bench.py
run_item resnet_conv_256 900 env PTPU_BENCH_RESNET_STEM=conv \
  PTPU_BENCH_ONLY=resnet:256 python bench.py

# 4. Decomposition profile (batch 256)
run_item conv_profile 1200 python tools/conv_profile.py 256

# 5. YOLO + GPT headline re-bank (freshest hardware rows for r5)
run_item yolo_48 900 env PTPU_BENCH_ONLY=yolo:48 python bench.py
run_item gpt_base 900 env PTPU_BENCH_ONLY=gpt python bench.py

# 6. flash-attention vs XLA A/B at 2k/8k (VERDICT r4 item 10): backs
# the kernel docstring claims with on-chip numbers
run_item flash_ab 1200 python -m paddle_tpu.tools.op_bench \
  --ops flash_attn_2k,xla_attn_2k,flash_attn_8k,xla_attn_8k \
  --out flash_ab_tpu.json

echo "=== queue done $(date -u +%FT%TZ) ===" | tee -a "$LOG"
