#!/usr/bin/env python3
"""covcheck — gcov line-coverage gate for the native runtime
(`make -C csrc covcheck`; reference: the coverage thresholds upstream
CI enforces per directory).

The tree's correctness tooling (selftests, fuzz corpus, the schedck
model checker) is only as good as the lines it actually executes, so
coverage is a checked floor, not a dashboard: this script builds each
measurement unit with COV=1 (--coverage -O0; .cov-suffixed binaries,
never clobbering production artifacts), runs it, harvests
`gcov -t --json-format`, merges the per-source-file line counts
across units, and asserts the FLOORS table — the hot contract files
(ptpu_wire.h and its users, ptpu_net.cc, ptpu_sync.h) must keep their
measured line coverage. A new parser branch nobody tests drops the
percentage and fails the gate.

One unit is built and harvested AT A TIME: gcov names its .gcno/.gcda
after the SOURCE file, so two binaries compiling the same TU clobber
each other's counters if built side by side.

The merged result is written to csrc/covcheck_report.json (the CI
artifact tests/test_covcheck.py validates).

Usage:
  python3 tools/covcheck.py            # full gate (builds, runs, asserts)
  python3 tools/covcheck.py --report-only   # re-assert an existing report
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC = os.path.join(REPO, "csrc")
REPORT = os.path.join(CSRC, "covcheck_report.json")

# (make target, run argv relative to csrc) — each unit is one binary.
# The serving selftest is deliberately absent: its two-instance
# scaling assertion needs >1 core, and an abort() loses the .gcda
# (gcov flushes at exit) — its wire paths are credited by the
# wire_serving corpus replay instead.
UNITS: List[Tuple[str, List[str]]] = [
    ("ptpu_net_selftest.cov", ["./ptpu_net_selftest.cov"]),
    ("ptpu_ps_selftest.cov", ["./ptpu_ps_selftest.cov"]),
    ("ptpu_schedck_selftest.cov", ["./ptpu_schedck_selftest.cov"]),
    ("fuzz/fuzz_wire_ps.cov.fuzz",
     ["./fuzz/fuzz_wire_ps.cov.fuzz", "fuzz/corpus/wire_ps"]),
    ("fuzz/fuzz_wire_serving.cov.fuzz",
     ["./fuzz/fuzz_wire_serving.cov.fuzz", "fuzz/corpus/wire_serving"]),
    ("fuzz/fuzz_frames.cov.fuzz",
     ["./fuzz/fuzz_frames.cov.fuzz", "fuzz/corpus/frames"]),
    ("fuzz/fuzz_http.cov.fuzz",
     ["./fuzz/fuzz_http.cov.fuzz", "fuzz/corpus/http"]),
    # r19: the spill/hibernation/prefix-persist parsers route through
    # the ptpu_wire.h codecs; without this replay their codec lines
    # are instantiated (via ptpu_spill.h) but never credited.
    ("fuzz/fuzz_spill.cov.fuzz",
     ["./fuzz/fuzz_spill.cov.fuzz", "fuzz/corpus/spill"]),
    # r20: the json corpus replay drives both restricted-grammar
    # consumers — PromFromStatsJson and the ptpu_invar evaluator
    # (CheckJson over every input + ViolationCount over its report);
    # the selftests only credit invar's quiesce paths.
    ("fuzz/fuzz_json.cov.fuzz",
     ["./fuzz/fuzz_json.cov.fuzz", "fuzz/corpus/json"]),
]

# Minimum line coverage (percent of executable lines executed) per
# source file, merged across all units. Measured headroom is kept
# above each floor so routine edits don't trip it, but a tested-never
# subsystem landing in one of these files will.
FLOORS: Dict[str, float] = {
    "ptpu_wire.h": 90.0,      # measured 97.6 at introduction
    "ptpu_net.cc": 72.0,      # measured 79.6
    "ptpu_sync.h": 65.0,      # measured 73.4
    "ptpu_ps_server.cc": 75.0,  # measured 87.4
    "ptpu_serving.cc": 45.0,  # measured 52.0
    "ptpu_invar.cc": 80.0,    # measured at r20 introduction
}


def parse_gcov_json(text: str) -> Dict[str, Dict[int, int]]:
    """Parse `gcov -t --json-format` output (one JSON document per
    line, one per .gcda) into {source file: {line: count}}."""
    out: Dict[str, Dict[int, int]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        doc = json.loads(line)
        for f in doc.get("files", []):
            name = os.path.basename(f.get("file", ""))
            if not name:
                continue
            dst = out.setdefault(name, {})
            for rec in f.get("lines", []):
                ln = rec["line_number"]
                dst[ln] = max(dst.get(ln, 0), rec.get("count", 0))
    return out


def merge_counts(into: Dict[str, Dict[int, int]],
                 unit: Dict[str, Dict[int, int]]) -> None:
    """Union of executable lines; a line is covered if ANY unit ran
    it (max of counts)."""
    for name, lines in unit.items():
        dst = into.setdefault(name, {})
        for ln, cnt in lines.items():
            dst[ln] = max(dst.get(ln, 0), cnt)


def coverage_pct(lines: Dict[int, int]) -> float:
    if not lines:
        return 0.0
    hit = sum(1 for c in lines.values() if c > 0)
    return 100.0 * hit / len(lines)


def check_floors(merged: Dict[str, Dict[int, int]],
                 floors: Dict[str, float]) -> List[str]:
    """Return human-readable failures (empty == gate passes)."""
    failures = []
    for name, floor in sorted(floors.items()):
        lines = merged.get(name)
        if lines is None:
            failures.append(
                f"{name}: no coverage data harvested (floor "
                f"{floor:.0f}%) — did its measurement unit run?")
            continue
        pct = coverage_pct(lines)
        if pct < floor:
            failures.append(
                f"{name}: line coverage {pct:.1f}% is below the "
                f"{floor:.0f}% floor")
    return failures


def build_report(merged: Dict[str, Dict[int, int]],
                 floors: Dict[str, float]) -> dict:
    files = {}
    for name, lines in sorted(merged.items()):
        hit = sum(1 for c in lines.values() if c > 0)
        files[name] = {
            "executable_lines": len(lines),
            "executed_lines": hit,
            "pct": round(coverage_pct(lines), 2),
        }
    failures = check_floors(merged, floors)
    return {
        "schema": "ptpu-covcheck-report v1",
        "floors": floors,
        "files": files,
        "failures": failures,
        "pass": not failures,
    }


def _clean_gcda() -> None:
    # counters only — the .gcno notes files are compile-time artifacts
    # that pair with the (possibly warm) .cov binaries; removing them
    # without forcing a rebuild would leave gcov unable to attribute
    # the next run's counters. `make -C csrc clean` removes both.
    for pat in ("*.gcda", os.path.join("fuzz", "*.gcda")):
        for p in glob.glob(os.path.join(CSRC, pat)):
            os.remove(p)


def run_units(jobs: int) -> Dict[str, Dict[int, int]]:
    merged: Dict[str, Dict[int, int]] = {}
    for target, argv in UNITS:
        _clean_gcda()
        subprocess.run(["make", "-C", CSRC, f"-j{jobs}", target,
                        "COV=1"], check=True)
        subprocess.run(argv, cwd=CSRC, check=True,
                       stdout=subprocess.DEVNULL)
        gcda = sorted(glob.glob(os.path.join(CSRC, "*.gcda")) +
                      glob.glob(os.path.join(CSRC, "fuzz", "*.gcda")))
        if not gcda:
            raise RuntimeError(f"unit {target}: no .gcda produced")
        r = subprocess.run(["gcov", "-t", "--json-format"] + gcda,
                           cwd=CSRC, check=True, capture_output=True,
                           text=True)
        merge_counts(merged, parse_gcov_json(r.stdout))
    _clean_gcda()
    return merged


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("-j", "--jobs", type=int, default=2)
    ap.add_argument("--report-only", action="store_true",
                    help="re-assert the floors against an existing "
                         "csrc/covcheck_report.json (no build/run)")
    args = ap.parse_args(argv)

    if args.report_only:
        with open(REPORT) as f:
            report = json.load(f)
        failures = report.get("failures", ["report carries no "
                                           "failures field"])
    else:
        merged = run_units(args.jobs)
        report = build_report(merged, FLOORS)
        with open(REPORT, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        failures = report["failures"]

    for name in sorted(FLOORS):
        entry = report["files"].get(name)
        shown = (f"{entry['pct']:5.1f}% "
                 f"({entry['executed_lines']}/"
                 f"{entry['executable_lines']} lines)"
                 if entry else "no data")
        print(f"covcheck: {name:<18} {shown}  floor "
              f"{FLOORS[name]:.0f}%")
    if failures:
        for msg in failures:
            print(f"covcheck: FAIL {msg}", file=sys.stderr)
        return 1
    print(f"covcheck: PASS — report at {os.path.relpath(REPORT, REPO)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
