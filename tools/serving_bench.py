#!/usr/bin/env python
"""Concurrent serving throughput bench (ISSUE r8 tentpole acceptance).

One C-hosted serving runtime (csrc/ptpu_serving.cc) serves the MLP
artifact; NCLIENTS closed-loop client PROCESSES hammer it over the
framed HMAC TCP data plane. Three phases, each against a FRESH server
so counters isolate:

  1. seq_batch1          — 1 client, 1 request in flight, server
                           max_batch=1 (batching off): the sequential
                           baseline every speedup is measured against;
  2. concurrent_nobatch  — NCLIENTS clients, max_batch=1: instance
                           parallelism only;
  3. concurrent_batched  — NCLIENTS clients, dynamic batching on: the
                           headline. Acceptance: >= 3x phase 1 ops/s.

Server-side counters are cross-checked against client-observed counts
EXACTLY (requests == replies == clients x ops, zero errors), the same
discipline as tools/ps_bench.py. Client processes import the serving
client module standalone (no jax) so process startup stays light.

Config via env: PTPU_SRVBENCH_{CLIENTS,OPS,MAX_BATCH,DEADLINE_US,
INSTANCES,THREADS} (tests/test_serving_bench_persist.py runs a
shrunken 2-client config). Run:
  python tools/serving_bench.py [--out BENCH_SERVE_rNN.json]
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# every bench server Stop() doubles as a hard conservation gate: a
# counter-ledger violation (ISSUE 20) aborts instead of reporting
os.environ.setdefault("PTPU_INVAR_FATAL", "1")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from drill_replay import host_meta  # noqa: E402  (one fingerprint impl)


def invar_gate(snapshot, where):
    """Declarative counter-conservation gate (csrc/ptpu_invar.h) at a
    bench quiesce point. Replaces the per-bench replies/err-ledger
    arithmetic — the bench keeps only CLIENT-vs-server cross-checks
    (requests == client ops), the algebra among server counters is
    the manifest's job. Lazy import: client subprocesses never pay
    for the paddle_tpu package."""
    from paddle_tpu.profiler.stats import invar_assert
    invar_assert(snapshot, where)

NCLIENTS = int(os.environ.get("PTPU_SRVBENCH_CLIENTS", 8))
OPS = int(os.environ.get("PTPU_SRVBENCH_OPS", 300))
# match the closed-loop client count: with max_batch <= in-flight
# requests, steady-state flushes are FULL (no deadline wait); a larger
# max_batch would wait the deadline for rows that can never arrive
MAX_BATCH = int(os.environ.get("PTPU_SRVBENCH_MAX_BATCH", NCLIENTS))
DEADLINE_US = int(os.environ.get("PTPU_SRVBENCH_DEADLINE_US", 2000))
INSTANCES = int(os.environ.get("PTPU_SRVBENCH_INSTANCES", 2))
THREADS = int(os.environ.get("PTPU_SRVBENCH_THREADS", 0))
WARM = max(4, OPS // 20)

RESULTS: list = []


def emit(row: dict):
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def build_native():
    import subprocess
    if os.environ.get("PTPU_SRVBENCH_SKIP_BUILD"):
        return  # smoke tests run on the suite's portable build
    try:
        subprocess.run(["make", "-B", "all", "MARCH=-march=native"],
                       cwd=os.path.join(REPO, "csrc"), check=True,
                       capture_output=True, timeout=600)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"# native rebuild skipped ({e}); using existing .so",
              file=sys.stderr)


def build_mlp_artifact(tmp):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.onnx.converter import trace_to_onnx

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 512))
    net.eval()
    x = np.zeros((1, 512), np.float32)
    path = os.path.join(tmp, "mlp.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(lambda a: net(a), (jnp.asarray(x),)))
    return path


def _client(rank, port, authkey, ops, warm, cols, barrier, q):
    """Closed-loop client process. Loads the serving client module
    STANDALONE (socket + numpy only) — no paddle_tpu/jax import."""
    import importlib.util
    import numpy as np

    spec = importlib.util.spec_from_file_location(
        "ptpu_sv_client",
        os.path.join(REPO, "paddle_tpu", "inference", "serving.py"))
    sv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sv)

    cli = sv.InferenceClient(port, authkey)
    x = np.random.RandomState(rank).randn(1, cols).astype(np.float32)
    for _ in range(warm):
        cli.infer(x)
    barrier.wait(timeout=600)   # A: everyone warm; parent resets stats
    barrier.wait(timeout=600)   # B: measure starts
    t0 = time.perf_counter()
    for _ in range(ops):
        cli.infer(x)
    dt = time.perf_counter() - t0
    q.put({"rank": rank, "dt": dt, "ops": ops})
    barrier.wait(timeout=600)   # C: all replies in; parent snapshots
    cli.close()


def run_phase(model_path, clients, ops, max_batch, deadline_us,
              cols=512):
    import resource

    from paddle_tpu.inference.serving import create_server

    srv = create_server(model_path, max_batch=max_batch,
                        deadline_us=deadline_us, instances=INSTANCES,
                        threads_per_instance=THREADS)
    barrier = mp.Barrier(clients + 1)
    q: "mp.Queue" = mp.Queue()
    ps = [mp.Process(target=_client,
                     args=(r, srv.port, srv.authkey, ops, WARM, cols,
                           barrier, q))
          for r in range(clients)]
    for p in ps:
        p.start()
    barrier.wait(timeout=600)   # A: clients warm
    srv.stats_reset()
    # server CPU per request (ISSUE 17): the server's native threads
    # live in THIS process, the clients in their own — a
    # getrusage(SELF) delta over the measured window divided by the
    # request count is server CPU/request on ANY .so build (the
    # /statsz cpu_us counters only exist on the new one)
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    barrier.wait(timeout=600)   # B: go
    res = [q.get(timeout=600) for _ in range(clients)]
    barrier.wait(timeout=600)   # C: counters final
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    stats = srv.stats()
    config = srv.config()
    for p in ps:
        p.join(timeout=60)
    srv.stop()
    wall = max(r["dt"] for r in res)
    total = sum(r["ops"] for r in res)
    host_cpu_us = ((ru1.ru_utime - ru0.ru_utime) +
                   (ru1.ru_stime - ru0.ru_stime)) * 1e6
    return total / wall, stats, config, total, host_cpu_us / total


def _cpu_cols(stats, total, host_cpu_per_req):
    """The two cycles-per-request columns every phase row carries:
    /statsz cpu_us (serving + decode planes; None on a pre-r17 .so)
    and the host rusage measurement."""
    sv = stats["server"]
    cpu = sv.get("cpu_us")
    if cpu is not None:
        cpu += (stats.get("decode") or {}).get("cpu_us", 0)
    return {"sv_cpu_us_per_req":
                None if cpu is None else round(cpu / max(1, total), 2),
            "host_cpu_us_per_req": round(host_cpu_per_req, 2)}


# ---------------------------------------------------------------------------
# --trace: tracing-on/off overhead A/B (ISSUE 10 acceptance gate).
#
# Two hot paths, each run OFF/ON interleaved (2 rounds) in ONE session
# so machine drift cancels: the serving concurrent-batched phase (the
# r8 headline) and a single-process pipelined PS wire pull loop (the
# bandwidth-bound plane). "On" is the DEFAULT sampling config
# (PTPU_TRACE_SAMPLE=64, PTPU_TRACE_SLOW_US=100000) — what production
# pays; acceptance: on within 3% of off, counters still exact.
# ---------------------------------------------------------------------------

PULL_OPS = int(os.environ.get("PTPU_TRBENCH_PULL_OPS", 8000))
PULL_ROWS = int(os.environ.get("PTPU_TRBENCH_PULL_ROWS", 512))
PULL_DEPTH = int(os.environ.get("PTPU_TRBENCH_PULL_DEPTH", 8))


def _ps_pull_connect(port, authkey):
    """Handshaken raw socket for the pull legs. ONE connection serves
    every off/on leg: a fresh dial per leg lands on a different event
    thread each time (round-robin loop assignment), and thread
    placement moves single-conn throughput by >±10% on this box —
    keeping the conn fixed makes the A/B genuinely paired."""
    import hashlib
    import hmac
    import socket
    import struct

    s = socket.create_connection(("127.0.0.1", port), 10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    nonce = _read_n(s, 16)
    mac = hmac.new(authkey, nonce, hashlib.sha256).digest()
    s.sendall(struct.pack("<I", len(mac)) + mac)
    assert _read_n(s, 1) == b"\x01"
    return s


def _ps_pull_ops_per_s(s, ops, rows, depth):
    """Pipelined fast-frame pulls over an open raw socket (the
    ps_bench pipelined-pull shape, single process)."""
    import struct

    import numpy as np
    from paddle_tpu.distributed.ps import wire

    req = bytes(wire.build_pull_req("emb", np.arange(rows)))
    frame = struct.pack("<I", len(req)) + req

    def read_reply():
        n = struct.unpack("<I", _read_n(s, 4))[0]
        _read_n(s, n)

    warm = min(64, ops // 4)
    for _ in range(warm):
        s.sendall(frame)
        read_reply()
    t0 = time.perf_counter()
    sent = 0
    while sent < depth and sent < ops:
        s.sendall(frame)
        sent += 1
    done = 0
    while done < ops:
        read_reply()
        done += 1
        if sent < ops:
            s.sendall(frame)
            sent += 1
    dt = time.perf_counter() - t0
    return ops / dt


def _read_n(sock, n):
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        if not c:
            raise ConnectionError("peer closed")
        buf += c
    return buf


def run_trace_ab(out_path):
    import tempfile

    from paddle_tpu.core import native as N

    build_native()
    sv_lib = N._predictor_lib()
    ps_lib = N._ps_load()
    configs = [("off", (0, 0)), ("on", (64, 100000))]
    rounds = int(os.environ.get("PTPU_TRBENCH_ROUNDS", 4))
    results = {"serving_batched": {"off": [], "on": []},
               "ps_pipelined_pull": {"off": [], "on": []}}
    exact = []

    with tempfile.TemporaryDirectory() as tmp:
        model = build_mlp_artifact(tmp)
        # PS table served once; only the tracing knob flips per leg
        srv_key = b"trace-bench-key"
        ps_srv = N.PsDataServer(0, srv_key)
        tbl = N.NativePsTable(max(PULL_ROWS * 4, 4096), 64,
                              optimizer="sgd", lr=0.1)
        ps_srv.register("emb", tbl, 0)
        # each plane's off/on legs run back-to-back with nothing in
        # between (an 8-process serving phase perturbs thread placement
        # enough to swamp the signal if a pull leg follows it), and the
        # pair ORDER ALTERNATES per round — session drift on this box
        # is a slow ramp (±10% per leg), and fixed ordering aliases it
        # straight into the A/B; alternation cancels the linear part
        # the pull legs run FIRST: an 8-process serving phase perturbs
        # scheduler state for long after it exits, and the single-conn
        # pull loop is the most placement-sensitive measurement here.
        # One unrecorded warm leg (cold caches bias whichever config
        # runs first), then `rounds` recorded off/on pairs — all over
        # the SAME connection (see _ps_pull_connect)
        psock = _ps_pull_connect(ps_srv.port, srv_key)
        ps_lib.ptpu_trace_set(0, 0)
        _ps_pull_ops_per_s(psock, PULL_OPS, PULL_ROWS, PULL_DEPTH)
        for rnd in range(rounds):
            for name, (sample, slow) in (configs if rnd % 2 == 0
                                         else configs[::-1]):
                ps_lib.ptpu_trace_set(sample, slow)
                pull = _ps_pull_ops_per_s(psock, PULL_OPS, PULL_ROWS,
                                          PULL_DEPTH)
                results["ps_pipelined_pull"][name].append(
                    round(pull, 1))
        psock.close()
        ps_srv.stop()
        for rnd in range(rounds):
            for name, (sample, slow) in (configs if rnd % 2 == 0
                                         else configs[::-1]):
                sv_lib.ptpu_trace_set(sample, slow)
                ops, stats, _, total, _ = run_phase(
                    model, clients=NCLIENTS, ops=OPS,
                    max_batch=MAX_BATCH, deadline_us=DEADLINE_US)
                results["serving_batched"][name].append(round(ops, 1))
                sv = stats["server"]
                invar_gate(stats, f"serving_{name}_r{rnd}")
                exact.append({"leg": f"serving_{name}_r{rnd}",
                              "expected": total,
                              "requests": sv["requests"],
                              "replies": sv["replies"],
                              "exact": bool(
                                  sv["requests"] == total and
                                  sv["req_errors"] == 0)})
    sv_lib.ptpu_trace_set(64, 100000)
    ps_lib.ptpu_trace_set(64, 100000)

    rows = []
    all_within = True
    for leg, vals in results.items():
        # the phases carry ~±6% per-run session noise on this box
        # (documented across r8-r10 bench_guards), so the 3% gate
        # compares MEANS over the alternating rounds — drift hits both
        # configs equally; best-of is reported alongside
        off = sum(vals["off"]) / len(vals["off"])
        on = sum(vals["on"]) / len(vals["on"])
        overhead = (off - on) / off * 100.0
        within = overhead <= 3.0
        all_within = all_within and within
        row = {"metric": f"trace_ab_{leg}", "unit": "ops/s",
               "off": vals["off"], "on": vals["on"],
               "mean_off": round(off, 1), "mean_on": round(on, 1),
               "best_off": max(vals["off"]),
               "best_on": max(vals["on"]),
               "overhead_pct": round(overhead, 2),
               "acceptance_max_pct": 3.0,
               "within_3pct": bool(within)}
        rows.append(row)
        emit(row)
    emit({"metric": "trace_ab_counters_exact",
          "value": int(all(e["exact"] for e in exact)), "unit": "bool",
          "legs": exact})
    emit({"metric": "trace_ab_within_3pct", "value": int(all_within),
          "unit": "bool"})
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench --trace",
                       "host": host_meta(),
                       "clients": NCLIENTS, "ops": OPS,
                       "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES,
                       "pull": {"ops": PULL_OPS, "rows": PULL_ROWS,
                                "depth": PULL_DEPTH},
                       "trace_on_config": {"sample": 64,
                                           "slow_us": 100000},
                       "rounds": rounds,
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


# ---------------------------------------------------------------------------
# --cpr: cycles-per-request old-vs-new-.so A/B (ISSUE 17 acceptance).
#
# The zero-copy tentpole rewrote the request lifecycle (in-place
# ingestion + scatter replies), so the gated metric is SERVER CPU per
# request at equal throughput, not throughput alone — a closed-loop
# bench on a small box hides CPU savings behind client time. The r10
# A/B methodology, applied to .so builds: the OLD side is built from
# git HEAD in a temp worktree, each leg runs in a fresh SUBPROCESS
# with PTPU_PREDICTOR_SO / PTPU_PS_SO pointing at its side (a loaded
# CDLL can't be swapped in-process), and leg order alternates per
# round so session drift cancels. Every leg reports two CPU columns:
#
#   host_cpu_us_per_req — getrusage(SELF) over the measured window
#       (the server's native threads live in the leg process, the
#       serving clients do not); comparable across .so versions —
#       this is the column the 15% gate reads;
#   sv_cpu_us_per_req   — the new /statsz cpu_us counters (None on
#       the old .so; sanity column on the new).
#
# The serving artifact is wire-weighted (elementwise over wide f32
# rows): a GEMM-heavy model buries the request lifecycle under matmul
# time and cannot observe a wire-path change at all. PS and decode
# legs ride along under the 10% throughput guards.
# ---------------------------------------------------------------------------

CPR_COLS = int(os.environ.get("PTPU_CPRBENCH_COLS", 16384))
CPR_ROUNDS = int(os.environ.get("PTPU_CPRBENCH_ROUNDS", 3))
CPR_DECODE_ROUNDS = int(os.environ.get("PTPU_CPRBENCH_DECODE_ROUNDS",
                                       36))
CPR_PLANES = [p for p in os.environ.get(
    "PTPU_CPRBENCH_PLANES", "serving,ps,decode").split(",") if p]


def build_wire_artifact(tmp):
    """Elementwise y = x + 1 over (1, CPR_COLS) f32 rows: per-request
    bytes dominate per-request FLOPs, so the request lifecycle IS the
    measured work."""
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.onnx.converter import trace_to_onnx

    x = np.zeros((1, CPR_COLS), np.float32)
    path = os.path.join(tmp, "wire.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(lambda a: a + 1.0, (jnp.asarray(x),)))
    return path


def build_decode_artifact(tmp):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       export_gpt_decode, gpt_tiny)

    pt.seed(0)
    cfg = gpt_tiny(dtype=jnp.float32, dropout=0.0)
    model = GPTForPretraining(cfg)
    model.eval()
    return export_gpt_decode(model, os.path.join(tmp, "dec"),
                             batch=8, context=48)


def _ru_us():
    import resource
    r = resource.getrusage(resource.RUSAGE_SELF)
    return (r.ru_utime + r.ru_stime) * 1e6


def run_cpr_leg(plane):
    """One measured leg in THIS process (the parent spawned us with
    PTPU_PREDICTOR_SO / PTPU_PS_SO routing the native load). Prints a
    single `CPRLEG {json}` line for the parent."""
    if plane == "serving":
        model = os.environ["PTPU_CPRLEG_MODEL"]
        ops, stats, _, total, host_cpu = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=MAX_BATCH,
            deadline_us=DEADLINE_US, cols=CPR_COLS)
        sv = stats["server"]
        invar_gate(stats, "cpr_serving_leg")
        out = {"plane": "serving", "ops_per_s": round(ops, 1),
               "exact": bool(sv["requests"] == total and
                             sv["req_errors"] == 0),
               **_cpu_cols(stats, total, host_cpu)}
    elif plane == "ps":
        from paddle_tpu.core import native as N
        key = b"cpr-ps-key"
        srv = N.PsDataServer(0, key)
        tbl = N.NativePsTable(max(PULL_ROWS * 4, 4096), 64,
                              optimizer="sgd", lr=0.1)
        srv.register("emb", tbl, 0)
        s = _ps_pull_connect(srv.port, key)
        # unrecorded warm leg (cold caches bias whichever side is
        # first), then one measured pull loop; the loop's own small
        # internal warm-up is folded into the CPU denominator
        _ps_pull_ops_per_s(s, max(200, PULL_OPS // 8), PULL_ROWS,
                           PULL_DEPTH)
        st0 = (srv.stats() or {}).get("server") or {}
        c0 = _ru_us()
        pull = _ps_pull_ops_per_s(s, PULL_OPS, PULL_ROWS, PULL_DEPTH)
        done_ops = PULL_OPS + min(64, PULL_OPS // 4)
        host = (_ru_us() - c0) / done_ops
        st1 = (srv.stats() or {}).get("server") or {}
        cpu = None
        if "cpu_us" in st1:
            cpu = round((st1["cpu_us"] - st0.get("cpu_us", 0)) /
                        max(1, st1["pull_ops"] - st0.get("pull_ops",
                                                         0)), 2)
        out = {"plane": "ps", "ops_per_s": round(pull, 1),
               "sv_cpu_us_per_req": cpu,
               "host_cpu_us_per_req": round(host, 2),
               "exact": bool(st1.get("proto_errors", 0) == 0 and
                             st1.get("err_frames", 0) == 0)}
        s.close()
        srv.stop()
    elif plane == "decode":
        from paddle_tpu.inference.serving import create_server
        model = os.environ["PTPU_CPRLEG_MODEL"]
        dec = os.environ["PTPU_CPRLEG_DECODE"]
        srv = create_server(model, max_batch=8,
                            deadline_us=DEADLINE_US, instances=1,
                            decode_model=dec)
        cli = srv.client()
        sessions = [cli.decode_open() for _ in range(8)]
        tok = 3
        for _ in range(4):  # warm: plans every step bucket
            cli.decode_step_many([(sess, tok) for sess in sessions])
            tok += 1
        st0 = (srv.stats().get("decode") or {})
        c0 = _ru_us()
        t0 = time.perf_counter()
        steps = 0
        for _ in range(CPR_DECODE_ROUNDS):
            cli.decode_step_many([(sess, tok) for sess in sessions])
            tok += 1
            steps += len(sessions)
        dt = time.perf_counter() - t0
        host = (_ru_us() - c0) / steps
        st1 = (srv.stats().get("decode") or {})
        cpu = None
        if "cpu_us" in st1:
            cpu = round((st1["cpu_us"] - st0.get("cpu_us", 0)) /
                        max(1, steps), 2)
        got = st1.get("steps", 0) - st0.get("steps", 0)
        out = {"plane": "decode", "ops_per_s": round(steps / dt, 1),
               "sv_cpu_us_per_req": cpu,
               "host_cpu_us_per_req": round(host, 2),
               "exact": bool(got == steps)}
        for sess in sessions:
            cli.decode_close(sess)
        cli.close()
        srv.stop()
    else:
        sys.exit(f"unknown cpr leg plane {plane!r}")
    print("CPRLEG " + json.dumps(out), flush=True)


def _cpr_spawn_leg(plane, so_pred, so_ps, extra_env):
    import subprocess
    env = dict(os.environ)
    env.update({"PTPU_PREDICTOR_SO": so_pred, "PTPU_PS_SO": so_ps,
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep +
                              env.get("PYTHONPATH", "")})
    env.update(extra_env)
    r = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--cpr-leg", plane], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=1800)
    if r.returncode != 0:
        sys.exit(f"cpr {plane} leg failed (so={so_pred}):\n"
                 f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("CPRLEG "):
            return json.loads(line[len("CPRLEG "):])
    sys.exit(f"cpr {plane} leg printed no CPRLEG row:\n"
             f"{r.stdout[-2000:]}")


def _build_old_tree(tmp):
    """Build the pre-PR .so pair from git HEAD in a temp worktree."""
    import subprocess
    tree = os.path.join(tmp, "old_tree")
    subprocess.run(["git", "worktree", "add", "--detach", tree,
                    "HEAD"], cwd=REPO, check=True, capture_output=True)
    try:
        subprocess.run(["make", "all", "MARCH=-march=native"],
                       cwd=os.path.join(tree, "csrc"), check=True,
                       capture_output=True, timeout=1200)
    except subprocess.CalledProcessError as e:
        sys.exit(f"old-tree build failed:\n{e.stderr[-2000:]}")
    return (os.path.join(tree, "paddle_tpu", "_native_predictor.so"),
            os.path.join(tree, "paddle_tpu", "_native_ps.so"))


def _cpr_cleanup_worktree(tmp):
    import subprocess
    tree = os.path.join(tmp, "old_tree")
    if os.path.isdir(tree):
        subprocess.run(["git", "worktree", "remove", "--force", tree],
                       cwd=REPO, capture_output=True)


def run_cpr_ab(out_path):
    import tempfile

    build_native()
    new_pred = os.path.join(REPO, "paddle_tpu",
                            "_native_predictor.so")
    new_ps = os.path.join(REPO, "paddle_tpu", "_native_ps.so")
    planes = CPR_PLANES
    with tempfile.TemporaryDirectory() as tmp:
        try:
            # smoke tests point both sides at one build to skip the
            # worktree compile; the real run builds HEAD
            old_pred = os.environ.get("PTPU_CPRBENCH_OLD_PRED_SO")
            old_ps = os.environ.get("PTPU_CPRBENCH_OLD_PS_SO",
                                    new_ps)
            if not old_pred:
                old_pred, old_ps = _build_old_tree(tmp)
            extra = {}
            if "serving" in planes or "decode" in planes:
                extra["PTPU_CPRLEG_MODEL"] = build_wire_artifact(tmp)
            if "decode" in planes:
                extra["PTPU_CPRLEG_DECODE"] = \
                    build_decode_artifact(tmp)
            sides = {"old": (old_pred, old_ps),
                     "new": (new_pred, new_ps)}
            res = {p: {"old": [], "new": []} for p in planes}
            for rnd in range(CPR_ROUNDS):
                order = (["old", "new"] if rnd % 2 == 0
                         else ["new", "old"])
                for plane in planes:
                    for side in order:
                        leg = _cpr_spawn_leg(plane, *sides[side],
                                             extra)
                        res[plane][side].append(leg)
                        print(f"# r{rnd} {plane}/{side}: "
                              f"{leg['ops_per_s']} ops/s, "
                              f"{leg['host_cpu_us_per_req']} cpu us/"
                              f"req", flush=True)
        finally:
            _cpr_cleanup_worktree(tmp)

    def mean(vals):
        return sum(vals) / len(vals)

    all_exact = True
    gates_ok = True
    for plane in planes:
        legs = res[plane]
        all_exact = all_exact and all(
            leg["exact"] for s in ("old", "new") for leg in legs[s])
        old_cpu = mean([leg["host_cpu_us_per_req"]
                        for leg in legs["old"]])
        new_cpu = mean([leg["host_cpu_us_per_req"]
                        for leg in legs["new"]])
        old_ops = mean([leg["ops_per_s"] for leg in legs["old"]])
        new_ops = mean([leg["ops_per_s"] for leg in legs["new"]])
        reduction = (old_cpu - new_cpu) / old_cpu * 100.0
        tp_ratio = new_ops / old_ops
        if plane == "serving":
            # the headline gate: >= 15% less CPU/request at equal
            # (>= 90%) throughput
            ok = reduction >= 15.0 and tp_ratio >= 0.90
        else:
            # guard planes: not slower than the 10% band
            ok = tp_ratio >= 0.90
        gates_ok = gates_ok and ok
        emit({"metric": f"cpr_ab_{plane}", "unit": "us/req",
              "old_host_cpu_us_per_req": round(old_cpu, 2),
              "new_host_cpu_us_per_req": round(new_cpu, 2),
              "new_sv_cpu_us_per_req":
                  legs["new"][-1]["sv_cpu_us_per_req"],
              "cpu_reduction_pct": round(reduction, 2),
              "old_ops_per_s": round(old_ops, 1),
              "new_ops_per_s": round(new_ops, 1),
              "throughput_ratio": round(tp_ratio, 3),
              "rounds": CPR_ROUNDS,
              "old": legs["old"], "new": legs["new"],
              "acceptance": ("cpu_reduction>=15% and tp>=0.9x"
                             if plane == "serving" else "tp>=0.9x"),
              "meets_gate": bool(ok)})
    emit({"metric": "cpr_ab_counters_exact", "value": int(all_exact),
          "unit": "bool"})
    emit({"metric": "cpr_ab_gates", "value": int(gates_ok),
          "unit": "bool"})
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench --cpr",
                       "host": host_meta(),
                       "clients": NCLIENTS, "ops": OPS,
                       "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES, "cols": CPR_COLS,
                       "rounds": CPR_ROUNDS, "planes": planes,
                       "pull": {"ops": PULL_OPS, "rows": PULL_ROWS,
                                "depth": PULL_DEPTH},
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


def main():
    import tempfile

    if "--cpr-leg" in sys.argv:
        run_cpr_leg(sys.argv[sys.argv.index("--cpr-leg") + 1])
        return

    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: serving_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    if "--cpr" in sys.argv:
        run_cpr_ab(out_path)
        return

    if "--trace" in sys.argv:
        run_trace_ab(out_path)
        return

    build_native()
    phases = {}
    with tempfile.TemporaryDirectory() as tmp:
        model = build_mlp_artifact(tmp)

        seq_ops, seq_stats, _, seq_total, seq_cpu = run_phase(
            model, clients=1, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["seq_batch1"] = seq_stats
        emit({"metric": "serve_seq_batch1_ops_per_s",
              "value": round(seq_ops, 1), "unit": "ops/s",
              "clients": 1, "max_batch": 1, "ops": seq_total,
              **_cpu_cols(seq_stats, seq_total, seq_cpu)})

        nb_ops, nb_stats, _, nb_total, nb_cpu = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["concurrent_nobatch"] = nb_stats
        emit({"metric": "serve_concurrent_nobatch_ops_per_s",
              "value": round(nb_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": 1,
              "instances": INSTANCES, "ops": nb_total,
              **_cpu_cols(nb_stats, nb_total, nb_cpu)})

        b_ops, b_stats, b_cfg, b_total, b_cpu = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=MAX_BATCH,
            deadline_us=DEADLINE_US)
        phases["concurrent_batched"] = b_stats
        bb = b_stats["batcher"]
        mean_fill = (bb["batch_fill"]["sum"] /
                     max(1, bb["batch_fill"]["count"]))
        mean_e2e = (bb["e2e_us"]["sum"] / max(1, bb["e2e_us"]["count"]))
        emit({"metric": "serve_concurrent_batched_ops_per_s",
              "value": round(b_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": MAX_BATCH,
              "deadline_us": DEADLINE_US, "instances": INSTANCES,
              "buckets": b_cfg["buckets"], "ops": b_total,
              "mean_batch_fill": round(mean_fill, 2),
              "mean_e2e_us": round(mean_e2e, 1),
              **_cpu_cols(b_stats, b_total, b_cpu)})

        ratio = b_ops / seq_ops
        emit({"metric": "serve_batched_over_seq_ratio",
              "value": round(ratio, 2), "unit": "x",
              "acceptance_min": 3.0, "meets_3x": bool(ratio >= 3.0)})

        # counters vs client-observed counts, EXACT (ps_bench
        # discipline): every measured phase op is one INFER_REQ and
        # the batcher saw each request exactly once. The server-side
        # ledger (replies + error split) is the invar gate's law.
        checks = []
        for name, st, want in (("seq_batch1", seq_stats, seq_total),
                               ("concurrent_nobatch", nb_stats,
                                nb_total),
                               ("concurrent_batched", b_stats,
                                b_total)):
            sv, bt = st["server"], st["batcher"]
            invar_gate(st, name)
            checks.append({
                "phase": name, "expected": want,
                "requests": sv["requests"], "replies": sv["replies"],
                "req_errors": sv["req_errors"],
                "batched_requests": bt["batched_requests"],
                "dynamic_shape_fallback": bt["dynamic_shape_fallback"],
                "exact": bool(sv["requests"] == want and
                              sv["req_errors"] == 0 and
                              bt["batched_requests"] == want)})
        emit({"metric": "serve_stats_consistency",
              "value": int(all(c["exact"] for c in checks)),
              "unit": "bool", "phases": checks})

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench", "clients": NCLIENTS,
                       "host": host_meta(),
                       "ops": OPS, "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES,
                       "measurements": RESULTS,
                       "server_stats_phases": phases}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
