#!/usr/bin/env python
"""Concurrent serving throughput bench (ISSUE r8 tentpole acceptance).

One C-hosted serving runtime (csrc/ptpu_serving.cc) serves the MLP
artifact; NCLIENTS closed-loop client PROCESSES hammer it over the
framed HMAC TCP data plane. Three phases, each against a FRESH server
so counters isolate:

  1. seq_batch1          — 1 client, 1 request in flight, server
                           max_batch=1 (batching off): the sequential
                           baseline every speedup is measured against;
  2. concurrent_nobatch  — NCLIENTS clients, max_batch=1: instance
                           parallelism only;
  3. concurrent_batched  — NCLIENTS clients, dynamic batching on: the
                           headline. Acceptance: >= 3x phase 1 ops/s.

Server-side counters are cross-checked against client-observed counts
EXACTLY (requests == replies == clients x ops, zero errors), the same
discipline as tools/ps_bench.py. Client processes import the serving
client module standalone (no jax) so process startup stays light.

Config via env: PTPU_SRVBENCH_{CLIENTS,OPS,MAX_BATCH,DEADLINE_US,
INSTANCES,THREADS} (tests/test_serving_bench_persist.py runs a
shrunken 2-client config). Run:
  python tools/serving_bench.py [--out BENCH_SERVE_rNN.json]
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NCLIENTS = int(os.environ.get("PTPU_SRVBENCH_CLIENTS", 8))
OPS = int(os.environ.get("PTPU_SRVBENCH_OPS", 300))
# match the closed-loop client count: with max_batch <= in-flight
# requests, steady-state flushes are FULL (no deadline wait); a larger
# max_batch would wait the deadline for rows that can never arrive
MAX_BATCH = int(os.environ.get("PTPU_SRVBENCH_MAX_BATCH", NCLIENTS))
DEADLINE_US = int(os.environ.get("PTPU_SRVBENCH_DEADLINE_US", 2000))
INSTANCES = int(os.environ.get("PTPU_SRVBENCH_INSTANCES", 2))
THREADS = int(os.environ.get("PTPU_SRVBENCH_THREADS", 0))
WARM = max(4, OPS // 20)

RESULTS: list = []


def emit(row: dict):
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def build_native():
    import subprocess
    if os.environ.get("PTPU_SRVBENCH_SKIP_BUILD"):
        return  # smoke tests run on the suite's portable build
    try:
        subprocess.run(["make", "-B", "all", "MARCH=-march=native"],
                       cwd=os.path.join(REPO, "csrc"), check=True,
                       capture_output=True, timeout=600)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"# native rebuild skipped ({e}); using existing .so",
              file=sys.stderr)


def build_mlp_artifact(tmp):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.onnx.converter import trace_to_onnx

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 512))
    net.eval()
    x = np.zeros((1, 512), np.float32)
    path = os.path.join(tmp, "mlp.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(lambda a: net(a), (jnp.asarray(x),)))
    return path


def _client(rank, port, authkey, ops, warm, barrier, q):
    """Closed-loop client process. Loads the serving client module
    STANDALONE (socket + numpy only) — no paddle_tpu/jax import."""
    import importlib.util
    import numpy as np

    spec = importlib.util.spec_from_file_location(
        "ptpu_sv_client",
        os.path.join(REPO, "paddle_tpu", "inference", "serving.py"))
    sv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sv)

    cli = sv.InferenceClient(port, authkey)
    x = np.random.RandomState(rank).randn(1, 512).astype(np.float32)
    for _ in range(warm):
        cli.infer(x)
    barrier.wait(timeout=600)   # A: everyone warm; parent resets stats
    barrier.wait(timeout=600)   # B: measure starts
    t0 = time.perf_counter()
    for _ in range(ops):
        cli.infer(x)
    dt = time.perf_counter() - t0
    q.put({"rank": rank, "dt": dt, "ops": ops})
    barrier.wait(timeout=600)   # C: all replies in; parent snapshots
    cli.close()


def run_phase(model_path, clients, ops, max_batch, deadline_us):
    from paddle_tpu.inference.serving import create_server

    srv = create_server(model_path, max_batch=max_batch,
                        deadline_us=deadline_us, instances=INSTANCES,
                        threads_per_instance=THREADS)
    barrier = mp.Barrier(clients + 1)
    q: "mp.Queue" = mp.Queue()
    ps = [mp.Process(target=_client,
                     args=(r, srv.port, srv.authkey, ops, WARM,
                           barrier, q))
          for r in range(clients)]
    for p in ps:
        p.start()
    barrier.wait(timeout=600)   # A: clients warm
    srv.stats_reset()
    barrier.wait(timeout=600)   # B: go
    res = [q.get(timeout=600) for _ in range(clients)]
    barrier.wait(timeout=600)   # C: counters final
    stats = srv.stats()
    config = srv.config()
    for p in ps:
        p.join(timeout=60)
    srv.stop()
    wall = max(r["dt"] for r in res)
    total = sum(r["ops"] for r in res)
    return total / wall, stats, config, total


def main():
    import tempfile

    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: serving_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    build_native()
    phases = {}
    with tempfile.TemporaryDirectory() as tmp:
        model = build_mlp_artifact(tmp)

        seq_ops, seq_stats, _, seq_total = run_phase(
            model, clients=1, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["seq_batch1"] = seq_stats
        emit({"metric": "serve_seq_batch1_ops_per_s",
              "value": round(seq_ops, 1), "unit": "ops/s",
              "clients": 1, "max_batch": 1, "ops": seq_total})

        nb_ops, nb_stats, _, nb_total = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["concurrent_nobatch"] = nb_stats
        emit({"metric": "serve_concurrent_nobatch_ops_per_s",
              "value": round(nb_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": 1,
              "instances": INSTANCES, "ops": nb_total})

        b_ops, b_stats, b_cfg, b_total = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=MAX_BATCH,
            deadline_us=DEADLINE_US)
        phases["concurrent_batched"] = b_stats
        bb = b_stats["batcher"]
        mean_fill = (bb["batch_fill"]["sum"] /
                     max(1, bb["batch_fill"]["count"]))
        mean_e2e = (bb["e2e_us"]["sum"] / max(1, bb["e2e_us"]["count"]))
        emit({"metric": "serve_concurrent_batched_ops_per_s",
              "value": round(b_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": MAX_BATCH,
              "deadline_us": DEADLINE_US, "instances": INSTANCES,
              "buckets": b_cfg["buckets"], "ops": b_total,
              "mean_batch_fill": round(mean_fill, 2),
              "mean_e2e_us": round(mean_e2e, 1)})

        ratio = b_ops / seq_ops
        emit({"metric": "serve_batched_over_seq_ratio",
              "value": round(ratio, 2), "unit": "x",
              "acceptance_min": 3.0, "meets_3x": bool(ratio >= 3.0)})

        # counters vs client-observed counts, EXACT (ps_bench
        # discipline): every measured phase op is one INFER_REQ and
        # one INFER_REP; the batcher saw each request exactly once
        checks = []
        for name, st, want in (("seq_batch1", seq_stats, seq_total),
                               ("concurrent_nobatch", nb_stats,
                                nb_total),
                               ("concurrent_batched", b_stats,
                                b_total)):
            sv, bt = st["server"], st["batcher"]
            checks.append({
                "phase": name, "expected": want,
                "requests": sv["requests"], "replies": sv["replies"],
                "req_errors": sv["req_errors"],
                "batched_requests": bt["batched_requests"],
                "dynamic_shape_fallback": bt["dynamic_shape_fallback"],
                "exact": bool(sv["requests"] == want and
                              sv["replies"] == want and
                              sv["req_errors"] == 0 and
                              bt["batched_requests"] == want)})
        emit({"metric": "serve_stats_consistency",
              "value": int(all(c["exact"] for c in checks)),
              "unit": "bool", "phases": checks})

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench", "clients": NCLIENTS,
                       "ops": OPS, "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES,
                       "measurements": RESULTS,
                       "server_stats_phases": phases}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
