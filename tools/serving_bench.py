#!/usr/bin/env python
"""Concurrent serving throughput bench (ISSUE r8 tentpole acceptance).

One C-hosted serving runtime (csrc/ptpu_serving.cc) serves the MLP
artifact; NCLIENTS closed-loop client PROCESSES hammer it over the
framed HMAC TCP data plane. Three phases, each against a FRESH server
so counters isolate:

  1. seq_batch1          — 1 client, 1 request in flight, server
                           max_batch=1 (batching off): the sequential
                           baseline every speedup is measured against;
  2. concurrent_nobatch  — NCLIENTS clients, max_batch=1: instance
                           parallelism only;
  3. concurrent_batched  — NCLIENTS clients, dynamic batching on: the
                           headline. Acceptance: >= 3x phase 1 ops/s.

Server-side counters are cross-checked against client-observed counts
EXACTLY (requests == replies == clients x ops, zero errors), the same
discipline as tools/ps_bench.py. Client processes import the serving
client module standalone (no jax) so process startup stays light.

Config via env: PTPU_SRVBENCH_{CLIENTS,OPS,MAX_BATCH,DEADLINE_US,
INSTANCES,THREADS} (tests/test_serving_bench_persist.py runs a
shrunken 2-client config). Run:
  python tools/serving_bench.py [--out BENCH_SERVE_rNN.json]
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NCLIENTS = int(os.environ.get("PTPU_SRVBENCH_CLIENTS", 8))
OPS = int(os.environ.get("PTPU_SRVBENCH_OPS", 300))
# match the closed-loop client count: with max_batch <= in-flight
# requests, steady-state flushes are FULL (no deadline wait); a larger
# max_batch would wait the deadline for rows that can never arrive
MAX_BATCH = int(os.environ.get("PTPU_SRVBENCH_MAX_BATCH", NCLIENTS))
DEADLINE_US = int(os.environ.get("PTPU_SRVBENCH_DEADLINE_US", 2000))
INSTANCES = int(os.environ.get("PTPU_SRVBENCH_INSTANCES", 2))
THREADS = int(os.environ.get("PTPU_SRVBENCH_THREADS", 0))
WARM = max(4, OPS // 20)

RESULTS: list = []


def emit(row: dict):
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def build_native():
    import subprocess
    if os.environ.get("PTPU_SRVBENCH_SKIP_BUILD"):
        return  # smoke tests run on the suite's portable build
    try:
        subprocess.run(["make", "-B", "all", "MARCH=-march=native"],
                       cwd=os.path.join(REPO, "csrc"), check=True,
                       capture_output=True, timeout=600)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"# native rebuild skipped ({e}); using existing .so",
              file=sys.stderr)


def build_mlp_artifact(tmp):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.onnx.converter import trace_to_onnx

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(512, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 2048), pt.nn.ReLU(),
                           pt.nn.Linear(2048, 512))
    net.eval()
    x = np.zeros((1, 512), np.float32)
    path = os.path.join(tmp, "mlp.onnx")
    with open(path, "wb") as f:
        f.write(trace_to_onnx(lambda a: net(a), (jnp.asarray(x),)))
    return path


def _client(rank, port, authkey, ops, warm, barrier, q):
    """Closed-loop client process. Loads the serving client module
    STANDALONE (socket + numpy only) — no paddle_tpu/jax import."""
    import importlib.util
    import numpy as np

    spec = importlib.util.spec_from_file_location(
        "ptpu_sv_client",
        os.path.join(REPO, "paddle_tpu", "inference", "serving.py"))
    sv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sv)

    cli = sv.InferenceClient(port, authkey)
    x = np.random.RandomState(rank).randn(1, 512).astype(np.float32)
    for _ in range(warm):
        cli.infer(x)
    barrier.wait(timeout=600)   # A: everyone warm; parent resets stats
    barrier.wait(timeout=600)   # B: measure starts
    t0 = time.perf_counter()
    for _ in range(ops):
        cli.infer(x)
    dt = time.perf_counter() - t0
    q.put({"rank": rank, "dt": dt, "ops": ops})
    barrier.wait(timeout=600)   # C: all replies in; parent snapshots
    cli.close()


def run_phase(model_path, clients, ops, max_batch, deadline_us):
    from paddle_tpu.inference.serving import create_server

    srv = create_server(model_path, max_batch=max_batch,
                        deadline_us=deadline_us, instances=INSTANCES,
                        threads_per_instance=THREADS)
    barrier = mp.Barrier(clients + 1)
    q: "mp.Queue" = mp.Queue()
    ps = [mp.Process(target=_client,
                     args=(r, srv.port, srv.authkey, ops, WARM,
                           barrier, q))
          for r in range(clients)]
    for p in ps:
        p.start()
    barrier.wait(timeout=600)   # A: clients warm
    srv.stats_reset()
    barrier.wait(timeout=600)   # B: go
    res = [q.get(timeout=600) for _ in range(clients)]
    barrier.wait(timeout=600)   # C: counters final
    stats = srv.stats()
    config = srv.config()
    for p in ps:
        p.join(timeout=60)
    srv.stop()
    wall = max(r["dt"] for r in res)
    total = sum(r["ops"] for r in res)
    return total / wall, stats, config, total


# ---------------------------------------------------------------------------
# --trace: tracing-on/off overhead A/B (ISSUE 10 acceptance gate).
#
# Two hot paths, each run OFF/ON interleaved (2 rounds) in ONE session
# so machine drift cancels: the serving concurrent-batched phase (the
# r8 headline) and a single-process pipelined PS wire pull loop (the
# bandwidth-bound plane). "On" is the DEFAULT sampling config
# (PTPU_TRACE_SAMPLE=64, PTPU_TRACE_SLOW_US=100000) — what production
# pays; acceptance: on within 3% of off, counters still exact.
# ---------------------------------------------------------------------------

PULL_OPS = int(os.environ.get("PTPU_TRBENCH_PULL_OPS", 8000))
PULL_ROWS = int(os.environ.get("PTPU_TRBENCH_PULL_ROWS", 512))
PULL_DEPTH = int(os.environ.get("PTPU_TRBENCH_PULL_DEPTH", 8))


def _ps_pull_connect(port, authkey):
    """Handshaken raw socket for the pull legs. ONE connection serves
    every off/on leg: a fresh dial per leg lands on a different event
    thread each time (round-robin loop assignment), and thread
    placement moves single-conn throughput by >±10% on this box —
    keeping the conn fixed makes the A/B genuinely paired."""
    import hashlib
    import hmac
    import socket
    import struct

    s = socket.create_connection(("127.0.0.1", port), 10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    nonce = _read_n(s, 16)
    mac = hmac.new(authkey, nonce, hashlib.sha256).digest()
    s.sendall(struct.pack("<I", len(mac)) + mac)
    assert _read_n(s, 1) == b"\x01"
    return s


def _ps_pull_ops_per_s(s, ops, rows, depth):
    """Pipelined fast-frame pulls over an open raw socket (the
    ps_bench pipelined-pull shape, single process)."""
    import struct

    import numpy as np
    from paddle_tpu.distributed.ps import wire

    req = bytes(wire.build_pull_req("emb", np.arange(rows)))
    frame = struct.pack("<I", len(req)) + req

    def read_reply():
        n = struct.unpack("<I", _read_n(s, 4))[0]
        _read_n(s, n)

    warm = min(64, ops // 4)
    for _ in range(warm):
        s.sendall(frame)
        read_reply()
    t0 = time.perf_counter()
    sent = 0
    while sent < depth and sent < ops:
        s.sendall(frame)
        sent += 1
    done = 0
    while done < ops:
        read_reply()
        done += 1
        if sent < ops:
            s.sendall(frame)
            sent += 1
    dt = time.perf_counter() - t0
    return ops / dt


def _read_n(sock, n):
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        if not c:
            raise ConnectionError("peer closed")
        buf += c
    return buf


def run_trace_ab(out_path):
    import tempfile

    from paddle_tpu.core import native as N

    build_native()
    sv_lib = N._predictor_lib()
    ps_lib = N._ps_load()
    configs = [("off", (0, 0)), ("on", (64, 100000))]
    rounds = int(os.environ.get("PTPU_TRBENCH_ROUNDS", 4))
    results = {"serving_batched": {"off": [], "on": []},
               "ps_pipelined_pull": {"off": [], "on": []}}
    exact = []

    with tempfile.TemporaryDirectory() as tmp:
        model = build_mlp_artifact(tmp)
        # PS table served once; only the tracing knob flips per leg
        srv_key = b"trace-bench-key"
        ps_srv = N.PsDataServer(0, srv_key)
        tbl = N.NativePsTable(max(PULL_ROWS * 4, 4096), 64,
                              optimizer="sgd", lr=0.1)
        ps_srv.register("emb", tbl, 0)
        # each plane's off/on legs run back-to-back with nothing in
        # between (an 8-process serving phase perturbs thread placement
        # enough to swamp the signal if a pull leg follows it), and the
        # pair ORDER ALTERNATES per round — session drift on this box
        # is a slow ramp (±10% per leg), and fixed ordering aliases it
        # straight into the A/B; alternation cancels the linear part
        # the pull legs run FIRST: an 8-process serving phase perturbs
        # scheduler state for long after it exits, and the single-conn
        # pull loop is the most placement-sensitive measurement here.
        # One unrecorded warm leg (cold caches bias whichever config
        # runs first), then `rounds` recorded off/on pairs — all over
        # the SAME connection (see _ps_pull_connect)
        psock = _ps_pull_connect(ps_srv.port, srv_key)
        ps_lib.ptpu_trace_set(0, 0)
        _ps_pull_ops_per_s(psock, PULL_OPS, PULL_ROWS, PULL_DEPTH)
        for rnd in range(rounds):
            for name, (sample, slow) in (configs if rnd % 2 == 0
                                         else configs[::-1]):
                ps_lib.ptpu_trace_set(sample, slow)
                pull = _ps_pull_ops_per_s(psock, PULL_OPS, PULL_ROWS,
                                          PULL_DEPTH)
                results["ps_pipelined_pull"][name].append(
                    round(pull, 1))
        psock.close()
        ps_srv.stop()
        for rnd in range(rounds):
            for name, (sample, slow) in (configs if rnd % 2 == 0
                                         else configs[::-1]):
                sv_lib.ptpu_trace_set(sample, slow)
                ops, stats, _, total = run_phase(
                    model, clients=NCLIENTS, ops=OPS,
                    max_batch=MAX_BATCH, deadline_us=DEADLINE_US)
                results["serving_batched"][name].append(round(ops, 1))
                sv = stats["server"]
                exact.append({"leg": f"serving_{name}_r{rnd}",
                              "expected": total,
                              "requests": sv["requests"],
                              "replies": sv["replies"],
                              "exact": bool(
                                  sv["requests"] == total and
                                  sv["replies"] == total and
                                  sv["req_errors"] == 0)})
    sv_lib.ptpu_trace_set(64, 100000)
    ps_lib.ptpu_trace_set(64, 100000)

    rows = []
    all_within = True
    for leg, vals in results.items():
        # the phases carry ~±6% per-run session noise on this box
        # (documented across r8-r10 bench_guards), so the 3% gate
        # compares MEANS over the alternating rounds — drift hits both
        # configs equally; best-of is reported alongside
        off = sum(vals["off"]) / len(vals["off"])
        on = sum(vals["on"]) / len(vals["on"])
        overhead = (off - on) / off * 100.0
        within = overhead <= 3.0
        all_within = all_within and within
        row = {"metric": f"trace_ab_{leg}", "unit": "ops/s",
               "off": vals["off"], "on": vals["on"],
               "mean_off": round(off, 1), "mean_on": round(on, 1),
               "best_off": max(vals["off"]),
               "best_on": max(vals["on"]),
               "overhead_pct": round(overhead, 2),
               "acceptance_max_pct": 3.0,
               "within_3pct": bool(within)}
        rows.append(row)
        emit(row)
    emit({"metric": "trace_ab_counters_exact",
          "value": int(all(e["exact"] for e in exact)), "unit": "bool",
          "legs": exact})
    emit({"metric": "trace_ab_within_3pct", "value": int(all_within),
          "unit": "bool"})
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench --trace",
                       "clients": NCLIENTS, "ops": OPS,
                       "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES,
                       "pull": {"ops": PULL_OPS, "rows": PULL_ROWS,
                                "depth": PULL_DEPTH},
                       "trace_on_config": {"sample": 64,
                                           "slow_us": 100000},
                       "rounds": rounds,
                       "measurements": RESULTS}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


def main():
    import tempfile

    out_path = None
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: serving_bench.py [--out RESULTS.json]")
        out_path = sys.argv[idx + 1]

    if "--trace" in sys.argv:
        run_trace_ab(out_path)
        return

    build_native()
    phases = {}
    with tempfile.TemporaryDirectory() as tmp:
        model = build_mlp_artifact(tmp)

        seq_ops, seq_stats, _, seq_total = run_phase(
            model, clients=1, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["seq_batch1"] = seq_stats
        emit({"metric": "serve_seq_batch1_ops_per_s",
              "value": round(seq_ops, 1), "unit": "ops/s",
              "clients": 1, "max_batch": 1, "ops": seq_total})

        nb_ops, nb_stats, _, nb_total = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=1,
            deadline_us=DEADLINE_US)
        phases["concurrent_nobatch"] = nb_stats
        emit({"metric": "serve_concurrent_nobatch_ops_per_s",
              "value": round(nb_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": 1,
              "instances": INSTANCES, "ops": nb_total})

        b_ops, b_stats, b_cfg, b_total = run_phase(
            model, clients=NCLIENTS, ops=OPS, max_batch=MAX_BATCH,
            deadline_us=DEADLINE_US)
        phases["concurrent_batched"] = b_stats
        bb = b_stats["batcher"]
        mean_fill = (bb["batch_fill"]["sum"] /
                     max(1, bb["batch_fill"]["count"]))
        mean_e2e = (bb["e2e_us"]["sum"] / max(1, bb["e2e_us"]["count"]))
        emit({"metric": "serve_concurrent_batched_ops_per_s",
              "value": round(b_ops, 1), "unit": "ops/s",
              "clients": NCLIENTS, "max_batch": MAX_BATCH,
              "deadline_us": DEADLINE_US, "instances": INSTANCES,
              "buckets": b_cfg["buckets"], "ops": b_total,
              "mean_batch_fill": round(mean_fill, 2),
              "mean_e2e_us": round(mean_e2e, 1)})

        ratio = b_ops / seq_ops
        emit({"metric": "serve_batched_over_seq_ratio",
              "value": round(ratio, 2), "unit": "x",
              "acceptance_min": 3.0, "meets_3x": bool(ratio >= 3.0)})

        # counters vs client-observed counts, EXACT (ps_bench
        # discipline): every measured phase op is one INFER_REQ and
        # one INFER_REP; the batcher saw each request exactly once
        checks = []
        for name, st, want in (("seq_batch1", seq_stats, seq_total),
                               ("concurrent_nobatch", nb_stats,
                                nb_total),
                               ("concurrent_batched", b_stats,
                                b_total)):
            sv, bt = st["server"], st["batcher"]
            checks.append({
                "phase": name, "expected": want,
                "requests": sv["requests"], "replies": sv["replies"],
                "req_errors": sv["req_errors"],
                "batched_requests": bt["batched_requests"],
                "dynamic_shape_fallback": bt["dynamic_shape_fallback"],
                "exact": bool(sv["requests"] == want and
                              sv["replies"] == want and
                              sv["req_errors"] == 0 and
                              bt["batched_requests"] == want)})
        emit({"metric": "serve_stats_consistency",
              "value": int(all(c["exact"] for c in checks)),
              "unit": "bool", "phases": checks})

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"bench": "serving_bench", "clients": NCLIENTS,
                       "ops": OPS, "max_batch": MAX_BATCH,
                       "deadline_us": DEADLINE_US,
                       "instances": INSTANCES,
                       "measurements": RESULTS,
                       "server_stats_phases": phases}, f, indent=1)
        print(f"# persisted to {out_path}", flush=True)


if __name__ == "__main__":
    mp.set_start_method("spawn")
    main()
