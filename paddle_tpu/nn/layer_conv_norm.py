"""Conv / Norm / Pooling layer classes.

Mirrors `python/paddle/nn/layer/conv.py`, `norm.py`, `pooling.py`.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import functional as F
from . import initializer as I
from .layer import Layer


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self._n = n
        self._transpose = transpose
        self.output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
            fan_in = out_channels // groups * int(np.prod(self.kernel_size))
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
            fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), is_bias=True, attr=bias_attr,
                default_initializer=I.Uniform(-1.0 / np.sqrt(fan_in),
                                              1.0 / np.sqrt(fan_in)))

    def forward(self, x):
        fn = {1: (F.conv1d, F.conv1d_transpose),
              2: (F.conv2d, F.conv2d_transpose),
              3: (F.conv3d, F.conv3d_transpose)}[self._n][self._transpose]
        if self._transpose:
            # keyword args: conv{1,3}d_transpose and conv2d_transpose
            # order groups/dilation differently (reference arity)
            return fn(x, self.weight, self.bias, self.stride, self.padding,
                      self.output_padding, dilation=self.dilation,
                      groups=self.groups, data_format=self.data_format)
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups, self.data_format)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class _BatchNormBase(Layer):
    """Reference: `paddle.nn.BatchNorm2D` (batch_norm_op + cuDNN). Running
    stats live in buffers; the functional bridge threads their updates
    through jit (see `functional_call`)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_features,), is_bias=True,
                                              attr=bias_attr)
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))

    def forward(self, x):
        training = self.training and not self.use_global_stats
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format)
        if training:
            self._mean.value = new_mean
            self._variance.value = new_var
        return out

    def extra_repr(self):
        return f"num_features={self.num_features}"


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCL" if data_format == "NCL" else
                         data_format, use_global_stats, name)

    def forward(self, x):
        if x.ndim == 2:
            x3 = x[:, :, None]
            out = super().forward(x3)
            return out[:, :, 0]
        return super().forward(x)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


BatchNorm = _BatchNormBase  # 1.x alias


class SyncBatchNorm(_BatchNormBase):
    """Reference: sync_batch_norm_op (NCCL allreduce of stats). On TPU the
    cross-replica mean/var ride a psum over the data axis when run inside
    shard_map; under plain GSPMD data parallelism, per-replica stats match
    the reference's default (non-sync) DP behaviour."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 axis_name="data", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, None, name)
        self.axis_name = axis_name

    def forward(self, x):
        import jax
        if not self.training:
            return super().forward(x)
        channel_axis = 1 if self.data_format.startswith("NC") else x.ndim - 1
        axes = tuple(i for i in range(x.ndim) if i != channel_axis)
        mean = jnp.mean(x, axis=axes)
        meansq = jnp.mean(jnp.square(x), axis=axes)
        try:
            mean = jax.lax.pmean(mean, self.axis_name)
            meansq = jax.lax.pmean(meansq, self.axis_name)
        except NameError:
            pass  # not inside a mapped axis: degenerate to local BN
        var = meansq - jnp.square(mean)
        bshape = tuple(x.shape[i] if i == channel_axis else 1
                       for i in range(x.ndim))
        out = (x - jnp.reshape(mean, bshape)) * jnp.reshape(
            (var + self.epsilon) ** -0.5, bshape)
        if self.weight is not None:
            out = out * jnp.reshape(self.weight.value, bshape)
        if self.bias is not None:
            out = out + jnp.reshape(self.bias.value, bshape)
        self._mean.value = self.momentum * self._mean.value + \
            (1 - self.momentum) * mean
        self._variance.value = self.momentum * self._variance.value + \
            (1 - self.momentum) * var
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Reference: SyncBatchNorm.convert_sync_batchnorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """Beyond-reference (modern LLM blocks)."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               epsilon=self.epsilon,
                               data_format=self.data_format)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


# --- pooling layers ---

class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)
        self.data_format = data_format

    def forward(self, x):
        k, s, p, c = self.args
        return F.max_pool2d(x, k, s, p, c, data_format=self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override)
        self.data_format = data_format

    def forward(self, x):
        k, s, p, c, e, d = self.args
        return F.avg_pool2d(x, k, s, p, c, e, d,
                            data_format=self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, c = self.args
        return F.max_pool1d(x, k, s, p, c)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        k, s, p, c, e = self.args
        return F.avg_pool1d(x, k, s, p, c, e)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)
        self.data_format = data_format

    def forward(self, x):
        k, s, p, c = self.args
        return F.max_pool3d(x, k, s, p, c, data_format=self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override)
        self.data_format = data_format

    def forward(self, x):
        k, s, p, c, e, d = self.args
        return F.avg_pool3d(x, k, s, p, c, e, d,
                            data_format=self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class SpectralNorm(Layer):
    """Reference: `paddle.nn.SpectralNorm` (spectral_norm_op.cc): power
    iteration estimating sigma_max of the reshaped weight; u/v live in
    buffers and refresh each forward in training."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        from ..framework.random import next_key
        import jax as _jax
        self.register_buffer(
            "weight_u", _jax.random.normal(next_key(), (h,), jnp.float32))
        self.register_buffer(
            "weight_v", _jax.random.normal(next_key(), (w,), jnp.float32))

    def forward(self, weight):
        w = weight.value if hasattr(weight, "value") else weight
        mat = jnp.moveaxis(w, self.dim, 0).reshape(w.shape[self.dim], -1)
        u, v = self.weight_u.value, self.weight_v.value
        for _ in range(max(1, self.power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        if self.training:
            self.weight_u.value = u
            self.weight_v.value = v
        sigma = u @ mat @ v
        return w / sigma
