"""Weight initializers.

Mirrors `python/paddle/fluid/initializer.py` (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormal, Xavier, MSRA) and the
2.x `paddle.nn.initializer` namespace. An initializer is a callable
`(shape, dtype) -> jax.Array` drawing from the global RNG
(`paddle_tpu.framework.random`).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import convert_dtype, get_default_dtype
from ..framework.random import next_key


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]  # Linear layout [in, out]
    # conv kernels use the reference's OIHW layout: [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0, name=None):
        self.value = value

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=self.low, maxval=self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        return jax.random.normal(next_key(), tuple(shape),
                                 dtype=dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        return jax.random.truncated_normal(
            next_key(), -2.0, 2.0, tuple(shape), dtype=dtype
        ) * self.std + self.mean


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=-limit, maxval=limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), dtype=dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_key(), tuple(shape), dtype=dtype) * std


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        arr = jnp.asarray(self.value, dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign shape {arr.shape} != {tuple(shape)}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        init = jax.nn.initializers.orthogonal(scale=self.gain)
        return init(next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        init = jax.nn.initializers.delta_orthogonal()
        return init(next_key(), tuple(shape), dtype)


# paddle-2.x style aliases
constant_ = Constant
normal_ = Normal
uniform_ = Uniform


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference:
    fluid/initializer.py BilinearInitializer): weight[..., i, j] is the
    bilinear interpolation hat function, so a stride-s Conv2DTranspose
    initialized with it performs bilinear upsampling."""

    def __call__(self, shape, dtype=None):
        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv "
                             f"kernel shape, got {shape}")
        kh, kw = shape[2], shape[3]
        f_h = math.ceil(kh / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        f_w = math.ceil(kw / 2.0)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og, ig = np.ogrid[:kh, :kw]
        filt = (1 - abs(og / f_h - c_h)) * (1 - abs(ig / f_w - c_w))
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        return jnp.asarray(w, dtype=convert_dtype(dtype)
                           or get_default_dtype())


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference: `paddle.nn.initializer.set_global_initializer`
    (fluid/initializer.py): override the default initializers used by
    `Layer.create_parameter` when a layer specifies none. Pass None to
    reset."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init) \
        if weight_init is not None else None
