"""Decoding + hierarchical softmax.

Reference:
  * `operators/hierarchical_sigmoid_op.cc` + `math/matrix_bit_code.h`
    (complete-binary-tree hsigmoid) → `hsigmoid_loss`;
  * `operators/math/beam_search.{cc,cu}` + Python
    `layers/rnn.py BeamSearchDecoder` → `beam_search` (functional,
    static max_len, `lax.scan` over steps — the XLA shape contract).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Hierarchical sigmoid
# ---------------------------------------------------------------------------

def _complete_tree_codes(num_classes: int):
    """Path node ids + branch bits for a complete binary tree (reference
    `matrix_bit_code.h SimpleCode`: code(c) = c + num_classes; walk the
    implicit heap). Returns (paths [C, D], bits [C, D], mask [C, D])."""
    depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))))
    paths = np.zeros((num_classes, depth), np.int32)
    bits = np.zeros((num_classes, depth), np.float32)
    mask = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        seq = []
        while code > 1:
            seq.append((code // 2 - 1, float(code & 1)))  # (node, bit)
            code //= 2
        seq.reverse()  # root → leaf
        for d, (node, bit) in enumerate(seq):
            paths[c, d] = node
            bits[c, d] = bit
            mask[c, d] = 1.0
    return jnp.asarray(paths), jnp.asarray(bits), jnp.asarray(mask)


def hsigmoid_loss(input, label, num_classes: int, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (`hierarchical_sigmoid_op.cc`).

    input: [B, D]; label: [B] int; weight: [num_classes-1, D]
    internal-node vectors; bias: [num_classes-1]. Returns per-example
    loss [B]. Cost O(B * log C * D) vs softmax's O(B * C * D).
    path_table/path_code override the complete-tree codes with a custom
    tree (reference's custom-tree mode); is_sparse selects the sparse
    weight-update kernel in the reference and is a no-op under jit.
    """
    x, labels = input, label
    if path_table is not None or path_code is not None:
        if path_table is None or path_code is None:
            raise ValueError("custom-tree hsigmoid_loss needs BOTH "
                             "path_table and path_code (reference "
                             "contract: per-sample [N, L] tables)")
        # paddle contract: per-sample tables, path_table/path_code are
        # [N, L] aligned with `label`'s batch; -1 pads short paths
        paths = jnp.asarray(path_table)
        codes = jnp.asarray(path_code)
        valid = (paths >= 0)
        p = jnp.where(valid, paths, 0)
        w = weight.value if hasattr(weight, "value") else weight
        wv = w[p]                      # [B, depth, D]
        logits = jnp.einsum("bd,bkd->bk", x, wv)
        if bias is not None:
            bv = bias.value if hasattr(bias, "value") else bias
            logits = logits + bv[p]
        ll = jax.nn.log_sigmoid(jnp.where(codes > 0, logits, -logits))
        return -jnp.sum(ll * valid.astype(ll.dtype), axis=-1)
    paths, bits, mask = _complete_tree_codes(num_classes)
    p = paths[labels]            # [B, depth]
    b = bits[labels]             # [B, depth]
    m = mask[labels]             # [B, depth]
    w = weight[p]                # [B, depth, D]
    logits = jnp.einsum("bd,bkd->bk", x, w)
    if bias is not None:
        logits = logits + bias[p]
    # BCE with target = bit, masked beyond path length
    loss = m * (jnp.logaddexp(0.0, logits) - b * logits)
    return jnp.sum(loss, axis=1)


# ---------------------------------------------------------------------------
# Beam search
# ---------------------------------------------------------------------------

def beam_search(step_fn: Callable, init_state: Any, batch_size: int,
                beam_size: int, bos_id: int, eos_id: int, max_len: int,
                length_penalty: float = 0.0):
    """Functional beam search (`math/beam_search.cc` semantics, XLA
    shapes: everything [B, K, ...], `lax.scan` over max_len steps).

    step_fn(tokens [B, K] int32, state) -> (log_probs [B, K, V], state);
    state leaves carry leading dims [B, K]. Finished beams (emitted
    eos) are frozen: they propose only eos at zero incremental score.

    Returns (sequences [B, K, max_len] int32, scores [B, K]) sorted
    best-first along K. Scores are sum of token log-probs, length-
    normalized by ((5+len)/6)**length_penalty when length_penalty > 0
    (GNMT rule, reference BeamSearchDecoder).
    """
    B, K = batch_size, beam_size
    neg_inf = jnp.asarray(-1e9, jnp.float32)

    tokens0 = jnp.full((B, K), bos_id, jnp.int32)
    # only beam 0 is live at t=0 (all beams start identical)
    scores0 = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1),
                                   jnp.float32)[None], (B, 1))
    finished0 = jnp.zeros((B, K), bool)
    lengths0 = jnp.zeros((B, K), jnp.int32)
    seqs0 = jnp.full((B, K, max_len), eos_id, jnp.int32)

    def tick(carry, t):
        tokens, scores, finished, lengths, seqs, state = carry
        log_probs, new_state = step_fn(tokens, state)
        V = log_probs.shape[-1]
        # finished beams: force eos continuation at no cost
        eos_only = jnp.full((V,), -1e9, jnp.float32).at[eos_id].set(0.0)
        log_probs = jnp.where(finished[..., None], eos_only[None, None],
                              log_probs)
        cand = scores[..., None] + log_probs          # [B, K, V]
        flat = cand.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
        beam_idx = top_idx // V                       # source beam
        tok = (top_idx % V).astype(jnp.int32)

        def sel(x):
            return jnp.take_along_axis(
                x, beam_idx.reshape((B, K) + (1,) * (x.ndim - 2)), axis=1)

        state = jax.tree.map(sel, new_state)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        seqs = jnp.take_along_axis(seqs, beam_idx[..., None], axis=1)
        seqs = seqs.at[:, :, t].set(tok)
        lengths = jnp.where(finished, lengths, lengths + 1)
        finished = finished | (tok == eos_id)
        return (tok, top_scores, finished, lengths, seqs, state), None

    carry0 = (tokens0, scores0, finished0, lengths0, seqs0, init_state)
    (tokens, scores, finished, lengths, seqs, _), _ = jax.lax.scan(
        tick, carry0, jnp.arange(max_len))

    if length_penalty > 0.0:
        lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_penalty
        norm = scores / lp
    else:
        norm = scores
    order = jnp.argsort(-norm, axis=1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    norm = jnp.take_along_axis(norm, order, axis=1)
    return seqs, norm


def greedy_search(step_fn: Callable, init_state: Any, batch_size: int,
                  bos_id: int, eos_id: int, max_len: int):
    """Greedy decode = beam_size 1 without the bookkeeping."""
    tokens0 = jnp.full((batch_size,), bos_id, jnp.int32)
    seqs0 = jnp.full((batch_size, max_len), eos_id, jnp.int32)
    fin0 = jnp.zeros((batch_size,), bool)

    def tick(carry, t):
        tokens, finished, seqs, state = carry
        log_probs, state = step_fn(tokens[:, None], state)
        tok = jnp.argmax(log_probs[:, 0], axis=-1).astype(jnp.int32)
        tok = jnp.where(finished, eos_id, tok)
        seqs = seqs.at[:, t].set(tok)
        finished = finished | (tok == eos_id)
        return (tok, finished, seqs, state), None

    (_, _, seqs, _), _ = jax.lax.scan(tick, (tokens0, fin0, seqs0,
                                             init_state),
                                      jnp.arange(max_len))
    return seqs


def gather_tree(ids, parents):
    """Reference: `paddle.nn.functional.gather_tree` (gather_tree_op.cc):
    walk beam-search ancestry backward so each column holds a full
    hypothesis. ids/parents: [max_time, batch, beam] int. Returns same
    shape."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T, B, K = ids.shape
    beam0 = jnp.tile(jnp.arange(K, dtype=parents.dtype)[None], (B, 1))

    def walk(beam_idx, t):
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=1)
        prev = jnp.take_along_axis(parents[t], beam_idx, axis=1)
        return prev, tok

    _, toks = jax.lax.scan(walk, beam0, jnp.arange(T - 1, -1, -1))
    return toks[::-1]


class BeamSearchDecoder:
    """Reference: `paddle.nn.BeamSearchDecoder` (layers/rnn.py).

    TPU-native contract: wraps an RNNCell-like `cell` (callable
    `(inputs [B*K, E], states) -> (outputs, new_states)`) plus an
    `embedding_fn` (token ids -> embeddings) and optional `output_fn`
    (cell outputs -> vocab logits). Decoding itself runs through the
    functional `beam_search` engine (static shapes, lax.scan) via
    `dynamic_decode`.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def step_fn(self):
        def step(tokens, state):
            B, K = tokens.shape
            flat = tokens.reshape(B * K)
            emb = self.embedding_fn(flat) if self.embedding_fn is not None \
                else flat
            flat_state = jax.tree.map(
                lambda x: x.reshape((B * K,) + x.shape[2:]), state)
            out, new_state = self.cell(emb, flat_state)
            if self.output_fn is not None:
                out = self.output_fn(out)
            log_probs = jax.nn.log_softmax(out, axis=-1)
            unflat = jax.tree.map(
                lambda x: x.reshape((B, K) + x.shape[1:]), new_state)
            return log_probs.reshape(B, K, -1), unflat

        return step


def dynamic_decode(decoder, inits=None, max_step_num=None, batch_size=None,
                   length_penalty=0.0, **kwargs):
    """Reference: `paddle.nn.dynamic_decode` (layers/rnn.py dynamic_decode).
    Runs `decoder` (a BeamSearchDecoder) to `max_step_num` steps and
    returns (sequences [B, K, T], scores [B, K]) best-first.

    Unlike the reference's while_loop with growing arrays, steps run under
    `lax.scan` with a static `max_step_num` — the XLA shape contract.
    `inits` are the cell's initial states with leading dim [B]; they are
    always tiled to [B, K] here (the reference decoder tiles too). Pass
    `states_tiled=True` via kwargs if yours already carry the beam dim —
    shape sniffing cannot distinguish [B, K, ...] from [B, H] when
    H == K, so tiling is never inferred.
    """
    if max_step_num is None:
        raise ValueError("dynamic_decode requires max_step_num (static "
                         "sequence bound under XLA)")
    K = decoder.beam_size
    states_tiled = kwargs.pop("states_tiled", False)
    if batch_size is None:
        leaves = jax.tree.leaves(inits)
        if not leaves:
            raise ValueError("pass batch_size when inits is empty")
        batch_size = leaves[0].shape[0]

    def tile(x):
        x = jnp.asarray(x)
        if states_tiled:
            return x
        return jnp.tile(x[:, None], (1, K) + (1,) * (x.ndim - 1))

    state0 = jax.tree.map(tile, inits) if inits is not None else ()
    return beam_search(decoder.step_fn(), state0, batch_size, K,
                       decoder.start_token, decoder.end_token,
                       int(max_step_num), length_penalty=length_penalty)
