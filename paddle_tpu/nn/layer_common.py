"""Common layers: Linear, Embedding, Dropout, containers, activations.

Mirrors `python/paddle/nn/layer/common.py` + `container.py` +
`activation.py` layer classes of the reference.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer import Layer, Parameter


class Linear(Layer):
    """Reference: `paddle.nn.Linear` — weight stored [in, out] so forward is
    a single MXU matmul without transpose."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Layer):
    """Reference: `paddle.nn.Embedding` (lookup_table_v2)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight.value = self.weight.value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode = padding, mode
        self.value, self.data_format = value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features))
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_features,), is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# --- containers (reference: python/paddle/nn/layer/container.py) ---

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            items = layers[0].items()
        elif layers and isinstance(layers[0], (list, tuple)) and \
                not isinstance(layers[0], Layer) and \
                all(isinstance(t, tuple) for t in layers):
            items = layers
        else:
            items = ((str(i), l) for i, l in enumerate(layers))
        for name, layer in items:
            self.add_sublayer(str(name), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else
                                    len(self._sub_layers) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for name, l in (sublayers.items()
                            if isinstance(sublayers, dict) else sublayers):
                self.add_sublayer(name, l)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def __len__(self):
        return len(self._sub_layers)


# --- activation layers ---

def _act_layer(fn_name, *arg_names, **defaults):
    fn = getattr(F, fn_name)

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(defaults)
            for n, v in zip(arg_names, args):
                self._kwargs[n] = v
            self._kwargs.update({k: v for k, v in kwargs.items()
                                 if k in arg_names or k in defaults})

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = fn_name.title().replace("_", "")
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
LeakyReLU = _act_layer("leaky_relu", "negative_slope",
                       negative_slope=0.01)
ELU = _act_layer("elu", "alpha", alpha=1.0)
SELU = _act_layer("selu")
CELU = _act_layer("celu", "alpha", alpha=1.0)
GELU = _act_layer("gelu", "approximate", approximate=False)
Silu = _act_layer("silu")
Swish = _act_layer("swish")
Mish = _act_layer("mish")
Sigmoid = _act_layer("sigmoid")
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Hardtanh = _act_layer("hardtanh", "min", "max", min=-1.0, max=1.0)
Hardshrink = _act_layer("hardshrink", "threshold", threshold=0.5)
Softshrink = _act_layer("softshrink", "threshold", threshold=0.5)
Tanhshrink = _act_layer("tanhshrink")
Tanh = _act_layer("tanh")
Softplus = _act_layer("softplus", "beta", "threshold", beta=1.0,
                      threshold=20.0)
Softsign = _act_layer("softsign")
LogSigmoid = _act_layer("log_sigmoid")
Softmax = _act_layer("softmax", "axis", axis=-1)
LogSoftmax = _act_layer("log_softmax", "axis", axis=-1)
ThresholdedReLU = _act_layer("thresholded_relu", "threshold", threshold=1.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), default_initializer=I.Constant(init))
        self.data_format = data_format

    def forward(self, x):
        w = self.weight.value
        if w.shape[0] > 1:
            shape = [1] * x.ndim
            ch = 1 if self.data_format.startswith("NC") else x.ndim - 1
            shape[ch] = w.shape[0]
            w = jnp.reshape(w, shape)
        return jnp.where(x > 0, x, w * x)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode = padding, mode
        self.value, self.data_format = value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.padding, self.mode = padding, mode
        self.value, self.data_format = value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Unfold(Layer):
    """Reference: `paddle.nn.Unfold` (im2col, unfold_op)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             False, data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             True, data_format=self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        d = jnp.abs(x - y) + self.epsilon
        return jnp.sum(d ** self.p, axis=-1,
                       keepdims=self.keepdim) ** (1.0 / self.p)
