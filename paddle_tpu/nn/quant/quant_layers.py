"""QAT fake-quantization layers.

Reference: `python/paddle/nn/quant/quant_layers.py`
(QuantizedLinear/QuantizedConv2D wrapping a float layer with
fake_quantize ops) and the imperative QAT pass
(`fluid/contrib/slim/quantization/imperative/qat.py`). The fake-quant op
is a straight-through estimator: round in the forward, identity gradient
— expressed here with jax's stop_gradient trick, which XLA folds into
the surrounding computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..layer import Layer
from .. import functional as F


def fake_quant(x, scale, bits: int = 8):
    """Symmetric uniform fake quantization with straight-through grads."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax) * scale / qmax
    # straight-through: forward q, backward identity
    return x + jax.lax.stop_gradient(q - x)


class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max scale, recomputed every call (weight quant)."""

    def __init__(self, quant_bits: int = 8, name=None):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        scale = jnp.max(jnp.abs(x))
        return fake_quant(x, scale, self.quant_bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """EMA of the abs-max (activation quant; reference:
    moving_average_abs_max)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 name=None):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self.register_buffer("scale", jnp.ones((), jnp.float32))

    def forward(self, x):
        cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
        r = self.moving_rate
        if self.training:
            new_scale = r * self.scale.value + (1 - r) * cur
            self.scale.value = new_scale
        else:
            new_scale = self.scale.value
        return fake_quant(x, new_scale, self.quant_bits)


def _int8_quantize(x, step):
    """x / step rounded into int8 range (symmetric)."""
    return jnp.clip(jnp.round(x / jnp.maximum(step, 1e-12)),
                    -127, 127).astype(jnp.int8)


class QuantizedLinear(Layer):
    """Reference: quant_layers.py QuantizedLinear — wraps a float Linear
    with weight+activation fake quant. With `int8_execution` set (see
    `quantization.convert_to_int8`) the matmul actually RUNS on int8
    operands with an int32 accumulator (lax.dot_general — the MXU int8
    path) and per-OUTPUT-channel weight scales, matching the reference's
    calibrated int8 execution (inference/api/mkldnn_quantizer.cc,
    tensorrt/trt_int8_calibrator.cc) instead of merely annotating."""

    int8_execution = False

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kwargs):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits,
                                                      moving_rate)

    def forward(self, x):
        if self.int8_execution:
            return self._forward_int8(x)
        x = self.act_quant(x)
        w = self.weight_quant(jnp.asarray(self.inner.weight))
        b = self.inner.bias
        return F.linear(x, w, None if b is None else jnp.asarray(b))

    def _forward_int8(self, x):
        if self.training:
            raise RuntimeError(
                "int8 execution is inference-only (jnp.round has no "
                "gradient); keep fake-quant mode for training")
        qmax = float(2 ** (self.act_quant.quant_bits - 1) - 1)
        w = jnp.asarray(self.inner.weight)            # [in, out]
        w_step = jnp.max(jnp.abs(w), axis=0) / qmax   # per out channel
        a_step = jnp.maximum(
            jnp.asarray(self.act_quant.scale.value, jnp.float32),
            1e-8) / qmax
        x_i8 = _int8_quantize(x, a_step)
        w_i8 = _int8_quantize(w, w_step[None, :])
        acc = jax.lax.dot_general(
            x_i8, w_i8, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (a_step * w_step)
        b = self.inner.bias
        if b is not None:
            y = y + jnp.asarray(b, jnp.float32)
        return y.astype(x.dtype)


class QuantizedConv2D(Layer):
    """Reference: quant_layers.py QuantizedConv2D. `int8_execution` runs
    the conv on int8 operands / int32 accumulator with per-output-channel
    weight scales (see QuantizedLinear)."""

    int8_execution = False

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kwargs):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits,
                                                      moving_rate)

    def forward(self, x):
        if self.int8_execution:
            return self._forward_int8(x)
        x = self.act_quant(x)
        inner = self.inner
        w = self.weight_quant(jnp.asarray(inner.weight))
        return F.conv2d(
            x, w, None if inner.bias is None else jnp.asarray(inner.bias),
            stride=inner.stride, padding=inner.padding,
            dilation=inner.dilation, groups=inner.groups,
            data_format=getattr(inner, "data_format", "NCHW"))

    def _forward_int8(self, x):
        if self.training:
            raise RuntimeError(
                "int8 execution is inference-only (jnp.round has no "
                "gradient); keep fake-quant mode for training")
        inner = self.inner
        fmt = getattr(inner, "data_format", "NCHW")
        qmax = float(2 ** (self.act_quant.quant_bits - 1) - 1)
        w = jnp.asarray(inner.weight)                 # [oc, ic/g, kh, kw]
        w_step = jnp.max(jnp.abs(w), axis=(1, 2, 3)) / qmax   # [oc]
        a_step = jnp.maximum(
            jnp.asarray(self.act_quant.scale.value, jnp.float32),
            1e-8) / qmax
        x_i8 = _int8_quantize(x, a_step)
        w_i8 = _int8_quantize(w, w_step[:, None, None, None])
        # direct lax conv: int8 operands, int32 accumulator (the int8
        # conv path; F.conv2d would keep the operand dtype and overflow)
        from ..functional.conv import _padding, _tuple
        acc = jax.lax.conv_general_dilated(
            x_i8, w_i8, window_strides=_tuple(inner.stride, 2),
            padding=_padding(inner.padding, 2),
            rhs_dilation=_tuple(inner.dilation, 2),
            feature_group_count=inner.groups,
            dimension_numbers=(fmt, "OIHW", fmt),
            preferred_element_type=jnp.int32)
        scale = a_step * w_step
        if fmt == "NCHW":
            y = acc.astype(jnp.float32) * scale[None, :, None, None]
        else:
            y = acc.astype(jnp.float32) * scale
        if inner.bias is not None:
            b = jnp.asarray(inner.bias, jnp.float32)
            y = y + (jnp.reshape(b, (1, -1, 1, 1)) if fmt == "NCHW" else b)
        return y.astype(x.dtype)
