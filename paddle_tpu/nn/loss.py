"""`paddle.nn.loss` submodule path parity (reference exposes loss layer
classes both at `paddle.nn.X` and `paddle.nn.loss.X`)."""
from .layer_loss import *  # noqa: F401,F403
from .layer_loss import (  # noqa: F401
    BCELoss, CrossEntropyLoss, CTCLoss, HSigmoidLoss, KLDivLoss, L1Loss,
    MSELoss, NLLLoss, SmoothL1Loss)
