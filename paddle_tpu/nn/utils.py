"""`paddle.nn.utils` — weight reparameterizations.

Reference: python/paddle/nn/utils/weight_norm_hook.py (forward pre-hooks
rewriting `weight` from `weight_g`/`weight_v`) and spectral_norm_hook.py.
The same hook mechanism exists here (`Layer.register_forward_pre_hook`),
so the implementation mirrors the reference's shape directly.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .layer import Parameter


def _norm_except(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `layer.<name>` as g * v / ||v|| (weight_norm_hook.py).
    Returns the layer; `weight_g`/`weight_v` become the trainable params."""
    w = getattr(layer, name)
    v = w.value if hasattr(w, "value") else jnp.asarray(w)
    g = _norm_except(v, dim)
    layer.add_parameter(name + "_g", Parameter(g, name=name + "_g"))
    layer.add_parameter(name + "_v", Parameter(v, name=name + "_v"))
    if name in layer._parameters:
        del layer._parameters[name]

    def hook(lyr, inputs):
        gv = lyr._parameters[name + "_g"].value
        vv = lyr._parameters[name + "_v"].value
        object.__setattr__(lyr, name,
                           gv * vv / (_norm_except(vv, dim) + 1e-12))
        return inputs

    handle = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = handle
    hook(layer, ())  # materialize once so eager access works pre-forward
    return layer


def remove_weight_norm(layer, name="weight"):
    """Undo `weight_norm`: bake the current normalized weight back."""
    handles = layer.__dict__.get("_weight_norm_hooks", {})
    if name not in handles:
        raise ValueError(f"no weight_norm hook on parameter {name!r}")
    handles.pop(name).remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    dim_norm = _norm_except(v.value, _infer_dim(g.value))
    w = g.value * v.value / (dim_norm + 1e-12)
    layer.add_parameter(name, Parameter(w, name=name))
    return layer


def _infer_dim(g):
    if g.ndim == 0:
        return None
    return int(np.argmax(np.asarray(g.shape) != 1)) \
        if any(s != 1 for s in g.shape) else 0


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Reference: `paddle.nn.utils.spectral_norm` (spectral_norm_hook.py):
    divide the weight by its spectral norm, estimated by power iteration
    refreshed on every forward in training."""
    import jax
    from ..framework.random import next_key

    w = getattr(layer, name)
    v0 = w.value if hasattr(w, "value") else jnp.asarray(w)
    if dim is None:
        dim = 1 if type(layer).__name__.endswith("Transpose") else 0
    h = v0.shape[dim]
    ncols = int(np.prod(v0.shape)) // h
    layer.register_buffer(name + "_u",
                          jax.random.normal(next_key(), (h,), jnp.float32))
    layer.register_buffer(name + "_v",
                          jax.random.normal(next_key(), (ncols,),
                                            jnp.float32))
    orig = layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    del layer._parameters[name]

    def hook(lyr, inputs):
        wv = lyr._parameters[name + "_orig"].value
        mat = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
        u = lyr._buffers[name + "_u"].value
        v = lyr._buffers[name + "_v"].value
        for _ in range(max(1, n_power_iterations)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        if lyr.training:
            lyr._buffers[name + "_u"].value = u
            lyr._buffers[name + "_v"].value = v
        sigma = u @ mat @ v
        object.__setattr__(lyr, name, wv / sigma)
        return inputs

    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer
