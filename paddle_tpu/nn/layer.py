"""Layer/Parameter system — the imperative module API.

TPU-native equivalent of the reference's dygraph layer stack
(`python/paddle/fluid/dygraph/layers.py` `Layer`, 1507 lines; `ParamBase`;
hooks). Eager forward runs ops op-by-op exactly like dygraph; training uses
the **functional bridge** (`functional_call`) that swaps a params/buffers
pytree into the layer tree, runs forward under trace, and captures updated
buffers — replacing the reference's C++ `Tracer`/`BasicEngine` autograd
(`imperative/tracer.cc:144`, `basic_engine.cc:305`) with `jax.grad` over a
pure function. XLA then compiles the whole step; no per-op dispatch hot loop
survives.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import enforce
from ..core.dtypes import convert_dtype, get_default_dtype


class Parameter:
    """A named, trainable array slot (reference: `ParamBase`).

    Holds a `jax.Array`; during `functional_call` the value is temporarily a
    tracer. `stop_gradient=True` marks the slot non-trainable (excluded from
    `trainable_params`), mirroring paddle's `param.stop_gradient` /
    `trainable` flag.
    """

    __slots__ = ("value", "name", "stop_gradient", "_is_buffer",
                 "optimize_attr", "sharding_spec", "regularizer")

    def __init__(self, value, name: str = "", stop_gradient: bool = False,
                 is_buffer: bool = False):
        self.value = jnp.asarray(value)
        self.name = name
        self.stop_gradient = stop_gradient
        self._is_buffer = is_buffer
        self.optimize_attr = {"learning_rate": 1.0}
        # PartitionSpec for hybrid-parallel training (set by mp/pp layers;
        # consumed by the distributed train-step to build NamedShardings).
        self.sharding_spec = None
        # per-param weight-decay override (reference: ParamAttr.regularizer)
        self.regularizer = None

    @property
    def trainable(self) -> bool:
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v: bool):
        self.stop_gradient = not v

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, v):
        v = jnp.asarray(v, dtype=self.value.dtype)
        if tuple(v.shape) != tuple(self.value.shape):
            raise ValueError(
                f"set_value shape mismatch for {self.name!r}: parameter is "
                f"{tuple(self.value.shape)}, got {tuple(v.shape)}")
        self.value = v

    def astype(self, dtype):
        return self.value.astype(convert_dtype(dtype))

    def __repr__(self):
        kind = "Buffer" if self._is_buffer else "Parameter"
        return (f"{kind}(name={self.name!r}, shape={tuple(self.value.shape)}, "
                f"dtype={self.value.dtype.name}, trainable={self.trainable})")

    # Arithmetic convenience so `param * x` works in eager code.
    def __array__(self, dtype=None):
        return np.asarray(self.value, dtype=dtype)

    def __jax_array__(self):
        return self.value


# Make Parameter transparently usable where an array is expected.
jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p.value,), (p.name, p.stop_gradient, p._is_buffer)),
    lambda aux, children: Parameter(children[0], name=aux[0],
                                    stop_gradient=aux[1], is_buffer=aux[2]),
)


_name_counters: Dict[str, int] = {}


def _unique_name(prefix: str) -> str:
    i = _name_counters.get(prefix, 0)
    _name_counters[prefix] = i + 1
    return f"{prefix}_{i}"


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base building block (reference: dygraph `Layer`, layers.py).

    Subclasses define parameters in `__init__` (via attribute assignment or
    `create_parameter`) and computation in `forward`. The layer tree is
    introspectable exactly like the reference: `named_parameters`,
    `sublayers`, `state_dict`, forward pre/post hooks, `train`/`eval`.
    """

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._full_name = _unique_name(name_scope or
                                       self.__class__.__name__.lower())
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = {}
        self._forward_post_hooks: Dict[int, Callable] = {}
        self._hook_id = 0

    # --- construction helpers ---

    def create_parameter(self, shape, dtype=None, is_bias=False,
                         default_initializer=None, attr=None) -> Parameter:
        """Reference: `Layer.create_parameter` → `LayerHelper` param creation
        (`fluid/layer_helper.py`). `attr` accepts a `ParamAttr` (or a
        name/initializer it normalizes from) whose initializer overrides
        `default_initializer` and whose regularizer/trainable/lr hints land
        on the created Parameter."""
        from . import initializer as I
        from ..framework.param_attr import ParamAttr
        attr = ParamAttr._to_attr(attr)
        dtype = convert_dtype(dtype) or self._dtype
        if isinstance(attr, ParamAttr) and attr.initializer is not None:
            default_initializer = attr.initializer
        if default_initializer is None:
            glob = I._global_initializer   # set_global_initializer hook
            if glob is not None and (glob[1] if is_bias else glob[0]) \
                    is not None:
                default_initializer = glob[1] if is_bias else glob[0]
            else:
                default_initializer = I.Constant(0.0) if is_bias \
                    else I.XavierUniform()
        value = default_initializer(tuple(int(s) for s in shape), dtype)
        param = Parameter(value, name=_unique_name(self._full_name + ".w"))
        if isinstance(attr, ParamAttr):
            attr.apply_to(param)
        return param

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        buf = Parameter(tensor, name=f"{self._full_name}.{name}",
                        stop_gradient=True, is_buffer=True)
        self._buffers[name] = buf
        object.__setattr__(self, name, buf)
        return buf

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        object.__setattr__(self, name, sublayer)
        return sublayer

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    # --- attribute interception (mirrors layers.py __setattr__) ---

    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            if value._is_buffer:
                buffers[name] = value
            else:
                params[name] = value
        elif isinstance(value, Layer) and layers is not None:
            layers[name] = value
        elif params is not None and name in params and not isinstance(value, Parameter):
            # assigning an array to a parameter slot updates its value
            params[name].set_value(value)
            return
        object.__setattr__(self, name, value)

    def __delattr__(self, name):
        self._parameters.pop(name, None)
        self._buffers.pop(name, None)
        self._sub_layers.pop(name, None)
        object.__delattr__(self, name)

    # --- forward & hooks ---

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # --- traversal ---

    def children(self) -> Iterator["Layer"]:
        yield from self._sub_layers.values()

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        yield from self._sub_layers.items()

    def sublayers(self, include_self: bool = False) -> Iterator["Layer"]:
        if include_self:
            yield self
        for l in self._sub_layers.values():
            yield from l.sublayers(include_self=True)

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            p = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=p, include_self=True)

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters()] if include_sublayers \
            else list(self._parameters.values())

    def named_parameters(self, prefix: str = ""):
        for name, p in self._parameters.items():
            yield (f"{prefix}.{name}" if prefix else name), p
        for lname, l in self._sub_layers.items():
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from l.named_parameters(prefix=sub_prefix)

    def named_buffers(self, prefix: str = ""):
        for name, b in self._buffers.items():
            yield (f"{prefix}.{name}" if prefix else name), b
        for lname, l in self._sub_layers.items():
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from l.named_buffers(prefix=sub_prefix)

    def buffers(self):
        return [b for _, b in self.named_buffers()]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # --- mode / dtype ---

    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    def to(self, device=None, dtype=None):
        dtype = convert_dtype(dtype)
        for p in list(self.parameters()) + list(self.buffers()):
            v = p.value
            if dtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(dtype)
            if device is not None:
                v = jax.device_put(v, device.jax_device()
                                   if hasattr(device, "jax_device") else device)
            p.value = v
        if dtype is not None:
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # --- state dict (reference: layers.py state_dict/set_state_dict) ---

    def state_dict(self, include_sublayers=True, keep_vars=False):
        out = OrderedDict()
        for name, p in self.named_parameters():
            out[name] = p if keep_vars else p.value
        for name, b in self.named_buffers():
            out[name] = b if keep_vars else b.value
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], set(state_dict.keys())
        for name, slot in list(self.named_parameters()) + \
                list(self.named_buffers()):
            if name in state_dict:
                slot.set_value(state_dict[name])
                unexpected.discard(name)
            else:
                missing.append(name)
        return missing, sorted(unexpected)

    load_dict = set_state_dict

    def full_name(self):
        return self._full_name

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"] if extra else \
            [f"{self.__class__.__name__}("]
        for name, child in self._sub_layers.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        return "\n".join(lines) + "\n)" if len(lines) > 1 else \
            f"{self.__class__.__name__}({extra})"


# --- functional bridge -------------------------------------------------------

def _slots(layer: Layer):
    slots = OrderedDict()
    for name, p in layer.named_parameters():
        slots[name] = p
    for name, b in layer.named_buffers():
        slots[name] = b
    return slots


def functional_call(layer: Layer, params: Dict[str, Any], *args,
                    buffers: Optional[Dict[str, Any]] = None,
                    **kwargs):
    """Run `layer` as a pure function of (params, buffers, inputs).

    Swaps the given values into the layer's Parameter slots, runs forward,
    captures (possibly updated) buffer values, then restores the originals.
    Safe under `jax.jit`/`jax.grad` tracing: swapped values may be tracers.

    Returns `(outputs, new_buffers)`.

    This is the TPU replacement for the reference's dygraph execution: the
    per-op C++ `Tracer` (`imperative/tracer.cc:144`) becomes a jax trace of
    the whole forward.
    """
    slots = _slots(layer)
    saved = {name: s.value for name, s in slots.items()}
    try:
        for name, v in params.items():
            if name in slots:
                slots[name].value = v
        if buffers:
            for name, v in buffers.items():
                if name in slots:
                    slots[name].value = v
        out = layer(*args, **kwargs)
        new_buffers = {name: b.value for name, b in layer.named_buffers()}
        return out, new_buffers
    finally:
        for name, s in slots.items():
            s.value = saved[name]


def trainable_state(layer: Layer) -> Dict[str, Any]:
    """Params pytree to differentiate w.r.t. (excludes frozen + buffers).

    Plain dicts (insertion-ordered) — OrderedDict is a distinct pytree node
    type and would break structure equality across lax.cond branches."""
    return {n: p.value for n, p in layer.named_parameters() if p.trainable}


def frozen_state(layer: Layer) -> Dict[str, Any]:
    return {n: p.value for n, p in layer.named_parameters()
            if not p.trainable}


def buffer_state(layer: Layer) -> Dict[str, Any]:
    return {n: b.value for n, b in layer.named_buffers()}


def load_state(layer: Layer, params: Dict[str, Any],
               buffers: Optional[Dict[str, Any]] = None):
    """Write arrays back into the layer (post-step sync in training loops)."""
    slots = _slots(layer)
    for name, v in params.items():
        if name in slots:
            slots[name].value = v
    if buffers:
        for name, v in buffers.items():
            if name in slots:
                slots[name].value = v


@contextlib.contextmanager
def no_init():
    yield
