"""Convolution ops.

Mirrors `python/paddle/nn/functional/conv.py` (reference kernels:
`operators/conv_op.*` → cuDNN). Lowers to `lax.conv_general_dilated`, which
XLA tiles onto the MXU directly — no im2col, no algorithm search. Weights are
stored in the reference's OIHW layout for state-dict parity; XLA's layout
assignment transposes to the TPU-preferred layout at compile time.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ...core import enforce


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        enforce.enforce_eq(len(v), n, "conv parameter rank mismatch")
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(p) for p in padding]
    if len(padding) == n:
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:  # paddle flat [before0, after0, ...]
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, transpose=False, output_padding=0):
    from ...amp.auto_cast import maybe_autocast
    w = weight.value if hasattr(weight, "value") else weight
    x, w = maybe_autocast(x, w, op="conv")
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")
    spatial = "DHW"[3 - n:] if n <= 3 else None
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    out_spec = lhs_spec
    if not transpose:
        rhs_spec = "OI" + spatial  # paddle weight layout [out_c, in_c/g, *k]
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec))
    else:
        # conv_transpose = gradient-of-conv: flip kernel spatially, treat the
        # stored [in_c, out_c/g, *k] layout as (I, O, *k), fractionally
        # stride the input (lhs_dilation), and use the k-1-p padding rule.
        out_pad = _tuple(output_padding, n)
        in_c = w.shape[0]
        out_cg = w.shape[1]
        w_t = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # regroup to I=in_c/g, O=out_c (group-major) for XLA's grouped
            # convolution contract
            w_t = jnp.reshape(w_t, (groups, in_c // groups, out_cg)
                              + w_t.shape[2:])
            w_t = jnp.swapaxes(w_t, 0, 1)
            w_t = jnp.reshape(w_t, (in_c // groups, groups * out_cg)
                              + w_t.shape[3:])
        rhs_spec = "IO" + spatial
        if isinstance(pad, str):
            pads = pad
        else:
            k = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n)]
            pads = [(k[i] - 1 - pad[i][0],
                     k[i] - 1 - pad[i][1] + out_pad[i]) for i in range(n)]
        y = jax.lax.conv_general_dilated(
            x, w_t,
            window_strides=(1,) * n,
            padding=pads, lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec))
    if bias is not None:
        b = bias.value if hasattr(bias, "value") else bias
        b = b.astype(y.dtype)
        if channel_last:
            y = y + b
        else:
            y = y + jnp.reshape(b, (1, -1) + (1,) * n)
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def _outpad_for_size(x, weight, stride, padding, dilation, output_size, n,
                     data_format):
    """Back out the output_padding that yields `output_size` (reference:
    conv2d_transpose's output_size argument, conv_transpose_op.cc)."""
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        raise ValueError("output_size with string padding is unsupported")
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")
    ins = x.shape[1:1 + n] if channel_last else x.shape[2:2 + n]
    size = _tuple(output_size, n)
    out_pad = []
    for i in range(n):
        k = (weight.shape[2 + i] - 1) * dilation[i] + 1
        base = (ins[i] - 1) * stride[i] - pad[i][0] - pad[i][1] + k
        op = size[i] - base
        if not 0 <= op < stride[i] + dilation[i]:
            raise ValueError(f"output_size[{i}]={size[i]} unreachable "
                             f"(base {base}, stride {stride[i]})")
        out_pad.append(op)
    return tuple(out_pad)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    if output_size is not None:
        output_padding = _outpad_for_size(x, weight, stride, padding,
                                          dilation, output_size, 1,
                                          data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    if output_size is not None:
        output_padding = _outpad_for_size(x, weight, stride, padding,
                                          dilation, output_size, 2,
                                          data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    if output_size is not None:
        output_padding = _outpad_for_size(x, weight, stride, padding,
                                          dilation, output_size, 3,
                                          data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, transpose=True, output_padding=output_padding)
