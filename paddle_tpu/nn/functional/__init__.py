"""`paddle.nn.functional` equivalent namespace."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention  # noqa: F401
from ..decode import beam_search, greedy_search, hsigmoid_loss  # noqa: F401
from ..decode import gather_tree  # noqa: F401
from ...tensor.sequence import sequence_mask  # noqa: F401
