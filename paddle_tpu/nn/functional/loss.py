"""Loss functions.

Mirrors `python/paddle/nn/functional/loss.py` (reference kernels:
`operators/softmax_with_cross_entropy_op.*`, `cross_entropy_op`,
`bce_loss_op`, `smooth_l1_loss_op`, `kldiv_loss_op`, `margin_rank_loss` …).
`cross_entropy` fuses log-softmax + NLL exactly like the reference's fused
`softmax_with_cross_entropy` CUDA kernel — here the fusion is XLA's.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: `softmax_with_cross_entropy` (fused)."""
    if use_softmax:
        logp = jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.clip(input, 1e-15, 1.0))
    if soft_label or (label.ndim == input.ndim and label.shape == input.shape):
        if label_smoothing > 0.0:
            k = input.shape[axis]
            label = (1.0 - label_smoothing) * label + label_smoothing / k
        loss = -jnp.sum(label * logp, axis=axis)
        valid = None
    else:
        label = label.astype(jnp.int32)
        if label.ndim == input.ndim:  # trailing 1 dim
            label = jnp.squeeze(label, axis=axis)
        k = input.shape[axis]
        safe_label = jnp.clip(label, 0, k - 1)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_label, axis), axis=axis)
        nll = -jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0.0:
            smooth = -jnp.mean(logp, axis=axis)
            nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
        valid = (label != ignore_index)
        loss = jnp.where(valid, nll, 0.0)
        if weight is not None:
            w = jnp.take(weight, safe_label)
            loss = loss * w
    if reduction == "mean":
        if valid is not None:
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            if weight is not None:
                denom = jnp.maximum(jnp.sum(
                    jnp.where(valid, jnp.take(weight, jnp.clip(
                        label, 0, input.shape[axis] - 1)), 0.0)), 1e-12)
            return jnp.sum(loss) / denom
        return jnp.mean(loss)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100,
                               numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """`numeric_stable_mode` is the reference's kernel toggle
    (softmax_with_cross_entropy_op.cu); the XLA lowering is always the
    stable log-sum-exp form."""
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    """`input` is LOG-probabilities (paddle contract: pair with
    log_softmax) — no further log is applied."""
    label = label.astype(jnp.int32)
    k = input.shape[-1]
    safe_label = jnp.clip(label, 0, k - 1)
    picked = jnp.take_along_axis(input, jnp.expand_dims(safe_label, -1),
                                 axis=-1)
    loss = -jnp.squeeze(picked, axis=-1)
    valid = (label != ignore_index)
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        loss = loss * jnp.take(weight, safe_label)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        if weight is not None:
            denom = jnp.maximum(jnp.sum(jnp.where(
                valid, jnp.take(weight, safe_label), 0.0)), 1e-12)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.square(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, 1.0)) +
             (1.0 - label) * jnp.log(jnp.clip(1.0 - input, eps, 1.0)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None, name=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_weight = (pos_weight - 1.0) * label + 1.0
        loss = (1.0 - label) * logit + log_weight * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1.0 - label) * logit + max_val + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1.0, input,
                     jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1))
    loss = jnp.where(label == 1, 1.0 - cos,
                     jnp.clip(cos - margin, 0, None))
    return _reduce(loss, reduction)


def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, reduction="mean"):
    d_pos = jnp.linalg.norm(anchor - positive + epsilon, ord=p, axis=-1)
    d_neg = jnp.linalg.norm(anchor - negative + epsilon, ord=p, axis=-1)
    loss = jnp.clip(d_pos - d_neg + margin, 0, None)
    return _reduce(loss, reduction)


def log_loss(input, label, epsilon=1e-4):
    return -label * jnp.log(input + epsilon) - \
        (1.0 - label) * jnp.log(1.0 - input + epsilon)


def square_error_cost(input, label):
    return jnp.square(input - label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1.0 - p) * (1.0 - label)
    loss = ce * jnp.power(1.0 - p_t, gamma)
    alpha_t = alpha * label + (1.0 - alpha) * (1.0 - label)
    loss = alpha_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: warpctc_op. Uses a dense alpha-recursion over lax.scan.
    norm_by_times divides each sequence loss by its input length before
    reduction (warpctc's norm_by_times flag)."""
    # log_probs: [T, B, C]; labels: [B, S]
    T, B, C = log_probs.shape
    S = labels.shape[1]
    # extended label seq: blank, l1, blank, l2, ... blank (len 2S+1)
    ext = jnp.full((B, 2 * S + 1), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths + 1

    neg_inf = -1e30

    def get_prob(t_probs, idx):
        return jnp.take_along_axis(t_probs, idx, axis=-1)

    alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(S > 0, get_prob(log_probs[0], ext[:, 1:2])[:, 0], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), dtype=bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, t_probs):
        shift1 = jnp.concatenate(
            [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + get_prob(t_probs, ext)
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, 2S+1]
    batch_idx = jnp.arange(B)
    final = alphas[input_lengths - 1, batch_idx]  # [B, 2S+1]
    last = jnp.take_along_axis(final, (ext_len - 1)[:, None], axis=1)[:, 0]
    second_last = jnp.take_along_axis(
        final, jnp.clip(ext_len - 2, 0, None)[:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(last, second_last)
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths, 1)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths, 1))
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):
    """Reference: `python/paddle/fluid/layers/nn.py dice_loss` —
    1 - 2|X∩Y| / (|X|+|Y|) over all but the batch dim; `input` is
    probabilities [N, ..., C], `label` class ids [N, ..., 1]."""
    label = jnp.squeeze(jnp.asarray(label), axis=-1)
    one_hot = jax.nn.one_hot(label, input.shape[-1], dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = jnp.sum(input * one_hot, axis=reduce_axes)
    union = jnp.sum(input, axis=reduce_axes) + jnp.sum(one_hot,
                                                       axis=reduce_axes)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference: `fluid/layers/loss.py npair_loss` (improved deep metric
    learning): cross-entropy over anchor·positiveᵀ similarities with
    same-label targets + L2 on the embeddings."""
    anchor = jnp.asarray(anchor)
    positive = jnp.asarray(positive)
    labels = jnp.asarray(labels).reshape(-1)
    same = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    targets = same / jnp.sum(same, axis=1, keepdims=True)
    sim = anchor @ positive.T
    ce = jnp.mean(jnp.sum(
        -targets * jax.nn.log_softmax(sim, axis=1), axis=1))
    l2 = jnp.sum(anchor * anchor) / anchor.shape[0] \
        + jnp.sum(positive * positive) / positive.shape[0]
    return ce + l2_reg * l2 * 0.25


def huber_loss(input, label, delta=1.0):
    """Reference: `huber_loss_op.cc` — quadratic within |r| <= delta,
    linear outside (NO mean reduction, elementwise like the ref)."""
    r = jnp.asarray(label) - jnp.asarray(input)
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r,
                     delta * (a - 0.5 * delta))


def hinge_loss(input, label):
    """Reference: `hinge_loss_op.cc` — max(1 - pred*sign, 0) with
    label in {0, 1} mapped to {-1, +1}."""
    sign = 2.0 * jnp.asarray(label, jnp.float32) - 1.0
    return jnp.maximum(1.0 - jnp.asarray(input) * sign, 0.0)


def rank_loss(label, left, right):
    """Reference: `rank_loss_op.cc` (RankNet pairwise):
    C = log(1 + exp(o)) - label*o with o = left - right."""
    o = jnp.asarray(left) - jnp.asarray(right)
    return jnp.log1p(jnp.exp(-jnp.abs(o))) + jnp.maximum(o, 0.0) \
        - jnp.asarray(label) * o


def bpr_loss(input, label):
    """Reference: `bpr_loss_op.cc` (Bayesian personalized ranking):
    mean over negatives of -log(sigmoid(score_pos - score_neg));
    input [N, C] scores, label [N] or [N, 1] positive index."""
    x = jnp.asarray(input)
    y = jnp.asarray(label).reshape(-1)
    pos = jnp.take_along_axis(x, y[:, None], axis=1)
    diff = pos - x                                  # [N, C]
    neg_mask = jax.nn.one_hot(y, x.shape[1]) == 0
    ll = jax.nn.log_sigmoid(diff)
    return -(jnp.sum(ll * neg_mask, axis=1, keepdims=True)
             / jnp.maximum(jnp.sum(neg_mask, axis=1, keepdims=True), 1))


def center_loss(input, label, centers, alpha=0.1, update_center=True):
    """Reference: `center_loss_op.cc` (face-rec auxiliary loss):
    0.5*||x - c_y||^2 per sample; returns (loss [N, 1], new_centers)
    where centers move toward their class means at rate alpha."""
    x = jnp.asarray(input)
    y = jnp.asarray(label).reshape(-1)
    c = jnp.asarray(centers)
    cy = c[y]
    diff = x - cy
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if update_center:
        num = jnp.zeros((c.shape[0],), x.dtype).at[y].add(1.0)
        upd = jnp.zeros_like(c).at[y].add(diff)
        c = c + alpha * upd / (num[:, None] + 1.0)
    return loss, c


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Reference: `teacher_student_sigmoid_loss_op.h` (CTR
    distillation). Label encodes click z AND teacher score z':
    -2 -> no teacher, no click; -1 -> no teacher, click;
    [0, 1) -> teacher z'=label, no click; [1, 2] -> teacher
    z'=label-1, click. Each present part contributes the sigmoid
    log-loss max(x,0) - x*target + log(1+exp(-|x|))."""
    x = jnp.clip(jnp.asarray(input, jnp.float32), soft_max_lower_bound,
                 soft_max_up_bound)
    y = jnp.asarray(label, jnp.float32)
    sp = jnp.log1p(jnp.exp(-jnp.abs(x)))          # log(1+exp(-|x|))
    mx = jnp.maximum(x, 0.0)
    part = lambda target: mx - x * target + sp    # noqa: E731
    return jnp.where(
        y < -1.0, part(0.0),
        jnp.where(y < 0.0, part(1.0),
                  jnp.where(y < 1.0, part(0.0) + part(y),
                            part(1.0) + part(y - 1.0))))


def modified_huber_loss(input, label):
    """Reference: `modified_huber_loss_op.cc`: label {0,1} -> {-1,+1};
    z = pred*sign; piecewise (1-z)^2 clipped / -4z."""
    sign = 2.0 * jnp.asarray(label, jnp.float32) - 1.0
    z = jnp.asarray(input) * sign
    return jnp.where(z >= -1.0, jnp.square(jnp.maximum(1.0 - z, 0.0)),
                     -4.0 * z)


def sample_logits(logits, label, num_samples, seed=0, remove_accidental_hits=True):
    """Reference: `sample_logits_op.cc` — sampled-softmax prep for big
    vocabularies: keep the true label's logit plus `num_samples`
    uniformly sampled negatives, adjusted by -log(expected count) so
    full-softmax probabilities are approximated.

    logits [B, V]; label [B] int. Returns (sampled_logits
    [B, 1 + num_samples], sampled_labels [B] (always 0: the true class
    sits in column 0), sample_ids [B, num_samples])."""
    from ...framework.random import next_key
    B, V = logits.shape
    # seed may be a TRACED value (fresh per jitted step); the 0-means-
    # global-stream convention applies only to concrete host integers
    # (python or numpy scalars)
    import numpy as _np
    if isinstance(seed, (int, _np.integer)) and int(seed) == 0:
        key = next_key()
    else:
        key = jax.random.key(seed)
    ids = jax.random.randint(key, (B, num_samples), 0, V)
    true_logit = jnp.take_along_axis(logits, label[:, None], axis=1)
    neg = jnp.take_along_axis(logits, ids, axis=1)
    # uniform sampling: Q(y) = num_samples / V; subtract log-expected
    logq = jnp.log(jnp.asarray(num_samples / V, logits.dtype))
    neg = neg - logq
    if remove_accidental_hits:
        neg = jnp.where(ids == label[:, None], -1e20, neg)
    out = jnp.concatenate([true_logit - logq, neg], axis=1)
    return out, jnp.zeros((B,), jnp.int32), ids


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       seed=0):
    """Sampled-softmax CE (the training use of `sample_logits`):
    mean CE of the true class against sampled negatives."""
    s_logits, s_labels, _ = sample_logits(logits, label, num_samples,
                                          seed=seed)
    return cross_entropy(s_logits, s_labels)
