"""Activation functions.

Mirrors `python/paddle/nn/functional/activation.py` (reference kernels:
`operators/activation_op.*`). All are single XLA HLOs or small fusions — the
compiler fuses them into neighbouring matmuls, which is what the reference's
`fuse_elewise_add_act_pass` did manually.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def relu(x, name=None):
    return jax.nn.relu(x)


def relu6(x, name=None):
    return jax.nn.relu6(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(x, negative_slope)


def prelu(x, weight, name=None):
    w = weight.value if hasattr(weight, "value") else weight
    return jnp.where(x > 0, x, w * x)


def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x, name=None):
    return jax.nn.silu(x)


swish = silu


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardswish(x, name=None):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


def tanh(x, name=None):
    return jnp.tanh(x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return jnp.where(beta * x > threshold, x,
                     jnp.log1p(jnp.exp(beta * jnp.minimum(x, threshold / beta))) / beta)


def softsign(x, name=None):
    return jax.nn.soft_sign(x)


def maxout(x, groups, axis=1, name=None):
    shape = list(x.shape)
    ch = shape[axis]
    shape[axis] = ch // groups
    shape.insert(axis + 1, groups)
    return jnp.max(jnp.reshape(x, shape), axis=axis + 1)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ...framework.random import next_key
    g = jax.random.gumbel(next_key(), x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                    inplace=False)
        # straight-through: value y_hard, gradient of the soft sample
        y = y_hard - jax.lax.stop_gradient(y) + y
    return y


def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0.0)


def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


# In-place variants: plain ops in a functional world (reference exposes
# them as mutation-fused kernels; semantics here are the returned array).
relu_ = relu
elu_ = elu
softmax_ = softmax


def tanh_(x, name=None):
    return jnp.tanh(x)
