"""Attention ops.

The reference ships fused attention only as inference CUDA kernels
(`operators/fused/multihead_matmul_op.cu`, `math/bert_encoder_functor.cu`).
Here attention is a first-class training op: the default path is a plain XLA
composition (fuses well on TPU); when `FLAGS_enable_pallas_kernels` is set and
shapes qualify, a Pallas flash-attention kernel (`paddle_tpu/ops/`) is used to
keep the S×S score matrix out of HBM for long sequences.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.flags import flag


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None):
    """query/key/value: [batch, seq, heads, head_dim] (paddle 2.x layout).

    attn_mask: broadcastable to [batch, heads, q_len, k_len]; boolean (True =
    keep) or additive float.
    """
    if flag("enable_pallas_kernels") and dropout_p == 0.0 \
            and attn_mask is None and _pallas_ok(query, key, is_causal):
        try:
            from ...ops.flash_attention import flash_attention
        except ImportError:
            pass
        else:
            return flash_attention(query, key, value, causal=is_causal,
                                   scale=scale)
    return _xla_attention(query, key, value, attn_mask, dropout_p, is_causal,
                          training, scale)


def _pallas_ok(q, k, causal: bool) -> bool:
    """Dispatch heuristic, measured on v5e (512-seq tiles): causal flash
    wins from 1K tokens in training (fwd+bwd 9.2ms vs XLA 12.1ms at
    [8,1024,16,64]; 1.7x at 2K); NON-causal flash wins already at 512
    (BERT-base b32: 35.5% vs 33.1% MFU — XLA's dense path carries the
    full S x S fp32 score tensor either way, while the bubble the causal
    kernel skips doesn't exist). Flash is the only option from ~8K where
    dense score temps exceed HBM. Floor tunable via
    FLAGS_pallas_attention_min_seq (causal; non-causal uses
    min(floor, 512)). Cross-attention (k_len != q_len) stays on the XLA
    path."""
    if jax.default_backend() not in ("tpu",):
        return False
    b, s, h, d = q.shape
    floor = int(flag("pallas_attention_min_seq"))
    if not causal:
        floor = min(floor, 512)
    return (k.shape == q.shape and s % 128 == 0 and s >= floor
            and d <= 256)


def _xla_attention(query, key, value, attn_mask, dropout_p, is_causal,
                   training, scale):
    q_len, k_len = query.shape[1], key.shape[1]
    head_dim = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    # [b, s, h, d] -> [b, h, s, d]
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    # score accumulation in fp32 for bf16 inputs (MXU native mixed precision)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((q_len, k_len), dtype=bool))
        scores = jnp.where(causal, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(query.dtype)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout
        probs = _dropout(probs, p=dropout_p, training=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)
