"""Attention ops.

The reference ships fused attention only as inference CUDA kernels
(`operators/fused/multihead_matmul_op.cu`, `math/bert_encoder_functor.cu`).
Here attention is a first-class training op: the default path is a plain XLA
composition (fuses well on TPU); when `FLAGS_enable_pallas_kernels` is set and
shapes qualify, a Pallas flash-attention kernel (`paddle_tpu/ops/`) is used to
keep the S×S score matrix out of HBM for long sequences.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.flags import flag


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None):
    """query/key/value: [batch, seq, heads, head_dim] (paddle 2.x layout).

    attn_mask: broadcastable to [batch, heads, q_len, k_len]; boolean (True =
    keep) or additive float.
    """
    if flag("enable_pallas_kernels") and dropout_p == 0.0 \
            and _pallas_ok(query, key, is_causal):
        kv_mask = _as_kv_mask(attn_mask, query.shape[0], key.shape[1]) \
            if attn_mask is not None else None
        if attn_mask is None or kv_mask is not None:
            try:
                from ...ops.flash_attention import flash_attention
            except ImportError:
                _log_fallback("pallas flash kernel unavailable")
            else:
                return flash_attention(query, key, value, causal=is_causal,
                                       scale=scale, kv_mask=kv_mask)
        else:
            _log_fallback("attn_mask is not a [b,1,1,k] bool/int k-side "
                          "padding mask")
    return _xla_attention(query, key, value, attn_mask, dropout_p, is_causal,
                          training, scale)


def _as_kv_mask(attn_mask, batch: int, k_len: int):
    """Reduce an attention mask to a k-side [b, k_len] padding mask when
    its SEMANTICS are provably keep/drop — the padded-batch BERT case,
    which keeps the flash path. Rules (content is traced, so the
    decision is dtype/shape-only):
    - dtype: bool (True = keep) or integer (nonzero = keep); float masks
      are ADDITIVE in the XLA path and finite biases are legal, so they
      never reduce.
    - shape: [k] or [b-or-1, 1, 1, k] — exactly the shapes whose XLA
      broadcast has pure k-side meaning. [b, k]/[b, 1, k] would align
      against (q, k)/(h, q, k) in the XLA path, so they fall back."""
    m = jnp.asarray(attn_mask)
    if m.dtype != jnp.bool_ and not jnp.issubdtype(m.dtype, jnp.integer):
        return None
    shape = m.shape
    if m.ndim == 1 and shape[0] == k_len:
        m = jnp.broadcast_to(m[None, :], (batch, k_len))
    elif m.ndim == 4 and shape[-1] == k_len and shape[1] == 1 \
            and shape[2] == 1 and shape[0] in (1, batch):
        m = jnp.broadcast_to(m.reshape(shape[0], k_len), (batch, k_len))
    else:
        return None
    return m if m.dtype == jnp.bool_ else m != 0


_fallback_logged = False


def _log_fallback(reason: str) -> None:
    """One-time notice when a flash-eligible call falls back to XLA
    (VERDICT r3 weak 8: the fallback cliff was silent)."""
    global _fallback_logged
    if not _fallback_logged:
        _fallback_logged = True
        import logging
        logging.getLogger("paddle_tpu").info(
            "scaled_dot_product_attention: using the XLA path (%s); the "
            "Pallas flash kernel supports dense/causal with an optional "
            "k-side padding mask", reason)


def _pallas_ok(q, k, causal: bool) -> bool:
    """Dispatch heuristic, measured on v5e (512-seq tiles): causal flash
    wins from 1K tokens in training (fwd+bwd 9.2ms vs XLA 12.1ms at
    [8,1024,16,64]; 1.7x at 2K); NON-causal flash wins already at 512
    (BERT-base b32: 35.5% vs 33.1% MFU — XLA's dense path carries the
    full S x S fp32 score tensor either way, while the bubble the causal
    kernel skips doesn't exist). Flash is the only option from ~8K where
    dense score temps exceed HBM. Floor tunable via
    FLAGS_pallas_attention_min_seq (causal; non-causal uses
    min(floor, 512)). Cross-attention (k_len != q_len) stays on the XLA
    path."""
    if jax.default_backend() not in ("tpu",):
        return False
    b, s, h, d = q.shape
    floor = int(flag("pallas_attention_min_seq"))
    if not causal:
        floor = min(floor, 512)
    return (k.shape == q.shape and s % 128 == 0 and s >= floor
            and d <= 256)


def _xla_attention(query, key, value, attn_mask, dropout_p, is_causal,
                   training, scale):
    q_len, k_len = query.shape[1], key.shape[1]
    head_dim = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    # [b, s, h, d] -> [b, h, s, d]
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    # score accumulation in fp32 for bf16 inputs (MXU native mixed precision)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((q_len, k_len), dtype=bool))
        scores = jnp.where(causal, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(query.dtype)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout
        probs = _dropout(probs, p=dropout_p, training=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)
