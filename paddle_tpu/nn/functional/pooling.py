"""Pooling ops.

Mirrors `python/paddle/nn/functional/pooling.py` (reference:
`operators/pool_op.*` + `math/pooling.{cc,cu}`). Lowers to
`lax.reduce_window`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .conv import _padding, _tuple


def _pool(x, kernel, stride, padding, n, data_format, reducer, init,
          ceil_mode=False):
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _padding(padding, n)
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pad_all = [(0, 0)] + (pad if not isinstance(pad, str) else pad) + [(0, 0)] \
            if not isinstance(pad, str) else pad
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pad_all = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    if ceil_mode and not isinstance(pad_all, str):
        # extend the high side so the last partial window is included
        spatial_off = 1 if channel_last else 2
        pad_all = list(pad_all)
        for i in range(n):
            d = spatial_off + i
            size = x.shape[d] + pad_all[d][0] + pad_all[d][1]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                pad_all[d] = (pad_all[d][0], pad_all[d][1] + stride[i] - rem)
    return jax.lax.reduce_window(x, init, reducer, dims, strides, pad_all)


def _max_init(x):
    if jnp.issubdtype(x.dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(x.dtype).min


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format,
                 jax.lax.max, _max_init(x), ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "NCL", jax.lax.max,
                 _max_init(x), ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format,
                 jax.lax.max, _max_init(x), ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    summed = _pool(x, kernel_size, stride, padding, 2, data_format,
                   jax.lax.add, 0.0, ceil_mode)
    if divisor_override is not None:
        return summed / float(divisor_override)
    if exclusive:
        ones = jnp.ones_like(x)
        counts = _pool(ones, kernel_size, stride, padding, 2, data_format,
                       jax.lax.add, 0.0, ceil_mode)
        return summed / counts
    k = _tuple(kernel_size, 2)
    return summed / float(np.prod(k))


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, name=None):
    summed = _pool(x, kernel_size, stride, padding, 1, "NCL",
                   jax.lax.add, 0.0, ceil_mode)
    if exclusive:
        counts = _pool(jnp.ones_like(x), kernel_size, stride, padding, 1,
                       "NCL", jax.lax.add, 0.0, ceil_mode)
        return summed / counts
    return summed / float(_tuple(kernel_size, 1)[0])


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    summed = _pool(x, kernel_size, stride, padding, 3, data_format,
                   jax.lax.add, 0.0, ceil_mode)
    if divisor_override is not None:
        return summed / float(divisor_override)
    if exclusive:
        counts = _pool(jnp.ones_like(x), kernel_size, stride, padding, 3,
                       data_format, jax.lax.add, 0.0, ceil_mode)
        return summed / counts
    return summed / float(np.prod(_tuple(kernel_size, 3)))


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    """Reference: pool2d with adaptive=True."""
    oh, ow = _tuple(output_size, 2)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x_ = jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow)) \
            if h % oh == 0 and w % ow == 0 else None
        if x_ is not None:
            return jnp.mean(x_, axis=(3, 5))
        target = (n, c, oh, ow)
    else:
        n, h, w, c = x.shape
        if h % oh == 0 and w % ow == 0:
            x_ = jnp.reshape(x, (n, oh, h // oh, ow, w // ow, c))
            return jnp.mean(x_, axis=(2, 4))
        target = (n, oh, ow, c)
    return jax.image.resize(x, target, method="linear").astype(x.dtype)


def adaptive_max_pool2d(x, output_size, return_mask=False,
                        data_format="NCHW", name=None):
    oh, ow = _tuple(output_size, 2)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        assert h % oh == 0 and w % ow == 0, \
            "adaptive_max_pool2d requires divisible sizes on TPU"
        win = jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow))
        out = jnp.max(win, axis=(3, 5))
        if not return_mask:
            return out
        # flattened argmax over each (kh, kw) window -> global h*w index,
        # matching the reference's max_pool_with_index mask layout
        kh, kw = h // oh, w // ow
        flat = jnp.reshape(jnp.moveaxis(win, 4, 3),
                           (n, c, oh, ow, kh * kw))
        arg = jnp.argmax(flat, axis=-1)
        wr, wc = arg // kw, arg % kw
        gi = (jnp.arange(oh)[:, None] * kh + wr) * w \
            + jnp.arange(ow)[None, :] * kw + wc
        return out, gi.astype(jnp.int32)
    n, h, w, c = x.shape
    out = jnp.max(jnp.reshape(x, (n, oh, h // oh, ow, w // ow, c)),
                  axis=(2, 4))
    if return_mask:
        raise NotImplementedError("return_mask requires NCHW")
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    n, c, l = x.shape
    o = output_size if isinstance(output_size, int) else output_size[0]
    assert l % o == 0
    return jnp.mean(jnp.reshape(x, (n, c, o, l // o)), axis=3)


def global_avg_pool2d(x, data_format="NCHW"):
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.mean(x, axis=axes, keepdims=True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    n, c, l = x.shape
    o = output_size if isinstance(output_size, int) else output_size[0]
    assert l % o == 0, "adaptive_max_pool1d requires divisible sizes on TPU"
    win = jnp.reshape(x, (n, c, o, l // o))
    out = jnp.max(win, axis=3)
    if return_mask:
        arg = jnp.argmax(win, axis=3)
        gi = jnp.arange(o) * (l // o) + arg
        return out, gi.astype(jnp.int32)
    return out


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    od, oh, ow = _tuple(output_size, 3)
    if data_format == "NCDHW":
        n, c, d, h, w = x.shape
        assert d % od == 0 and h % oh == 0 and w % ow == 0
        return jnp.mean(jnp.reshape(
            x, (n, c, od, d // od, oh, h // oh, ow, w // ow)),
            axis=(3, 5, 7))
    n, d, h, w, c = x.shape
    assert d % od == 0 and h % oh == 0 and w % ow == 0
    return jnp.mean(jnp.reshape(
        x, (n, od, d // od, oh, h // oh, ow, w // ow, c)), axis=(2, 4, 6))


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW", name=None):
    od, oh, ow = _tuple(output_size, 3)
    if data_format == "NCDHW":
        n, c, d, h, w = x.shape
        assert d % od == 0 and h % oh == 0 and w % ow == 0
        win = jnp.reshape(
            x, (n, c, od, d // od, oh, h // oh, ow, w // ow))
        out = jnp.max(win, axis=(3, 5, 7))
        if not return_mask:
            return out
        kd, kh, kw = d // od, h // oh, w // ow
        flat = jnp.reshape(jnp.transpose(
            win, (0, 1, 2, 4, 6, 3, 5, 7)),
            (n, c, od, oh, ow, kd * kh * kw))
        arg = jnp.argmax(flat, axis=-1)
        wd, rem = arg // (kh * kw), arg % (kh * kw)
        wr, wc = rem // kw, rem % kw
        gi = ((jnp.arange(od)[:, None, None] * kd + wd) * h
              + jnp.arange(oh)[None, :, None] * kh + wr) * w \
            + jnp.arange(ow)[None, None, :] * kw + wc
        return out, gi.astype(jnp.int32)
    n, d, h, w, c = x.shape
    assert d % od == 0 and h % oh == 0 and w % ow == 0
    return jnp.max(jnp.reshape(
        x, (n, od, d // od, oh, h // oh, ow, w // ow, c)), axis=(2, 4, 6))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Reference: `unpool_op.cc` — inverse of max_pool2d with
    return_mask: scatter each pooled value back to its argmax position
    (indices are global h*w positions, the max_pool_with_index
    convention); everything else is 0."""
    assert data_format == "NCHW", "max_unpool2d: NCHW only"
    k = _tuple(kernel_size, 2)
    s = _tuple(stride, 2) if stride is not None else k
    p = _tuple(padding, 2)
    n, c, ph, pw = x.shape
    if output_size is None:
        H = (ph - 1) * s[0] - 2 * p[0] + k[0]
        W = (pw - 1) * s[1] - 2 * p[1] + k[1]
    else:
        H, W = output_size[-2], output_size[-1]
    flat = jnp.zeros((n, c, H * W), x.dtype)
    idx = jnp.reshape(jnp.asarray(indices, jnp.int32), (n, c, ph * pw))
    vals = jnp.reshape(x, (n, c, ph * pw))
    flat = flat.at[jnp.arange(n)[:, None, None],
                   jnp.arange(c)[None, :, None], idx].set(
        vals, mode="drop")
    return jnp.reshape(flat, (n, c, H, W))
