"""Common NN functional ops: linear, dropout, embedding, one_hot, interpolate.

Mirrors `python/paddle/nn/functional/common.py` + `input.py` (reference
kernels: `operators/matmul_v2_op`, `dropout_op`, `lookup_table_v2_op`,
`one_hot_v2_op`, `interpolate_v2`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.random import next_key


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] as in the reference
    (`matmul` with the stored layout; no transpose → clean MXU mapping)."""
    from ...amp.auto_cast import maybe_autocast
    w = weight.value if hasattr(weight, "value") else weight
    x, w = maybe_autocast(x, w, op="linear")
    y = jnp.matmul(x, w)
    if bias is not None:
        b = bias.value if hasattr(bias, "value") else bias
        y = y + b.astype(y.dtype)
    return y


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Reference: dropout_op. `upscale_in_train` (default) scales by 1/(1-p)
    at train time; `downscale_in_infer` scales by (1-p) at eval."""
    if p == 0.0:
        return x
    if not training:
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    if p == 1.0:
        return jnp.zeros_like(x)
    shape = x.shape
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(next_key(), 1.0 - p, shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(next_key(), 1.0 - p, x.shape)
    a = (1.0 / ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: lookup_table_v2_op. Gather along vocab dim; `sparse` is
    accepted for parity (XLA gather handles both)."""
    w = weight.value if hasattr(weight, "value") else weight
    out = jnp.take(w, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is None:
        return (1.0 - epsilon) * label + epsilon / k
    return (1.0 - epsilon) * label + epsilon * prior_dist


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Reference: interpolate_v2 (bilinear/nearest/bicubic...).
    `align_mode` selects the src-index formula when align_corners is
    False; jax.image.resize implements mode 1 (pixel-center) semantics,
    which is what the reference's default-path models use."""
    is_nchw = data_format in ("NCHW", "NCDHW", "NCL")
    spatial = x.shape[2:] if is_nchw else x.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    method = {"nearest": "nearest", "bilinear": "bilinear",
              "bicubic": "bicubic", "trilinear": "trilinear",
              "linear": "linear", "area": "linear"}[mode]
    if is_nchw:
        target = x.shape[:2] + tuple(size)
    else:
        target = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    return jax.image.resize(x, target, method=method).astype(x.dtype)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """Reference: unfold_op (im2col). NCHW input -> [N, C*kh*kw, L]."""
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patch = x[:, :, i * dh:i * dh + oh * sh:sh,
                      j * dw:j * dw + ow * sw:sw]
            patches.append(patch)
    out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
    return jnp.reshape(out, (n, c * kh * kw, oh * ow))


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as _tensor_pad
    return _tensor_pad(x, pad, mode=mode, value=value,
                       data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def bilinear(x1, x2, weight, bias=None, name=None):
    w = weight.value if hasattr(weight, "value") else weight
    out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if bias is not None:
        b = bias.value if hasattr(bias, "value") else bias
        out = out + b
    return out


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return jnp.reshape(x, (n, c // (r * r), h * r, w * r))
    n, h, w, c = x.shape
    x = jnp.reshape(x, (n, h, w, r, r, c // (r * r)))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (n, h * r, w * r, c // (r * r)))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """Reference: `temporal_shift_op.cc` (TSM): fold channels shifted one
    segment backward/forward in time; input [N*T, C, H, W]."""
    if data_format != "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    t = seg_num
    n = nt // t
    fold = int(c * shift_ratio)
    xr = jnp.reshape(x, (n, t, c, h, w))
    past = jnp.pad(xr[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0),
                                      (0, 0)))            # shift left
    future = jnp.pad(xr[:, :-1, fold:2 * fold],
                     ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))  # shift right
    out = jnp.concatenate([past, future, xr[:, :, 2 * fold:]], axis=2)
    out = jnp.reshape(out, (nt, c, h, w))
    if data_format != "NCHW":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """Reference: `paddle.nn.functional.diag_embed` (diag_embed_op)."""
    x = jnp.asarray(input)
    last = x.shape[-1]
    size = last + abs(offset)
    idx = jnp.arange(last)
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (size, size), x.dtype)
    out = out.at[..., rows, cols].set(x)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Reference: `affine_grid_op.cc`. theta [N, 2, 3]; out_shape
    [N, C, H, W] -> grid [N, H, W, 2] of (x, y) source coords in [-1, 1]."""
    n, _, h, w = [int(s) for s in out_shape]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    base = jnp.stack([
        jnp.tile(xs[None, :], (h, 1)),
        jnp.tile(ys[:, None], (1, w)),
        jnp.ones((h, w)),
    ], axis=-1)                                   # [H, W, 3]
    # grid = base @ theta^T per batch
    return jnp.einsum("hwk,nck->nhwc", base, jnp.asarray(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Reference: `grid_sampler_op.cc` (cuDNN SpatialTfSampler). x
    [N, C, H, W]; grid [N, Hg, Wg, 2] of (x, y) in [-1, 1]."""
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"grid_sample padding_mode={padding_mode!r}: 'zeros' and "
            "'border' are supported")
    x = jnp.asarray(x)
    grid = jnp.asarray(grid)
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) * 0.5 * (size - 1)
        return ((g + 1.0) * size - 1.0) * 0.5

    ix = unnorm(gx, w)
    iy = unnorm(gy, h)

    def sample(ix, iy):
        """Gather x at integer coords with padding handling; returns
        [N, C, Hg, Wg] plus validity mask for zeros-padding."""
        valid = (ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1)
        ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
        flat = iyc * w + ixc                       # [N, Hg, Wg]
        xf = x.reshape(n, c, h * w)
        got = jnp.take_along_axis(
            xf, flat.reshape(n, 1, -1).astype(jnp.int32), axis=2)
        got = got.reshape(n, c, *ix.shape[1:])
        if padding_mode == "zeros":
            got = got * valid[:, None].astype(got.dtype)
        return got

    if mode == "nearest":
        return sample(jnp.round(ix), jnp.round(iy))
    x0, y0 = jnp.floor(ix), jnp.floor(iy)
    x1, y1 = x0 + 1, y0 + 1
    wa = (x1 - ix) * (y1 - iy)
    wb = (x1 - ix) * (iy - y0)
    wc = (ix - x0) * (y1 - iy)
    wd = (ix - x0) * (iy - y0)
    out = (sample(x0, y0) * wa[:, None] + sample(x0, y1) * wb[:, None] +
           sample(x1, y0) * wc[:, None] + sample(x1, y1) * wd[:, None])
    return out.astype(x.dtype)


def shuffle_channel(x, group: int, name=None):
    """ShuffleNet channel shuffle (`shuffle_channel_op.cc`):
    [N, C, H, W] -> reshape [N, g, C/g, H, W] -> swap -> flatten."""
    n, c, h, w = x.shape
    assert c % group == 0, (c, group)
    return jnp.reshape(
        jnp.swapaxes(jnp.reshape(x, (n, group, c // group, h, w)), 1, 2),
        (n, c, h, w))


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (`fsp_op.cc`, distillation):
    [N, C1, H, W] x [N, C2, H, W] -> [N, C1, C2] = x·yᵀ / (H*W)."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    xf = jnp.reshape(x, (n, c1, h * w))
    yf = jnp.reshape(y, (n, c2, h * w))
    return jnp.einsum("nab,ncb->nac", xf, yf) / float(h * w)


def affine_channel(x, scale, bias, data_format="NCHW"):
    """Reference: `affine_channel_op.cc` — per-channel x*scale + bias
    (the frozen-BN form used by detection backbones)."""
    s = jnp.reshape(jnp.asarray(scale), (1, -1, 1, 1)
                    if data_format == "NCHW" else (1, 1, 1, -1))
    b = jnp.reshape(jnp.asarray(bias), s.shape)
    return x * s + b


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """Reference: `add_position_encoding_op.cc` — alpha*x + beta*PE with
    the sin/cos transformer table; x [B, T, D]."""
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(half, dtype=jnp.float32)[None, :]
    # reference exponent (add_position_encoding_op.h:85): k/(half-1)
    denom = float(max(half - 1, 1))
    angle = pos / jnp.power(10000.0, i / denom)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=1)
    return alpha * x + beta * pe[None].astype(x.dtype)


def im2sequence(x, filter_size=1, stride=1, padding=0):
    """Reference: `im2sequence_op.cc` (OCR): sliding patches flattened
    to a sequence — [N, C, H, W] -> [N, oh*ow, C*fh*fw]."""
    fh, fw = _pair(filter_size)
    cols = unfold(x, (fh, fw), strides=_pair(stride),
                  paddings=_pair(padding))           # [N, C*fh*fw, L]
    return jnp.swapaxes(cols, 1, 2)


def similarity_focus(x, axis, indexes):
    """Reference: `similarity_focus_op.cc` — build a 0/1 focus mask via
    GREEDY cell selection on the chosen channel plane: repeatedly take
    the largest remaining cell whose row AND column are both unused
    (each row/column holds at most one selected cell, min(H, W) picks);
    selected cells light up across all channels."""
    if axis != 1:
        raise NotImplementedError("similarity_focus: axis != 1")
    n, c, h, w = x.shape
    mask = jnp.zeros((n, h, w), jnp.bool_)
    for idx in indexes:
        plane = x[:, idx]                           # [N, H, W]

        def pick(carry, _):
            m, row_used, col_used = carry
            avail = (~row_used[:, :, None]) & (~col_used[:, None, :])
            neg = jnp.where(avail, plane, -jnp.inf)
            flat = jnp.argmax(neg.reshape(n, -1), axis=1)
            r, col = flat // w, flat % w
            m = m.at[jnp.arange(n), r, col].set(True)
            row_used = row_used.at[jnp.arange(n), r].set(True)
            col_used = col_used.at[jnp.arange(n), col].set(True)
            return (m, row_used, col_used), None

        (m, _, _), _ = jax.lax.scan(
            pick, (jnp.zeros((n, h, w), jnp.bool_),
                   jnp.zeros((n, h), jnp.bool_),
                   jnp.zeros((n, w), jnp.bool_)),
            None, length=min(h, w))
        mask = mask | m
    return jnp.broadcast_to(mask[:, None], x.shape).astype(x.dtype)


def conv_shift(x, y):
    """Reference: `conv_shift_op.cc` — circular correlation of each row
    of x [B, M] with the kernel row y [B, N] (N odd, N <= M)."""
    B, M = x.shape
    N = y.shape[1]
    half = N // 2
    outs = []
    for k in range(N):
        outs.append(jnp.roll(x, half - k, axis=1) * y[:, k:k + 1])
    return sum(outs)


def spp(x, pyramid_height=3, pool_type="max"):
    """Reference: `spp_op.cc` (spatial pyramid pooling): concat of
    1x1, 2x2, ... 2^(h-1) bin poolings -> [N, C*sum(4^l)]. Arbitrary
    H/W: bins use ceil/floor boundaries (the SPP-net kernel-size
    formula), realized as masked reductions."""
    n, c, h, w = x.shape
    ys = jnp.arange(h)
    xs = jnp.arange(w)
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        i = jnp.arange(bins)
        y_lo = jnp.floor(i * h / bins).astype(jnp.int32)
        y_hi = jnp.ceil((i + 1) * h / bins).astype(jnp.int32)
        x_lo = jnp.floor(i * w / bins).astype(jnp.int32)
        x_hi = jnp.ceil((i + 1) * w / bins).astype(jnp.int32)
        in_y = (ys[None, :] >= y_lo[:, None]) & \
               (ys[None, :] < y_hi[:, None])          # [bins, h]
        in_x = (xs[None, :] >= x_lo[:, None]) & \
               (xs[None, :] < x_hi[:, None])          # [bins, w]
        m = in_y[:, None, :, None] & in_x[None, :, None, :]  # [bi,bj,h,w]
        if pool_type == "max":
            masked = jnp.where(m[None, None], x[:, :, None, None],
                               -jnp.inf)
            pooled = jnp.max(masked, axis=(-1, -2))   # [N, C, bi, bj]
        else:
            mf = m.astype(x.dtype)
            s = jnp.einsum("nchw,ijhw->ncij", x, mf)
            pooled = s / jnp.maximum(
                jnp.sum(mf, axis=(-1, -2)), 1.0)[None, None]
        outs.append(jnp.reshape(pooled, (n, -1)))
    return jnp.concatenate(outs, axis=1)
