"""Normalization ops.

Mirrors `python/paddle/nn/functional/norm.py` (reference kernels:
`operators/batch_norm_op.*` → cuDNN, `layer_norm_op.*` hand-written CUDA with
welford reductions, `instance_norm_op`, `group_norm_op`). On TPU these are
plain jnp reductions — XLA fuses mean/var/normalize/affine into one or two
passes, matching the hand-fused CUDA kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _val(p):
    return p.value if hasattr(p, "value") else p


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW"):
    """Returns (out, new_mean, new_var) in training mode — the functional
    form; the BatchNorm layer handles buffer threading."""
    rm, rv = _val(running_mean), _val(running_var)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    bshape = tuple(x.shape[i] if i == channel_axis else 1
                   for i in range(x.ndim))
    compute_dtype = jnp.promote_types(x.dtype, jnp.float32)
    xc = x.astype(compute_dtype)
    if training:
        mean = jnp.mean(xc, axis=axes)
        var = jnp.var(xc, axis=axes)
        n = float(np.prod([x.shape[i] for i in axes]))
        unbiased = var * (n / max(n - 1.0, 1.0))
        new_mean = momentum * rm + (1.0 - momentum) * mean
        new_var = momentum * rv + (1.0 - momentum) * unbiased
    else:
        mean, var = rm.astype(compute_dtype), rv.astype(compute_dtype)
        new_mean, new_var = rm, rv
    inv = jnp.reshape((var + epsilon) ** -0.5, bshape)
    out = (xc - jnp.reshape(mean, bshape)) * inv
    if weight is not None:
        out = out * jnp.reshape(_val(weight).astype(compute_dtype), bshape)
    if bias is not None:
        out = out + jnp.reshape(_val(bias).astype(compute_dtype), bshape)
    return out.astype(x.dtype), new_mean.astype(rm.dtype), \
        new_var.astype(rv.dtype)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    """Reference: layer_norm_op. Stats in fp32 even under bf16 AMP (matches
    the reference's float accumulators)."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n, x.ndim))
    compute_dtype = jnp.promote_types(x.dtype, jnp.float32)
    xc = x.astype(compute_dtype)
    mean = jnp.mean(xc, axis=axes, keepdims=True)
    var = jnp.var(xc, axis=axes, keepdims=True)
    out = (xc - mean) * jnp.reciprocal(jnp.sqrt(var + epsilon))
    if weight is not None:
        out = out * _val(weight).astype(compute_dtype)
    if bias is not None:
        out = out + _val(bias).astype(compute_dtype)
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, epsilon=1e-5, data_format="NCHW"):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(2, x.ndim)) if channel_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + epsilon)
    bshape = tuple(x.shape[i] if i == channel_axis else 1
                   for i in range(x.ndim))
    if weight is not None:
        out = out * jnp.reshape(_val(weight), bshape)
    if bias is not None:
        out = out + jnp.reshape(_val(bias), bshape)
    return out


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format == "NCHW":
        n, c = x.shape[:2]
        spatial = x.shape[2:]
        g = num_groups
        xg = jnp.reshape(x, (n, g, c // g) + spatial)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        out = jnp.reshape((xg - mean) / jnp.sqrt(var + epsilon), x.shape)
        bshape = (1, c) + (1,) * len(spatial)
    else:
        n, c = x.shape[0], x.shape[-1]
        spatial = x.shape[1:-1]
        g = num_groups
        xg = jnp.reshape(x, (n,) + spatial + (g, c // g))
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        out = jnp.reshape((xg - mean) / jnp.sqrt(var + epsilon), x.shape)
        bshape = (1,) * (x.ndim - 1) + (c,)
    if weight is not None:
        out = out * jnp.reshape(_val(weight), bshape)
    if bias is not None:
        out = out + jnp.reshape(_val(bias), bshape)
    return out


def rms_norm(x, weight=None, epsilon=1e-6):
    """Beyond-reference: RMSNorm for modern LLM blocks."""
    compute_dtype = jnp.promote_types(x.dtype, jnp.float32)
    xc = x.astype(compute_dtype)
    ms = jnp.mean(jnp.square(xc), axis=-1, keepdims=True)
    out = xc * jnp.reciprocal(jnp.sqrt(ms + epsilon))
    if weight is not None:
        out = out * _val(weight).astype(compute_dtype)
    return out.astype(x.dtype)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    import jax
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    window = [1] * x.ndim
    window[channel_axis] = size
    pads = [(0, 0)] * x.ndim
    pads[channel_axis] = (half, size - half - 1)
    summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                   (1,) * x.ndim, pads)
    return x / jnp.power(k + alpha * summed, beta)


def lrn(x, n=5, k=1.0, alpha=1e-4, beta=0.75, data_format="NCHW"):
    """Reference: `lrn_op.cc` — the classic AlexNet local response norm
    (alias of local_response_norm with the 1.x argument names)."""
    return local_response_norm(x, size=n, alpha=alpha, beta=beta, k=k,
                               data_format=data_format)
