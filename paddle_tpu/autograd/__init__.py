"""Autograd — functional differentiation.

TPU-native replacement for the reference's imperative autograd engine
(`imperative/basic_engine.cc:305` reverse topological walk,
`partial_grad_engine.cc` for `paddle.grad`, `PyLayer` custom ops). On TPU the
whole step is traced and differentiated by `jax.grad`; there is no tape, no
per-op GradOpMaker, no dependency counting — XLA sees the full graph and
schedules it.

- `value_and_grad` / `grad`: differentiate pure functions (including
  `nn.functional_call` closures over a Layer).
- `PyLayer`: custom forward/backward via `jax.custom_vjp` (reference:
  `python/paddle/autograd/py_layer.py:192` + C++ `py_layer_fwd.h`).
- `no_grad`: parity context — inside, arrays are wrapped with
  `stop_gradient` on exit from the scope's functions (primarily an eager-mode
  annotation; under traced training use `jax.lax.stop_gradient`).
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Optional, Sequence, Union

import jax
from jax import lax


def value_and_grad(func: Callable, argnums: Union[int, Sequence[int]] = 0,
                   has_aux: bool = False, holomorphic: bool = False):
    return jax.value_and_grad(func, argnums=argnums, has_aux=has_aux,
                              holomorphic=holomorphic)


def grad(outputs=None, inputs=None, *, func: Optional[Callable] = None,
         argnums: Union[int, Sequence[int]] = 0, has_aux: bool = False,
         **kwargs):
    """Dual-form `grad`:

    - Functional (TPU-idiomatic): `grad(func)(x)` or
      `grad(func=..., argnums=...)` — thin wrapper over `jax.grad`.
    - `paddle.grad(outputs, inputs)` imperative form is NOT supported on an
      already-computed eager result (there is no tape); the error points the
      user at the functional form.
    """
    if callable(outputs) and inputs is None and func is None:
        return jax.grad(outputs, argnums=argnums, has_aux=has_aux)
    if func is not None:
        return jax.grad(func, argnums=argnums, has_aux=has_aux)
    raise RuntimeError(
        "paddle_tpu.grad(outputs, inputs) on eager tensors is unsupported: "
        "autograd is functional on TPU. Write the computation as a function "
        "and use paddle_tpu.grad(fn)(inputs) / value_and_grad(fn).")


stop_gradient = lax.stop_gradient


@contextlib.contextmanager
def no_grad():
    """Parity with `paddle.no_grad`. In the functional world gradients only
    flow through explicitly-differentiated functions, so this is a no-op
    scope; kept so reference training scripts port unchanged."""
    yield


def no_grad_(func=None):
    if func is None:
        return no_grad()

    @functools.wraps(func)
    def wrapper(*a, **k):
        return func(*a, **k)
    return wrapper


class PyLayerContext:
    """Reference: `paddle/autograd/py_layer.py:21` — save tensors between
    forward and backward."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class _PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)
        if bases and ("forward" in ns or "backward" in ns):
            cls._build()


class PyLayer(metaclass=_PyLayerMeta):
    """Custom autograd op (reference: PyLayer / C++ py_layer op).

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x ** 3
        @staticmethod
        def backward(ctx, dy):
            x, = ctx.saved_tensor
            return 3 * x ** 2 * dy

    Cube.apply(x) works in eager and under jit/grad — it lowers to
    `jax.custom_vjp`.
    """

    @classmethod
    def _build(cls):
        fwd_static = cls.__dict__.get("forward") or cls.forward
        bwd_static = cls.__dict__.get("backward") or cls.backward
        fwd = fwd_static.__func__ if isinstance(fwd_static, staticmethod) \
            else fwd_static
        bwd = bwd_static.__func__ if isinstance(bwd_static, staticmethod) \
            else bwd_static

        @jax.custom_vjp
        def op(*args):
            return fwd(PyLayerContext(), *args)

        def op_fwd(*args):
            ctx = PyLayerContext()
            out = fwd(ctx, *args)
            # residuals must be jax types: persist only the saved tensors
            return out, tuple(ctx._saved)

        def op_bwd(saved, g):
            ctx = PyLayerContext()
            ctx._saved = tuple(saved)
            grads = bwd(ctx, *(g if isinstance(g, tuple) else (g,)))
            if not isinstance(grads, tuple):
                grads = (grads,)
            return grads

        cls._op = op
        cls._op_fwd = op_fwd
        cls._op_bwd = op_bwd
        op.defvjp(op_fwd, op_bwd)

    @classmethod
    def apply(cls, *args):
        return cls._op(*args)

    @staticmethod
    def forward(ctx, *args):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError


def jacobian(func, xs, create_graph=False):
    return jax.jacrev(func)(xs)


def hessian(func, xs, create_graph=False):
    return jax.hessian(func)(xs)


def vjp(func, xs, v=None):
    out, pullback = jax.vjp(func, xs)
    if v is None:
        import jax.numpy as jnp
        v = jnp.ones_like(out)
    return out, pullback(v)[0]


def jvp(func, xs, v):
    return jax.jvp(func, (xs,), (v,))


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Reference: `paddle.autograd.backward` (imperative tape backward).
    Autograd here is functional — there is no tape behind an eager array —
    so this mirrors `paddle_tpu.grad`'s contract: write the computation as
    a function and differentiate it."""
    raise RuntimeError(
        "paddle_tpu.autograd.backward(tensors) is unsupported: autograd "
        "is functional on TPU (no tape). Write the computation as a "
        "function and use paddle_tpu.grad(fn) / value_and_grad(fn); for "
        "custom backward rules use PyLayer (jax.custom_vjp).")
