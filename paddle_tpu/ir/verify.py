"""IR well-formedness verifier (reference: the graph checks
`paddle/fluid/framework/ir/graph_helper.cc` runs after passes —
`HasCircle`, dangling-node detection — restated over the jaxpr IR).

A buggy pass does not fail where it runs; it produces a jaxpr that
miscompiles (or crashes deep inside XLA lowering) at the NEXT use, with
an error pointing nowhere near the pass. `verify_jaxpr` pins the
invariants every pass must preserve, immediately after the pass:

  * defs-before-uses — every eqn input is a program input, constvar,
    literal, or the output of an EARLIER eqn (jaxprs are topologically
    ordered SSA; a pass that reorders or rewires eqns breaks this
    first);
  * single assignment — no var is defined twice;
  * no dangling outvars — every program output is actually defined
    (dropout_removal retargets outvars through its substitution map; a
    bug there leaves an output pointing at a deleted eqn);
  * no empty eqns — every eqn defines at least one output;
  * fused-op arity — call-style eqns carrying a subgraph (`pjit`,
    `closed_call`, `core_call` — the jaxpr spelling of a fused op, e.g.
    the `_where`/`_bernoulli` sites dropout_removal rewrites) must bind
    exactly as many invars/outvars as their inner jaxpr declares.

Wiring: `Program.apply_pass` calls `maybe_verify` after EVERY
registered pass when verification is on. The switch is the
`PTPU_IR_VERIFY` env var (default off in production — the walk is
O(eqns) cheap but not free) or an explicit `set_verify(True)`;
tests/conftest.py turns it on for the whole tier-1 suite.
"""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["IRVerificationError", "verify_jaxpr", "verify_program",
           "maybe_verify", "set_verify", "enabled"]

_FLAG: Optional[bool] = None  # explicit override; None defers to env


class IRVerificationError(RuntimeError):
    """A pass produced an ill-formed jaxpr (the message lists every
    violated invariant and the pass that produced it)."""


def set_verify(on: Optional[bool]) -> None:
    """Force verification on/off; None restores the env-var default."""
    global _FLAG
    _FLAG = on


def enabled() -> bool:
    if _FLAG is not None:
        return _FLAG
    return os.environ.get("PTPU_IR_VERIFY", "0").lower() not in (
        "0", "", "false", "off")


# call-style primitives whose params carry the fused subgraph and whose
# eqn arity must match it exactly (scan/while/cond pack extra operands
# around their bodies, so they are checked structurally, not by arity)
_ARITY_CHECKED = {"pjit", "closed_call", "core_call"}


def _inner_jaxpr(params: dict):
    for key in ("jaxpr", "call_jaxpr"):
        v = params.get(key)
        if v is None:
            continue
        return v.jaxpr if hasattr(v, "jaxpr") else v
    return None


def verify_jaxpr(jaxpr, pass_name: Optional[str] = None) -> None:
    """Raise IRVerificationError if `jaxpr` violates an invariant."""
    from jax.extend.core import Literal

    errors: List[str] = []
    where = f" after pass {pass_name!r}" if pass_name else ""

    defined = set()
    for v in list(jaxpr.constvars) + list(jaxpr.invars):
        if id(v) in defined:
            errors.append(f"program binder {v} appears twice")
        defined.add(id(v))

    for i, e in enumerate(jaxpr.eqns):
        prim = e.primitive.name
        for v in e.invars:
            if isinstance(v, Literal):
                continue
            if id(v) not in defined:
                errors.append(
                    f"eqn {i} ({prim}): input {v} is used before any "
                    f"definition — defs-before-uses violated")
        if not e.outvars:
            errors.append(f"eqn {i} ({prim}) defines no outputs")
        for v in e.outvars:
            if type(v).__name__ == "DropVar":
                continue
            if id(v) in defined:
                errors.append(
                    f"eqn {i} ({prim}): output {v} redefines an "
                    f"existing var — single assignment violated")
            defined.add(id(v))
        if prim in _ARITY_CHECKED:
            inner = _inner_jaxpr(e.params)
            if inner is not None:
                if len(e.invars) != len(inner.invars):
                    errors.append(
                        f"eqn {i} ({prim}): binds {len(e.invars)} "
                        f"inputs but its subgraph declares "
                        f"{len(inner.invars)} — fused-op arity broken")
                if len(e.outvars) != len(inner.outvars):
                    errors.append(
                        f"eqn {i} ({prim}): binds {len(e.outvars)} "
                        f"outputs but its subgraph declares "
                        f"{len(inner.outvars)} — fused-op arity broken")

    for v in jaxpr.outvars:
        if isinstance(v, Literal):
            continue
        if id(v) not in defined:
            errors.append(
                f"program output {v} is dangling — no binder or eqn "
                f"defines it")

    if errors:
        raise IRVerificationError(
            f"ill-formed jaxpr{where}: " + "; ".join(errors))


def verify_program(program, pass_name: Optional[str] = None) -> None:
    verify_jaxpr(program.closed.jaxpr, pass_name=pass_name)


def maybe_verify(program, pass_name: Optional[str] = None):
    """Verify when enabled; always returns `program` so apply_pass can
    tail-call it."""
    if enabled():
        verify_program(program, pass_name=pass_name)
    return program
