"""Op-level IR + pass framework.

Reference mapping:
  * ProgramDesc/BlockDesc/OpDesc (`framework/framework.proto:43-207`) —
    the serialized op-level program;
  * `framework/ir/` Pass framework + GraphPatternDetector
    (`ir/graph_pattern_detector.cc`, 72+ passes).

TPU-native: the op-level program IS the jaxpr — typed, SSA, already the
form every jax transform manipulates. `Program` wraps a ClosedJaxpr with
a Paddle-flavored surface: `ops()` lists OpDesc-like views,
`find_pattern` is the GraphPatternDetector, passes are functions from
eqn-list to eqn-list registered in a `PassRegistry`, and the result
compiles straight back through XLA (`to_callable`). Serialization rides
StableHLO (`jit.save`), the same artifact the inference engine loads —
unlike the reference there is no second proto format to keep in sync.

Most reference passes (fusion, memory reuse, layout) are subsumed by
XLA; the infra here exists for the passes XLA can NOT see: framework-
level rewrites like dropout removal for inference, collective
annotation, quant/dequant insertion, or DCE after head-pruning.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax


class OpView:
    """OpDesc-like read view of one jaxpr eqn (reference:
    `framework.proto:43` OpDesc {type, inputs, outputs, attrs})."""

    def __init__(self, eqn):
        self._eqn = eqn

    @property
    def type(self) -> str:
        return self._eqn.primitive.name

    @property
    def inputs(self) -> List[str]:
        return [str(v) for v in self._eqn.invars]

    @property
    def outputs(self) -> List[str]:
        return [str(v) for v in self._eqn.outvars]

    @property
    def attrs(self) -> dict:
        return dict(self._eqn.params)

    def __repr__(self):
        return (f"OpView({self.type}: {', '.join(self.inputs)} -> "
                f"{', '.join(self.outputs)})")


class Program:
    """A captured op-level program (reference: ProgramDesc)."""

    def __init__(self, closed_jaxpr):
        self.closed = closed_jaxpr

    # -- capture ----------------------------------------------------------

    @classmethod
    def capture(cls, fn: Callable, *example_args, **example_kwargs):
        """Trace `fn` into a Program (reference: Program construction via
        `program_guard` + append_op; here one jax trace)."""
        closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
        return cls(closed)

    # -- inspection -------------------------------------------------------

    def ops(self) -> List[OpView]:
        return [OpView(e) for e in self.closed.jaxpr.eqns]

    def op_types(self) -> List[str]:
        return [o.type for o in self.ops()]

    def find_pattern(self, pattern: Sequence[str]) -> List[List[OpView]]:
        """GraphPatternDetector-lite: consecutive def-use chains whose
        primitive names match `pattern` (each op's output feeds the
        next)."""
        eqns = self.closed.jaxpr.eqns
        hits = []
        for i, e in enumerate(eqns):
            if e.primitive.name != pattern[0]:
                continue
            chain = [e]
            for want in pattern[1:]:
                nxt = None
                outs = set(map(id, chain[-1].outvars))
                for e2 in eqns[i + 1:]:
                    if e2.primitive.name == want and \
                            any(id(v) in outs for v in e2.invars):
                        nxt = e2
                        break
                if nxt is None:
                    break
                chain.append(nxt)
            if len(chain) == len(pattern):
                hits.append([OpView(e) for e in chain])
        return hits

    # -- passes -----------------------------------------------------------

    def apply_pass(self, name_or_fn) -> "Program":
        """Run a registered pass (or a callable eqns->eqns) and return a
        NEW Program (reference: `ir/pass.h` Pass::Apply)."""
        fn = PassRegistry.get(name_or_fn) if isinstance(name_or_fn, str) \
            else name_or_fn
        jaxpr = self.closed.jaxpr
        new_eqns = fn(list(jaxpr.eqns), jaxpr)
        new_jaxpr = jaxpr.replace(eqns=new_eqns)
        return Program(self.closed.replace(jaxpr=new_jaxpr))

    # -- execution / export ----------------------------------------------

    def to_callable(self) -> Callable:
        closed = self.closed

        def run(*args):
            flat = jax.tree.leaves(args)
            out = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
            return out[0] if len(out) == 1 else tuple(out)
        return run

    def __call__(self, *args):
        return self.to_callable()(*args)

    def __repr__(self):
        return f"Program({len(self.closed.jaxpr.eqns)} ops)"

    def __str__(self):
        return str(self.closed)


class PassRegistry:
    """Reference: `ir/pass.h` PassRegistry + REGISTER_PASS."""

    _passes: Dict[str, Callable] = {}

    @classmethod
    def register(cls, name: str):
        def deco(fn):
            cls._passes[name] = fn
            return fn
        return deco

    @classmethod
    def get(cls, name: str) -> Callable:
        if name not in cls._passes:
            raise KeyError(f"unknown pass {name!r}; registered: "
                           f"{sorted(cls._passes)}")
        return cls._passes[name]

    @classmethod
    def list(cls) -> List[str]:
        return sorted(cls._passes)


# --------------------------------------------------------------------------
# Built-in passes
# --------------------------------------------------------------------------

@PassRegistry.register("dead_code_elimination")
def dead_code_elimination(eqns, jaxpr):
    """Drop eqns none of whose outputs are used (reference:
    `ir/memory_optimize_pass/eager_deletion_pass.cc` spirit; here a
    classic backward liveness sweep)."""
    from jax.extend.core import Literal
    live = {id(v) for v in jaxpr.outvars}
    kept = []
    for e in reversed(eqns):
        used = any(id(v) in live for v in e.outvars)
        # keep possibly-effectful primitives conservatively
        effectful = bool(getattr(e, "effects", ()))
        if used or effectful:
            kept.append(e)
            for v in e.invars:
                if not isinstance(v, Literal):
                    live.add(id(v))
    return list(reversed(kept))


@PassRegistry.register("op_stats")
def op_stats(eqns, jaxpr):
    """Identity pass that prints an op histogram (reference:
    `graph_viz_pass` class of diagnostics)."""
    import collections
    hist = collections.Counter(e.primitive.name for e in eqns)
    for name, n in hist.most_common():
        print(f"{name:24s} {n}")
    return eqns
