"""Op-level IR + pass framework.

Reference mapping:
  * ProgramDesc/BlockDesc/OpDesc (`framework/framework.proto:43-207`) —
    the serialized op-level program;
  * `framework/ir/` Pass framework + GraphPatternDetector
    (`ir/graph_pattern_detector.cc`, 72+ passes).

TPU-native: the op-level program IS the jaxpr — typed, SSA, already the
form every jax transform manipulates. `Program` wraps a ClosedJaxpr with
a Paddle-flavored surface: `ops()` lists OpDesc-like views,
`find_pattern` is the GraphPatternDetector, passes are functions from
eqn-list to eqn-list registered in a `PassRegistry`, and the result
compiles straight back through XLA (`to_callable`). Serialization rides
StableHLO (`jit.save`), the same artifact the inference engine loads —
unlike the reference there is no second proto format to keep in sync.

Most reference passes (fusion, memory reuse, layout) are subsumed by
XLA; the infra here exists for the passes XLA can NOT see: framework-
level rewrites — `dead_code_elimination`, and `dropout_removal` (the
inference rewrite `jit.save` applies before export and
`inference.Predictor` checks on load; reference:
`delete_dropout_op_pass.cc`). Quant/dequant insertion and DCE after
head-pruning are further candidates on the same registry.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax


class OpView:
    """OpDesc-like read view of one jaxpr eqn (reference:
    `framework.proto:43` OpDesc {type, inputs, outputs, attrs})."""

    def __init__(self, eqn):
        self._eqn = eqn

    @property
    def type(self) -> str:
        return self._eqn.primitive.name

    @property
    def inputs(self) -> List[str]:
        return [str(v) for v in self._eqn.invars]

    @property
    def outputs(self) -> List[str]:
        return [str(v) for v in self._eqn.outvars]

    @property
    def attrs(self) -> dict:
        return dict(self._eqn.params)

    def __repr__(self):
        return (f"OpView({self.type}: {', '.join(self.inputs)} -> "
                f"{', '.join(self.outputs)})")


class Program:
    """A captured op-level program (reference: ProgramDesc)."""

    def __init__(self, closed_jaxpr):
        self.closed = closed_jaxpr

    # -- capture ----------------------------------------------------------

    @classmethod
    def capture(cls, fn: Callable, *example_args, **example_kwargs):
        """Trace `fn` into a Program (reference: Program construction via
        `program_guard` + append_op; here one jax trace)."""
        closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
        return cls(closed)

    # -- inspection -------------------------------------------------------

    def ops(self) -> List[OpView]:
        return [OpView(e) for e in self.closed.jaxpr.eqns]

    def op_types(self) -> List[str]:
        return [o.type for o in self.ops()]

    def find_pattern(self, pattern: Sequence[str]) -> List[List[OpView]]:
        """GraphPatternDetector-lite: consecutive def-use chains whose
        primitive names match `pattern` (each op's output feeds the
        next)."""
        eqns = self.closed.jaxpr.eqns
        hits = []
        for i, e in enumerate(eqns):
            if e.primitive.name != pattern[0]:
                continue
            chain = [e]
            for want in pattern[1:]:
                nxt = None
                outs = set(map(id, chain[-1].outvars))
                for e2 in eqns[i + 1:]:
                    if e2.primitive.name == want and \
                            any(id(v) in outs for v in e2.invars):
                        nxt = e2
                        break
                if nxt is None:
                    break
                chain.append(nxt)
            if len(chain) == len(pattern):
                hits.append([OpView(e) for e in chain])
        return hits

    # -- passes -----------------------------------------------------------

    def apply_pass(self, name_or_fn) -> "Program":
        """Run a registered pass (or a callable eqns->eqns) and return a
        NEW Program (reference: `ir/pass.h` Pass::Apply). A pass may
        return either the new eqn list or an (eqns, outvars) pair —
        rewrites that replace a program OUTPUT (e.g. dropout as the
        last op) need to retarget outvars as well.

        When IR verification is on (PTPU_IR_VERIFY=1 or
        `ir.verify.set_verify(True)`; tier-1 runs with it on), the
        result is checked against the jaxpr well-formedness invariants
        (defs-before-uses, SSA, no dangling outvars, fused-op arity)
        IMMEDIATELY — a buggy pass fails here with the pass named,
        instead of miscompiling at the next trace."""
        from . import verify as _verify
        fn = PassRegistry.get(name_or_fn) if isinstance(name_or_fn, str) \
            else name_or_fn
        jaxpr = self.closed.jaxpr
        res = fn(list(jaxpr.eqns), jaxpr)
        if isinstance(res, tuple):
            new_eqns, new_outvars = res
            new_jaxpr = jaxpr.replace(eqns=new_eqns,
                                      outvars=list(new_outvars))
        else:
            new_jaxpr = jaxpr.replace(eqns=res)
        out = Program(self.closed.replace(jaxpr=new_jaxpr))
        pass_name = name_or_fn if isinstance(name_or_fn, str) else \
            getattr(fn, "__name__", repr(fn))
        return _verify.maybe_verify(out, pass_name=pass_name)

    # -- execution / export ----------------------------------------------

    def to_callable(self) -> Callable:
        closed = self.closed

        def run(*args):
            flat = jax.tree.leaves(args)
            out = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
            return out[0] if len(out) == 1 else tuple(out)
        return run

    def __call__(self, *args):
        return self.to_callable()(*args)

    def __repr__(self):
        return f"Program({len(self.closed.jaxpr.eqns)} ops)"

    def __str__(self):
        return str(self.closed)


class PassRegistry:
    """Reference: `ir/pass.h` PassRegistry + REGISTER_PASS."""

    _passes: Dict[str, Callable] = {}

    @classmethod
    def register(cls, name: str):
        def deco(fn):
            cls._passes[name] = fn
            return fn
        return deco

    @classmethod
    def get(cls, name: str) -> Callable:
        if name not in cls._passes:
            raise KeyError(f"unknown pass {name!r}; registered: "
                           f"{sorted(cls._passes)}")
        return cls._passes[name]

    @classmethod
    def list(cls) -> List[str]:
        return sorted(cls._passes)


# --------------------------------------------------------------------------
# Built-in passes
# --------------------------------------------------------------------------

@PassRegistry.register("dead_code_elimination")
def dead_code_elimination(eqns, jaxpr):
    """Drop eqns none of whose outputs are used (reference:
    `ir/memory_optimize_pass/eager_deletion_pass.cc` spirit; here a
    classic backward liveness sweep)."""
    from jax.extend.core import Literal
    live = {id(v) for v in jaxpr.outvars}
    kept = []
    for e in reversed(eqns):
        used = any(id(v) in live for v in e.outvars)
        # keep possibly-effectful primitives conservatively
        effectful = bool(getattr(e, "effects", ()))
        if used or effectful:
            kept.append(e)
            for v in e.invars:
                if not isinstance(v, Literal):
                    live.add(id(v))
    return list(reversed(kept))


_RNG_PRIMS = frozenset({
    "random_seed", "random_split", "random_bits", "random_wrap",
    "random_fold_in", "random_unwrap", "random_gamma", "threefry2x32"})


def _inner_jaxprs(params: dict):
    for v in params.values():
        if hasattr(v, "jaxpr"):        # ClosedJaxpr (pjit, custom_* ...)
            yield v.jaxpr
        elif hasattr(v, "eqns"):       # raw Jaxpr
            yield v


def _jaxpr_has_rng(jaxpr) -> bool:
    for e in jaxpr.eqns:
        if e.primitive.name in _RNG_PRIMS:
            return True
        for inner in _inner_jaxprs(e.params):
            if _jaxpr_has_rng(inner):
                return True
    return False


def has_rng_ops(closed_jaxpr) -> bool:
    """True when the program samples randomness (dropout and friends) —
    the load/save hooks use this to decide whether `dropout_removal`
    has anything to do."""
    return _jaxpr_has_rng(closed_jaxpr.jaxpr)


def _is_zero(v, producers, depth: int = 0) -> bool:
    from jax.extend.core import Literal
    if isinstance(v, Literal):
        try:
            import numpy as np
            return float(np.asarray(v.val)) == 0.0
        except (TypeError, ValueError):
            return False
    if depth > 4:
        return False
    e = producers.get(id(v))
    if e is not None and e.primitive.name in ("broadcast_in_dim",
                                              "convert_element_type"):
        return _is_zero(e.invars[0], producers, depth + 1)
    return False


def _keep_prob(pred, producers, depth: int = 0):
    """The bernoulli keep probability behind a dropout mask predicate,
    or None when it cannot be established. jax.random.bernoulli traces
    as `pjit[name=_bernoulli](key, p)` with p a scalar literal; the
    mask may pass through broadcasts/converts on its way to the
    select."""
    from jax.extend.core import Literal
    if depth > 4 or isinstance(pred, Literal):
        return None
    e = producers.get(id(pred))
    if e is None:
        return None
    name = e.primitive.name
    if name == "pjit" and e.params.get("name") == "_bernoulli" and \
            len(e.invars) == 2 and isinstance(e.invars[1], Literal):
        try:
            import numpy as np
            return float(np.asarray(e.invars[1].val))
        except (TypeError, ValueError):
            return None
    if name in ("broadcast_in_dim", "convert_element_type", "reshape"):
        return _keep_prob(e.invars[0], producers, depth + 1)
    return None


@PassRegistry.register("dropout_removal")
def dropout_removal(eqns, jaxpr):
    """Remove train-mode dropout for inference (reference:
    `delete_dropout_op_pass.cc`; here over the jaxpr).

    A dropout site is a select whose PREDICATE is RNG-derived
    (`where(bernoulli(key, keep), x / keep, 0)` in the default
    upscale_in_train mode): taint vars forward from the RNG primitives,
    find select_n / pjit-`_where` eqns with a tainted predicate and a
    zero branch, VERIFY the kept branch is `x / keep` with the divisor
    equal to the bernoulli keep probability, and rewire consumers to x
    — exactly the eval-mode (training=False) semantics. Sites that
    don't match the proven pattern (downscale_in_infer, whose eval
    semantics is x*(1-p), or a div that is user arithmetic rather than
    the upscale) are conservatively LEFT IN PLACE — never a silent
    numerics change — and `has_rng_ops` still reports them. The
    orphaned RNG chain then falls to DCE. A site whose result is a
    direct program output (dropout as the model's last op) retargets
    the outvar via the (eqns, outvars) pass return form.
    """
    from jax.extend.core import Literal
    tainted: set = set()

    def is_tainted(v) -> bool:
        return not isinstance(v, Literal) and id(v) in tainted

    producers = {}
    for e in eqns:
        rng_src = e.primitive.name in _RNG_PRIMS or any(
            _jaxpr_has_rng(inner) for inner in _inner_jaxprs(e.params))
        if rng_src or any(is_tainted(v) for v in e.invars):
            for v in e.outvars:
                tainted.add(id(v))
        for v in e.outvars:
            producers[id(v)] = e

    subst = {}          # id(select outvar) -> replacement var
    drop: set = set()   # id(eqn) to delete
    for e in eqns:
        name = e.primitive.name
        if name == "select_n" and len(e.invars) == 3:
            pred, on_false, on_true = e.invars
            cases = [on_false, on_true]
        elif name == "pjit" and e.params.get("name") == "_where" and \
                len(e.invars) == 3:
            pred, on_true, on_false = e.invars
            cases = [on_false, on_true]
        else:
            continue
        if not is_tainted(pred):
            continue
        zero = [c for c in cases if _is_zero(c, producers)]
        kept = [c for c in cases if not _is_zero(c, producers)]
        if len(zero) != 1 or len(kept) != 1:
            continue
        v = kept[0]
        if isinstance(v, Literal):
            continue
        # Only rewrite the PROVEN upscale_in_train shape
        # where(bern(keep), x / keep, 0): the kept branch must be a div
        # whose literal divisor equals the bernoulli keep probability.
        # Anything else — downscale_in_infer (eval semantics x*(1-p),
        # not x) or a kept branch whose div is the USER's arithmetic —
        # is left in place rather than silently changing numerics; the
        # save hook's has_rng_ops recheck then warns.
        keep = _keep_prob(pred, producers)
        pe = producers.get(id(v))
        if keep is None or pe is None or pe.primitive.name != "div" \
                or not isinstance(pe.invars[1], Literal):
            continue
        try:
            import numpy as np
            divisor = float(np.asarray(pe.invars[1].val))
        except (TypeError, ValueError):
            continue
        if abs(divisor - keep) > 1e-6 * max(1.0, abs(keep)):
            continue
        v = pe.invars[0]    # x / keep -> x (exact eval-mode value)
        if len(e.outvars) != 1:
            continue
        subst[id(e.outvars[0])] = v
        drop.add(id(e))
    if not subst:
        return eqns

    def resolve(v):
        while not isinstance(v, Literal) and id(v) in subst:
            v = subst[id(v)]
        return v

    new_eqns = []
    for e in eqns:
        if id(e) in drop:
            continue
        if any(not isinstance(v, Literal) and id(v) in subst
               for v in e.invars):
            e = e.replace(invars=[resolve(v) for v in e.invars])
        new_eqns.append(e)
    new_outvars = [resolve(v) for v in jaxpr.outvars]
    return (dead_code_elimination(new_eqns,
                                  jaxpr.replace(outvars=new_outvars)),
            new_outvars)


# the ISSUE/VERDICT spelling — same pass object under both names
PassRegistry._passes["dropout-removal"] = dropout_removal


@PassRegistry.register("op_stats")
def op_stats(eqns, jaxpr):
    """Identity pass that prints an op histogram (reference:
    `graph_viz_pass` class of diagnostics)."""
    import collections
    hist = collections.Counter(e.primitive.name for e in eqns)
    for name, n in hist.most_common():
        print(f"{name:24s} {n}")
    return eqns
