"""Collective communication API.

Mirrors `python/paddle/distributed/collective.py:166-1455` (all_reduce,
broadcast, all_gather, reduce, scatter, alltoall, send/recv, barrier,
new_group) whose reference backends are the `operators/collective/c_*` NCCL
kernels keyed by `ring_id` (`c_allreduce_op.h:253-322`).

TPU-native semantics: a "group" is a named mesh axis. Inside a traced
`shard_map` region the ops lower to XLA collectives over ICI
(psum/all_gather/ppermute/all_to_all); in eager single-process code they
operate on the global (replicated) view, so reductions over a size-1 or
replicated axis are identity — matching how the reference's ops behave with
ring size 1. No stream-sync ops exist: XLA schedules communication.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .env import get_rank, get_world_size

# op codes (parity with paddle.distributed.ReduceOp)
class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A named-axis handle (replaces NCCL ring_id)."""

    def __init__(self, axis_name: str, ranks=None):
        self.axis_name = axis_name
        self.ranks = ranks

    @property
    def nranks(self):
        # lazy: get_world_size() touches jax.process_count(), which
        # initializes a backend — must NOT happen at import time (a
        # module-level Group would dial the TPU tunnel on every import)
        return len(self.ranks) if self.ranks else get_world_size()

    def __repr__(self):
        return f"Group(axis={self.axis_name!r})"


_DEFAULT_GROUP = Group("data")


def new_group(ranks=None, backend=None, axis_name: str = "data") -> Group:
    """Reference: collective.py:206 — creates an extra NCCL ring. Here: a
    handle onto a mesh axis (create the axis via topology.build_mesh)."""
    return Group(axis_name, ranks)


def _axis(group) -> Optional[str]:
    if group is None:
        return "data"
    if isinstance(group, Group):
        return group.axis_name
    return str(group)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _eager_multiproc_guard(op_name: str):
    """Eager collectives in a multi-process job are a silent semantic
    divergence (VERDICT r5 item 7): the reference's eager ops REALLY
    communicate (`collective.py:413` NCCL rings), while the TPU-native
    eager path only sees this process's replicated view — returning the
    input unchanged would silently skip the cross-rank reduction. Raise
    with guidance instead. Single-process (world 1) keeps the identity
    semantics: there is nothing to communicate."""
    world = get_world_size()
    if world > 1:
        raise RuntimeError(
            f"paddle_tpu.distributed.{op_name}: called OUTSIDE a traced "
            f"computation in a {world}-process job. Eager collectives "
            f"do not communicate across processes here (the op would "
            f"silently return its input). Run the op inside the traced "
            f"step so it lowers to an XLA collective over the mesh "
            f"axis (see MIGRATION.md 'Collectives'), or exchange host "
            f"data explicitly via the PS KV store "
            f"(paddle_tpu.distributed.ps).")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    """Reference: c_allreduce_{sum,max,min,prod}."""
    axis = _axis(group)
    if _in_trace(tensor):
        try:
            if op == ReduceOp.SUM:
                return lax.psum(tensor, axis)
            if op == ReduceOp.MAX:
                return lax.pmax(tensor, axis)
            if op == ReduceOp.MIN:
                return lax.pmin(tensor, axis)
            if op == ReduceOp.AVG:
                return lax.pmean(tensor, axis)
            if op == ReduceOp.PROD:
                return jnp.exp(lax.psum(jnp.log(tensor), axis))
        except NameError:
            return tensor  # axis not mapped here → group of size 1
    _eager_multiproc_guard("all_reduce")
    return tensor  # eager global view: already reduced/replicated


def all_gather(tensor_list, tensor=None, group=None, sync_op=True,
               use_calc_stream=True, axis: int = 0):
    """Reference: c_allgather. Functional form returns the gathered array;
    the paddle list-out form appends to `tensor_list`."""
    if isinstance(tensor_list, list):
        t = tensor
        out = _all_gather_impl(t, group, axis)
        n = out.shape[axis] // t.shape[axis] if t.shape else 1
        tensor_list.extend(jnp.split(out, n, axis=axis))
        return tensor_list
    return _all_gather_impl(tensor_list, group, axis)


def _all_gather_impl(tensor, group, axis):
    ax = _axis(group)
    if _in_trace(tensor):
        try:
            return lax.all_gather(tensor, ax, axis=axis, tiled=True)
        except NameError:
            return tensor
    _eager_multiproc_guard("all_gather")
    return tensor


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, axis: int = 0):
    """Reference: c_reducescatter."""
    ax = _axis(group)
    if _in_trace(tensor):
        try:
            return lax.psum_scatter(tensor, ax, scatter_dimension=axis,
                                    tiled=True)
        except NameError:
            return tensor
    _eager_multiproc_guard("reduce_scatter")
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=True):
    """Reference: c_broadcast. Under SPMD every device computes the same
    program, so broadcast is realized by selecting src's shard."""
    ax = _axis(group)
    if _in_trace(tensor):
        try:
            idx = lax.axis_index(ax)
            full = lax.all_gather(tensor, ax)
            return full[src]
        except NameError:
            return tensor
    _eager_multiproc_guard("broadcast")
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=True):
    """Reference: c_reduce_*. SPMD form: psum everywhere (result only
    meaningful on dst, same contract as NCCL reduce)."""
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            use_calc_stream=True):
    ax = _axis(group)
    if tensor_list is not None and not _in_trace(tensor):
        return tensor_list[get_rank()]
    if _in_trace(tensor):
        try:
            idx = lax.axis_index(ax)
            n = lax.axis_size(ax)
            chunk = tensor.shape[0] // n
            return lax.dynamic_slice_in_dim(tensor, idx * chunk, chunk)
        except NameError:
            return tensor
    _eager_multiproc_guard("scatter")
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op=True, use_calc_stream=True):
    """Reference: alltoall_op. Traced form over a mesh axis uses
    lax.all_to_all; this is the building block for Ulysses sequence
    parallelism (see distributed/sequence_parallel.py)."""
    ax = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = jnp.stack(list(in_tensor_list), axis=0)
    else:
        stacked = in_tensor_list
    if _in_trace(stacked):
        try:
            out = lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0,
                                 tiled=False)
            if out_tensor_list is not None:
                out_tensor_list.extend(list(out))
                return out_tensor_list
            return out
        except NameError:
            pass   # traced, axis unmapped: group of size 1 — identity
    else:
        _eager_multiproc_guard("alltoall")
    if out_tensor_list is not None:
        out_tensor_list.extend(list(stacked))
        return out_tensor_list
    return stacked


def all_to_all_single(tensor, group=None, split_axis=0, concat_axis=0):
    ax = _axis(group)
    if _in_trace(tensor):
        try:
            return lax.all_to_all(tensor, ax, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
        except NameError:
            return tensor
    _eager_multiproc_guard("all_to_all_single")
    return tensor


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=True):
    """Reference: send_v2. SPMD equivalent is a collective_permute — use
    `p2p_push` with an explicit perm inside shard_map."""
    if not _in_trace(tensor):
        _eager_multiproc_guard("send")
    return tensor


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=True):
    if not _in_trace(tensor):
        _eager_multiproc_guard("recv")
    return tensor


def p2p_push(tensor, perm, group=None):
    """collective_permute over the group axis (reference: send_v2/recv_v2
    pairs in pipeline parallelism). `perm`: list of (src, dst)."""
    ax = _axis(group)
    if _in_trace(tensor):
        try:
            return lax.ppermute(tensor, ax, perm)
        except NameError:
            return tensor
    _eager_multiproc_guard("p2p_push")
    return tensor


def barrier(group=None):
    """Reference: barrier_op. Host-level sync across processes."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def get_group(id=0):
    return _DEFAULT_GROUP


def wait(tensor, group=None, use_calc_stream=True):
    """Reference: c_wait_comm / c_sync_comm_stream — XLA schedules comm, so
    this only blocks the host until `tensor` is ready."""
    if hasattr(tensor, "block_until_ready"):
        tensor.block_until_ready()
    return tensor


def split(x, num_partitions, axis=0):
    return jnp.split(x, num_partitions, axis=axis)
