"""`paddle.distributed.utils` parity surface.

Reference: `python/paddle/distributed/utils.py` (free-port discovery,
endpoint parsing, process watchdogs for the launcher). The launcher here
(`distributed/launch.py`) carries the process machinery; these are the
script-facing helpers.
"""
from __future__ import annotations

import socket


def find_free_ports(num: int):
    """Reference: utils.py find_free_ports — grab `num` ephemeral ports."""
    ports = set()
    socks = []
    try:
        while len(ports) < num:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("", 0))
            socks.append(s)
            ports.add(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def add_arguments(argname, type, default, help, argparser, **kwargs):  # noqa: A002
    """Reference: utils.py add_arguments — argparse sugar used by scripts."""
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=help + f" Default: {default}.", **kwargs)
