"""Sparse-table entry admission policies.

Reference: `python/paddle/distributed/entry_attr.py` — `ProbabilityEntry`
(admit a new sparse feature with probability p) and `CountFilterEntry`
(admit after min_count occurrences). Consumed by the PS sparse table
(`paddle_tpu/distributed/ps/table.py`) when deciding whether an unseen
feature id gets a row.
"""
from __future__ import annotations


class EntryAttr:
    def _to_attr(self) -> str:
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    def __init__(self, probability: float):
        if not 0 < probability <= 1:
            raise ValueError(
                f"probability must be in (0, 1], got {probability}")
        self._probability = float(probability)

    def _to_attr(self) -> str:
        return f"probability_entry:{self._probability}"

    def should_admit(self, rng) -> bool:
        return bool(rng.random() < self._probability)


class CountFilterEntry(EntryAttr):
    def __init__(self, count_filter: int):
        if count_filter < 0:
            raise ValueError(
                f"count_filter must be >= 0, got {count_filter}")
        self._count_filter = int(count_filter)

    def _to_attr(self) -> str:
        return f"count_filter_entry:{self._count_filter}"

    def should_admit(self, seen_count: int) -> bool:
        return seen_count >= self._count_filter
