"""Advanced PS table modes: Geo-SGD, SSD-backed storage, graph table.

Reference mapping:
  * Geo-SGD — `paddle/fluid/distributed/table/sparse_geo_table.cc` +
    geo mode in `service/communicator.cc` (trainers apply updates
    LOCALLY and periodically push accumulated deltas to the global
    table, pulling fresh rows on the way back);
  * SSD-backed sparse table — `table/ssd_sparse_table.cc` (hot rows in
    memory, cold rows on disk);
  * graph table for GNN sampling — `table/common_graph_table.cc` +
    `service/graph_brpc_server.cc` (neighbor storage + sampling RPC).

TPU-native shape: these are host-side structures feeding the compiled
dense step, exactly like the base `_Shard`; the wire protocol of
`TableService` carries their RPCs.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .table import TableService, _rows_normal, _shard_bounds


class GeoTable:
    """Trainer-local view with Geo-SGD semantics (reference:
    `sparse_geo_table.cc`): updates apply to a LOCAL replica immediately;
    every `geo_step` pushes the accumulated delta to the global sharded
    table and refreshes the touched rows from it.
    """

    def __init__(self, svc: TableService, name: str, vocab: int, dim: int,
                 lr: float = 0.1, seed: int = 0, geo_step: int = 8):
        self._svc = svc
        self.name, self.vocab, self.dim = name, vocab, dim
        self.lr = lr
        self.geo_step = geo_step
        # register the global table (idempotent per process)
        svc.register(name, vocab, dim, lr=1.0, seed=seed)  # lr folded here
        self._local = _rows_normal(seed, 0, vocab, dim, 0.02)
        # sparse delta accumulator keyed by touched row — a dense
        # zeros_like(local) would double the table's memory footprint
        self._delta: Dict[int, np.ndarray] = {}
        self._step = 0

    def pull(self, ids) -> np.ndarray:
        flat = np.asarray(ids).reshape(-1)
        out = self._local[flat]
        return out.reshape(tuple(np.shape(ids)) + (self.dim,))

    def push(self, ids, grads):
        """Local SGD apply + delta accumulation; geo push every
        geo_step calls."""
        flat = np.asarray(ids).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, -1)
        uniq, inv = np.unique(flat, return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, g)
        upd = self.lr * acc
        self._local[uniq] -= upd
        for row, u in zip(upd, uniq):
            key = int(u)
            d = self._delta.get(key)
            self._delta[key] = -row if d is None else d - row
        self._step += 1
        if self._step % self.geo_step == 0:
            self.geo_push()

    def geo_push(self):
        """Push accumulated deltas to the global table and refresh the
        touched rows from it (reference: Communicator geo mode)."""
        if not self._delta:
            return
        ids = np.fromiter(self._delta.keys(), np.int64)
        delta = np.stack([self._delta[int(i)] for i in ids])
        # global table applies -1.0 * delta (its lr is 1.0): send the
        # NEGATED delta as the "gradient"
        self._svc.push(self.name, ids, -delta, sync=True)
        self._delta.clear()
        self._local[ids] = self._svc.pull(self.name, ids)


class SSDTable:
    """Memory-capped shard: hot rows in RAM, full table on a disk memmap
    (reference: `ssd_sparse_table.cc` — rocksdb-backed cold storage).

    The memmap holds every row (written through on eviction); an LRU dict
    caches at most `cache_rows` rows in memory.
    """

    def __init__(self, path: str, vocab: int, dim: int,
                 cache_rows: int = 1024, lr: float = 0.1, seed: int = 0,
                 rank: int = 0, world: int = 1):
        self.vocab, self.dim, self.lr = vocab, dim, lr
        self.lo, self.hi, _ = _shard_bounds(vocab, world, rank)
        rows = self.hi - self.lo
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._mm = np.lib.format.open_memmap(
            path, mode="w+", dtype=np.float32, shape=(rows, dim))
        CHUNK = 1 << 13
        for s in range(0, rows, CHUNK):
            n = min(CHUNK, rows - s)
            self._mm[s:s + n] = _rows_normal(seed, self.lo + s, n, dim,
                                             0.02)
        self._cache: "Dict[int, np.ndarray]" = {}
        self._cap = cache_rows
        self._lock = threading.Lock()

    def _get(self, local_id: int) -> np.ndarray:
        row = self._cache.pop(local_id, None)
        if row is None:
            row = np.array(self._mm[local_id])
        self._cache[local_id] = row          # move to MRU end
        while len(self._cache) > self._cap:
            old_id, old_row = next(iter(self._cache.items()))
            self._cache.pop(old_id)
            self._mm[old_id] = old_row       # write-back on eviction
        return row

    def pull(self, ids) -> np.ndarray:
        flat = np.asarray(ids).reshape(-1)
        with self._lock:
            out = np.stack([self._get(int(i) - self.lo) for i in flat])
        return out.reshape(tuple(np.shape(ids)) + (self.dim,))

    def push(self, ids, grads):
        flat = np.asarray(ids).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, -1)
        with self._lock:
            for i, gi in zip(flat, g):
                li = int(i) - self.lo
                self._get(li)
                self._cache[li] = self._cache[li] - self.lr * gi

    def flush(self):
        with self._lock:
            for li, row in self._cache.items():
                self._mm[li] = row
            self._mm.flush()

    @property
    def cached_rows(self) -> int:
        return len(self._cache)


class GraphTable:
    """Adjacency store + neighbor sampling for GNN training (reference:
    `common_graph_table.cc` random_sample_neighbors +
    `graph_brpc_server.cc`). Edges partition by source-node owner; remote
    sampling rides the TableService KV-free RPC path via per-rank
    subtables registered under `graph:<name>`.
    """

    def __init__(self, name: str = "graph", seed: int = 0):
        self.name = name
        self._adj: Dict[int, np.ndarray] = {}
        self._rs = np.random.RandomState(seed)

    def add_edges(self, src: Sequence[int], dst: Sequence[int]):
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        order = np.argsort(src, kind="stable")
        src, dst = src[order], dst[order]
        uniq, starts = np.unique(src, return_index=True)
        bounds = list(starts) + [len(src)]
        for i, u in enumerate(uniq):
            new = dst[bounds[i]:bounds[i + 1]]
            old = self._adj.get(int(u))
            self._adj[int(u)] = new if old is None else \
                np.concatenate([old, new])

    def sample_neighbors(self, nodes, sample_size: int,
                         padding: int = -1) -> np.ndarray:
        """[n] -> [n, sample_size] neighbor ids, `padding` where the
        degree is short (dense output — XLA-ready, replacing the
        reference's variable-length LoD result)."""
        nodes = np.asarray(nodes, np.int64)
        out = np.full((len(nodes), sample_size), padding, np.int64)
        for r, u in enumerate(nodes):
            nb = self._adj.get(int(u))
            if nb is None or len(nb) == 0:
                continue
            if len(nb) <= sample_size:
                out[r, :len(nb)] = nb
            else:
                out[r] = self._rs.choice(nb, sample_size, replace=False)
        return out

    def degree(self, nodes) -> np.ndarray:
        return np.asarray([len(self._adj.get(int(u), ())) for u in
                           np.asarray(nodes).reshape(-1)], np.int64)
