"""Binary wire format for the PS TCP service — no pickle.

VERDICT r4 item 7: the reference's PS wire is a binary RPC schema
(brpc + protobuf `sendrecv.proto`, `distributed/service/
brpc_ps_server.cc:1` — never pickle). This module is the equivalent
contract for the TCP table service: a small TAGGED, LENGTH-PREFIXED
encoding covering exactly the value shapes the PS protocol uses
(ndarrays, scalars, str/bytes, lists/tuples/dicts, None). `loads` only
ever constructs these data types — unlike pickle there is no object
construction, so a malicious peer can at worst deliver wrong data, not
code execution. Connection-level auth stays the
multiprocessing.connection HMAC challenge (authkey) underneath.

Every frame leads with a one-byte PROTOCOL VERSION (WIRE_VERSION): a
mixed-version cluster (old pickle peer or a future layout change)
fails immediately with an explicit version-mismatch error instead of
opaque malformed-frame drops mid-training.

Layout per frame: 1-byte version, then one value.
Layout per value: 1-byte tag, then
  INT    int64-LE            FLOAT  float64-LE
  STR    u32 len + utf-8     BYTES  u32 len + raw
  ARR    u8 dtype-str len + dtype-str + u8 ndim + i64-LE dims + raw
         (C-order)
  LIST/TUPLE  u32 count + values
  DICT   u32 count + (key, value) pairs
Top-level messages ride Connection.send_bytes (u32-length-framed by
the transport itself).
"""
from __future__ import annotations

import struct
from typing import Any

import numpy as np

WIRE_VERSION = 1

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3
_T_FLOAT = 4
_T_STR = 5
_T_BYTES = 6
_T_ARR = 7
_T_LIST = 8
_T_TUPLE = 9
_T_DICT = 10

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")


def _pack(obj: Any, out: list) -> None:
    if obj is None:
        out.append(bytes([_T_NONE]))
    elif obj is True:
        out.append(bytes([_T_TRUE]))
    elif obj is False:
        out.append(bytes([_T_FALSE]))
    elif isinstance(obj, (int, np.integer)):
        out.append(bytes([_T_INT]) + _I64.pack(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(bytes([_T_FLOAT]) + _F64.pack(float(obj)))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(bytes([_T_STR]) + _U32.pack(len(b)) + b)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(bytes([_T_BYTES]) + _U32.pack(len(b)) + b)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:  # object dtype or structured-with-objects
            # tobytes() on an object array would ship raw POINTERS the
            # receiver cannot decode — fail here, at the sender, with
            # the clear message (dataset.py relays it for shuffles)
            raise TypeError("PS wire cannot encode object-dtype arrays")
        # ascontiguousarray promotes 0-d to (1,): reshape back so array
        # shape round-trips exactly (a 0-d loss must not grow an axis)
        a = np.ascontiguousarray(obj).reshape(obj.shape)
        ds = a.dtype.str.encode()   # e.g. b'<f4' — endian-explicit
        hdr = bytes([_T_ARR, len(ds)]) + ds + bytes([a.ndim])
        hdr += b"".join(_I64.pack(d) for d in a.shape)
        out.append(hdr)
        out.append(a.tobytes())
    elif isinstance(obj, (list, tuple)):
        tag = _T_LIST if isinstance(obj, list) else _T_TUPLE
        out.append(bytes([tag]) + _U32.pack(len(obj)))
        for v in obj:
            _pack(v, out)
    elif isinstance(obj, dict):
        out.append(bytes([_T_DICT]) + _U32.pack(len(obj)))
        for k, v in obj.items():
            _pack(k, out)
            _pack(v, out)
    else:
        # jax arrays and anything array-like with __array__ flatten to
        # ndarrays; true non-data objects are a protocol error — the
        # PS wire moves DATA, it is not a remote object system
        arr = np.asarray(obj)
        if arr.dtype == object:
            raise TypeError(f"PS wire cannot encode {type(obj).__name__}")
        _pack(arr, out)


def dumps(obj: Any) -> bytes:
    out: list = [bytes([WIRE_VERSION])]
    _pack(obj, out)
    return b"".join(out)


def _unpack(buf: memoryview, off: int):
    tag = buf[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_INT:
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == _T_FLOAT:
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag in (_T_STR, _T_BYTES):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        raw = bytes(buf[off:off + n])
        if len(raw) != n:
            raise ValueError("PS wire: truncated str/bytes")
        return (raw.decode() if tag == _T_STR else raw), off + n
    if tag == _T_ARR:
        dl = buf[off]
        off += 1
        dt = np.dtype(bytes(buf[off:off + dl]).decode())
        off += dl
        nd = buf[off]
        off += 1
        shape = tuple(_I64.unpack_from(buf, off + 8 * k)[0]
                      for k in range(nd))
        off += 8 * nd
        if any(d < 0 for d in shape):
            raise ValueError("PS wire: negative array dim")
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dt.itemsize
        if off + nbytes > len(buf):
            raise ValueError("PS wire: truncated array payload")
        a = np.frombuffer(buf, dtype=dt, count=n,
                          offset=off).reshape(shape).copy()
        return a, off + nbytes
    if tag in (_T_LIST, _T_TUPLE):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _unpack(buf, off)
            items.append(v)
        return (items if tag == _T_LIST else tuple(items)), off
    if tag == _T_DICT:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _unpack(buf, off)
            v, off = _unpack(buf, off)
            d[k] = v
        return d, off
    raise ValueError(f"PS wire: unknown tag {tag}")


def loads(data: bytes) -> Any:
    if not data:
        raise ValueError("PS wire: empty frame")
    if data[0] != WIRE_VERSION:
        # the FIRST check: a peer speaking another protocol revision
        # (or the pre-version pickle wire) must fail with an explicit,
        # actionable error, not a tag-decoding surprise further in
        raise ValueError(
            f"PS wire: protocol version mismatch (got {data[0]}, "
            f"expected {WIRE_VERSION}) — all ranks must run the same "
            f"paddle_tpu wire revision")
    try:
        obj, off = _unpack(memoryview(data), 1)
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 — uniform protocol-error type
        # header-level truncation/garbage raises IndexError/TypeError/
        # struct.error from the raw accessors; the module contract is
        # ValueError for ANY malformed input so _serve can treat it as
        # a protocol error instead of dying on a stray exception
        raise ValueError(f"PS wire: malformed message "
                         f"({type(e).__name__}: {e})") from e
    if off != len(data):
        raise ValueError("PS wire: trailing bytes")
    return obj


def send_msg(conn, obj: Any) -> None:
    conn.send_bytes(dumps(obj))


def recv_msg(conn) -> Any:
    return loads(conn.recv_bytes())
