"""Binary wire format for the PS TCP service — no pickle.

VERDICT r4 item 7: the reference's PS wire is a binary RPC schema
(brpc + protobuf `sendrecv.proto`, `distributed/service/
brpc_ps_server.cc:1` — never pickle). This module is the equivalent
contract for the TCP table service: a small TAGGED, LENGTH-PREFIXED
encoding covering exactly the value shapes the PS protocol uses
(ndarrays, scalars, str/bytes, lists/tuples/dicts, None). `loads` only
ever constructs these data types — unlike pickle there is no object
construction, so a malicious peer can at worst deliver wrong data, not
code execution. Connection-level auth stays the
multiprocessing.connection HMAC challenge (authkey) underneath.

Every frame leads with a one-byte PROTOCOL VERSION (WIRE_VERSION): a
mixed-version cluster (old pickle peer or a future layout change)
fails immediately with an explicit version-mismatch error instead of
opaque malformed-frame drops mid-training.

Layout per frame: 1-byte version, then one value.
Layout per value: 1-byte tag, then
  INT    int64-LE            FLOAT  float64-LE
  STR    u32 len + utf-8     BYTES  u32 len + raw
  ARR    u8 dtype-str len + dtype-str + u8 ndim + i64-LE dims + raw
         (C-order)
  LIST/TUPLE  u32 count + values
  DICT   u32 count + (key, value) pairs
Top-level messages ride Connection.send_bytes (u32-length-framed by
the transport itself).
"""
from __future__ import annotations

import struct
from typing import Any

import numpy as np

WIRE_VERSION = 1

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3
_T_FLOAT = 4
_T_STR = 5
_T_BYTES = 6
_T_ARR = 7
_T_LIST = 8
_T_TUPLE = 9
_T_DICT = 10

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")


def _pack(obj: Any, out: list) -> None:
    if obj is None:
        out.append(bytes([_T_NONE]))
    elif obj is True:
        out.append(bytes([_T_TRUE]))
    elif obj is False:
        out.append(bytes([_T_FALSE]))
    elif isinstance(obj, (int, np.integer)):
        out.append(bytes([_T_INT]) + _I64.pack(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(bytes([_T_FLOAT]) + _F64.pack(float(obj)))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(bytes([_T_STR]) + _U32.pack(len(b)) + b)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(bytes([_T_BYTES]) + _U32.pack(len(b)) + b)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:  # object dtype or structured-with-objects
            # tobytes() on an object array would ship raw POINTERS the
            # receiver cannot decode — fail here, at the sender, with
            # the clear message (dataset.py relays it for shuffles)
            raise TypeError("PS wire cannot encode object-dtype arrays")
        # ascontiguousarray promotes 0-d to (1,): reshape back so array
        # shape round-trips exactly (a 0-d loss must not grow an axis)
        a = np.ascontiguousarray(obj).reshape(obj.shape)
        ds = a.dtype.str.encode()   # e.g. b'<f4' — endian-explicit
        hdr = bytes([_T_ARR, len(ds)]) + ds + bytes([a.ndim])
        hdr += b"".join(_I64.pack(d) for d in a.shape)
        out.append(hdr)
        out.append(a.tobytes())
    elif isinstance(obj, (list, tuple)):
        tag = _T_LIST if isinstance(obj, list) else _T_TUPLE
        out.append(bytes([tag]) + _U32.pack(len(obj)))
        for v in obj:
            _pack(v, out)
    elif isinstance(obj, dict):
        out.append(bytes([_T_DICT]) + _U32.pack(len(obj)))
        for k, v in obj.items():
            _pack(k, out)
            _pack(v, out)
    else:
        # jax arrays and anything array-like with __array__ flatten to
        # ndarrays; true non-data objects are a protocol error — the
        # PS wire moves DATA, it is not a remote object system
        arr = np.asarray(obj)
        if arr.dtype == object:
            raise TypeError(f"PS wire cannot encode {type(obj).__name__}")
        _pack(arr, out)


def dumps(obj: Any) -> bytes:
    out: list = [bytes([WIRE_VERSION])]
    _pack(obj, out)
    return b"".join(out)


def _unpack(buf: memoryview, off: int):
    tag = buf[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_INT:
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == _T_FLOAT:
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag in (_T_STR, _T_BYTES):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        raw = bytes(buf[off:off + n])
        if len(raw) != n:
            raise ValueError("PS wire: truncated str/bytes")
        return (raw.decode() if tag == _T_STR else raw), off + n
    if tag == _T_ARR:
        dl = buf[off]
        off += 1
        dt = np.dtype(bytes(buf[off:off + dl]).decode())
        off += dl
        nd = buf[off]
        off += 1
        shape = tuple(_I64.unpack_from(buf, off + 8 * k)[0]
                      for k in range(nd))
        off += 8 * nd
        if any(d < 0 for d in shape):
            raise ValueError("PS wire: negative array dim")
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dt.itemsize
        if off + nbytes > len(buf):
            raise ValueError("PS wire: truncated array payload")
        a = np.frombuffer(buf, dtype=dt, count=n,
                          offset=off).reshape(shape).copy()
        return a, off + nbytes
    if tag in (_T_LIST, _T_TUPLE):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _unpack(buf, off)
            items.append(v)
        return (items if tag == _T_LIST else tuple(items)), off
    if tag == _T_DICT:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _unpack(buf, off)
            v, off = _unpack(buf, off)
            d[k] = v
        return d, off
    raise ValueError(f"PS wire: unknown tag {tag}")


def loads(data: bytes) -> Any:
    if not data:
        raise ValueError("PS wire: empty frame")
    if data[0] != WIRE_VERSION:
        # the FIRST check: a peer speaking another protocol revision
        # (or the pre-version pickle wire) must fail with an explicit,
        # actionable error, not a tag-decoding surprise further in
        raise ValueError(
            f"PS wire: protocol version mismatch (got {data[0]}, "
            f"expected {WIRE_VERSION}) — all ranks must run the same "
            f"paddle_tpu wire revision")
    try:
        obj, off = _unpack(memoryview(data), 1)
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 — uniform protocol-error type
        # header-level truncation/garbage raises IndexError/TypeError/
        # struct.error from the raw accessors; the module contract is
        # ValueError for ANY malformed input so _serve can treat it as
        # a protocol error instead of dying on a stray exception
        raise ValueError(f"PS wire: malformed message "
                         f"({type(e).__name__}: {e})") from e
    if off != len(data):
        raise ValueError("PS wire: trailing bytes")
    return obj


def send_msg(conn, obj: Any) -> None:
    conn.send_bytes(dumps(obj))


def recv_msg(conn) -> Any:
    return loads(conn.recv_bytes())


# ---------------------------------------------------------------------------
# Fast frames — the zero-copy hot path for the dominant pull/push RPCs.
#
# The generic tagged encoding above costs one `tobytes` copy plus one
# `b"".join` copy per array, on both sides of every RPC. At PS serving
# rates that is the wire's whole budget, so the four hot messages get
# fixed binary layouts (brpc analogue: the dedicated PsService method
# ids in sendrecv.proto, vs a generic variant encoding):
#
#   PULL_REQ  [ver][0x50][u8 tlen][table][u32 n][n x i64-LE ids]
#   PULL_REP  [ver][0x51][u32 n][u32 dim][n*dim x f32-LE rows]
#   PUSH_REQ  [ver][0x52][u8 tlen][table][u8 flags][u32 n][u32 dim]
#             [n x i64-LE ids][n*dim x f32-LE grads]   flags bit0=async
#   OK_REP    [ver][0x53]
#   ERR_REP   [ver][0x54][u32 len][utf-8 message]
#
# The reply body is never concatenated: `alloc_pull_rep` hands the
# server a preallocated frame whose body is a float32 view, the shard
# gather writes rows straight into it, and the one buffer goes to
# send_bytes. Parsers return zero-copy views over the received buffer.
# Fast tags start at 0x50, disjoint from the value tags above, so a
# frame's second byte dispatches between the two encodings; version
# mismatch fails identically to `loads`.
# ---------------------------------------------------------------------------

TAG_PULL_REQ = 0x50
TAG_PULL_REP = 0x51
TAG_PUSH_REQ = 0x52
TAG_OK = 0x53
TAG_ERR = 0x54
_FAST_MIN, _FAST_MAX = TAG_PULL_REQ, TAG_ERR

# Traced fast frames (ISSUE 10): version 2 inserts a client-generated
# [u64-LE trace id] between [ver][tag] and the v1 body; the server
# echoes it in PULL_REP/OK replies (ERR frames stay v1) and records
# its lifecycle spans under that id (csrc/ptpu_trace.{h,cc}, exposed
# over GET /tracez). Old v1 peers are untouched. C twin constants:
# kWireVersionTraced / ptpu::trace::kTraceExt in ptpu_ps_server.cc.
WIRE_VERSION_TRACED = 2
TRACE_EXT = 8

OK_FRAME = bytes([WIRE_VERSION, TAG_OK])

_U32x2 = struct.Struct("<II")
_U64 = struct.Struct("<Q")


def fast_tag(data) -> int:
    """The fast-frame tag of a received buffer, or -1 for generic
    frames. Raises the same version-mismatch error as `loads`."""
    if len(data) < 2:
        return -1
    if data[0] not in (WIRE_VERSION, WIRE_VERSION_TRACED):
        raise ValueError(
            f"PS wire: protocol version mismatch (got {data[0]}, "
            f"expected {WIRE_VERSION}) — all ranks must run the same "
            f"paddle_tpu wire revision")
    tag = data[1]
    return tag if _FAST_MIN <= tag <= _FAST_MAX else -1


def trace_id_of(data) -> int:
    """Trace id of a traced (v2) fast frame, 0 for v1 frames."""
    if len(data) >= 2 + TRACE_EXT and data[0] == WIRE_VERSION_TRACED:
        return _U64.unpack_from(data, 2)[0]
    return 0


def _trace_ext_of(data) -> int:
    """Byte shift of every v1 body offset for this frame (0 or 8)."""
    return TRACE_EXT if data[0] == WIRE_VERSION_TRACED else 0


def _table_header(tag: int, table: str, trace_id: int = 0) -> bytes:
    tb = table.encode()
    if len(tb) > 255:
        raise ValueError("PS wire: table name too long for fast frame")
    if trace_id:
        return (bytes([WIRE_VERSION_TRACED, tag]) +
                _U64.pack(trace_id) + bytes([len(tb)]) + tb)
    return bytes([WIRE_VERSION, tag, len(tb)]) + tb


def build_pull_req(table: str, ids: np.ndarray,
                   trace_id: int = 0) -> bytes:
    """trace_id nonzero builds a traced (v2) frame: the server records
    this request's lifecycle spans under that id and echoes it in the
    reply (old servers reject v2 — only send when tracing is on)."""
    ids = np.ascontiguousarray(ids, np.dtype("<i8"))
    return (_table_header(TAG_PULL_REQ, table, trace_id) +
            _U32.pack(ids.size) + ids.tobytes())


def parse_pull_req(data):
    """-> (table, ids) — ids a zero-copy int64 view of `data`."""
    buf = memoryview(data)
    ext = _trace_ext_of(buf)
    tlen = buf[2 + ext]
    off = 3 + ext + tlen
    table = bytes(buf[3 + ext:off]).decode()
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    if len(buf) != off + 8 * n:
        raise ValueError("PS wire: truncated pull request")
    return table, np.frombuffer(buf, np.dtype("<i8"), count=n, offset=off)


_PULL_REP_HDR = 2 + _U32x2.size


def alloc_pull_rep(n: int, dim: int):
    """-> (frame, body): a preallocated PULL_REP frame and the (n, dim)
    float32 view of its body for the gather to fill."""
    frame = bytearray(_PULL_REP_HDR + 4 * n * dim)
    frame[0], frame[1] = WIRE_VERSION, TAG_PULL_REP
    _U32x2.pack_into(frame, 2, n, dim)
    body = np.frombuffer(frame, np.dtype("<f4"),
                         offset=_PULL_REP_HDR).reshape(n, dim)
    return frame, body


def parse_pull_rep(data):
    """-> (n, dim) float32 zero-copy view of the reply body. Traced
    (v2) replies carry the echoed trace id — read it with
    `trace_id_of`; the body sits TRACE_EXT bytes later."""
    buf = memoryview(data)
    ext = _trace_ext_of(buf)
    n, dim = _U32x2.unpack_from(buf, 2 + ext)
    if len(buf) != ext + _PULL_REP_HDR + 4 * n * dim:
        raise ValueError("PS wire: truncated pull reply")
    return np.frombuffer(buf, np.dtype("<f4"), count=n * dim,
                         offset=ext + _PULL_REP_HDR).reshape(n, dim)


def build_push_req(table: str, ids: np.ndarray, grads: np.ndarray,
                   is_async: bool = False,
                   trace_id: int = 0) -> bytearray:
    ids = np.ascontiguousarray(ids, np.dtype("<i8"))
    grads = np.ascontiguousarray(grads, np.dtype("<f4"))
    n = ids.size
    dim = grads.size // max(n, 1)
    if grads.size != n * dim:
        raise ValueError("PS wire: grads size not a multiple of ids")
    hdr = (_table_header(TAG_PUSH_REQ, table, trace_id) +
           bytes([1 if is_async else 0]) + _U32x2.pack(n, dim))
    frame = bytearray(len(hdr) + 8 * n + 4 * n * dim)
    frame[:len(hdr)] = hdr
    frame[len(hdr):len(hdr) + 8 * n] = ids.tobytes()
    frame[len(hdr) + 8 * n:] = grads.tobytes()
    return frame


def parse_push_req(data):
    """-> (table, ids, grads, is_async) — ids/grads zero-copy views."""
    buf = memoryview(data)
    ext = _trace_ext_of(buf)
    tlen = buf[2 + ext]
    off = 3 + ext + tlen
    table = bytes(buf[3 + ext:off]).decode()
    is_async = bool(buf[off])
    n, dim = _U32x2.unpack_from(buf, off + 1)
    off += 1 + _U32x2.size
    if len(buf) != off + 8 * n + 4 * n * dim:
        raise ValueError("PS wire: truncated push request")
    ids = np.frombuffer(buf, np.dtype("<i8"), count=n, offset=off)
    grads = np.frombuffer(buf, np.dtype("<f4"), count=n * dim,
                          offset=off + 8 * n).reshape(n, dim)
    return table, ids, grads, is_async


def build_err(msg: str) -> bytes:
    b = msg.encode()
    return bytes([WIRE_VERSION, TAG_ERR]) + _U32.pack(len(b)) + b


def parse_err(data) -> str:
    buf = memoryview(data)
    (n,) = _U32.unpack_from(buf, 2)
    raw = bytes(buf[6:6 + n])
    if len(raw) != n:
        raise ValueError("PS wire: truncated error frame")
    return raw.decode()


def check_reply(data, expect_tag: int):
    """Validate a fast reply: raises RuntimeError carrying the server's
    message for ERR frames, ValueError for the wrong frame kind."""
    tag = fast_tag(data)
    if tag == TAG_ERR:
        raise RuntimeError(f"PS remote error: {parse_err(data)}")
    if tag != expect_tag:
        raise ValueError(f"PS wire: expected fast tag {expect_tag:#x}, "
                         f"got {tag:#x}")
    return data
