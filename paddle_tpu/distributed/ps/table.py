"""Sharded embedding table + TCP table service.

See package docstring for the reference mapping. Wire protocol:
length-prefixed BINARY (op, table, payload) messages (`wire.py` tagged
encoding — ndarrays ship as dtype+dims+raw bytes, never pickle) over
`multiprocessing.connection` transports whose connect handshake is
HMAC-authenticated by authkey — the brpc `sendrecv.proto` equivalent
(reference: `distributed/service/brpc_ps_server.cc:1`).
"""
from __future__ import annotations

import os
import queue
import threading
from multiprocessing.connection import Client, Listener
from typing import Dict, Optional

import numpy as np

from .wire import recv_msg, send_msg

_AUTHKEY_BASE = b"ptpu-ps-"
_PORT_OFFSET = 200  # launcher endpoints use MASTER_PORT+1+rank; stay clear


def _authkey() -> bytes:
    return _AUTHKEY_BASE + os.environ.get("MASTER_PORT", "0").encode()


def _shard_bounds(vocab: int, world: int, rank: int):
    """Block partition (reference: `ps_dispatcher.py` HashName/RoundRobin →
    block here so each shard's rows are one contiguous id range and the
    seeded init can position a counter-based stream in O(1))."""
    block = -(-vocab // world)          # ceil
    lo = min(rank * block, vocab)
    hi = min(lo + block, vocab)
    return lo, hi, block


def _rows_normal(seed: int, lo: int, rows: int, dim: int,
                 std: float) -> np.ndarray:
    """Normal(0, std) values for global rows [lo, lo+rows) of the table.

    Counter-based (Philox) stream: row g's values always come from stream
    positions [g*dim, (g+1)*dim) — identical for every world size — and
    generating a shard touches ONLY its own positions (per-rank cost
    O(vocab/world), killing the r2 O(full-table) bring-up). Normals come
    from Box–Muller over two fixed-consumption uniform draws per value
    (ziggurat consumes data-dependently and would break row alignment).
    """
    out = np.empty((rows, dim), np.float32)
    CHUNK = 1 << 13   # bounds Box–Muller temps to ~CHUNK*dim*8B each
    for start in range(0, rows, CHUNK):
        n = min(CHUNK, rows - start)
        bg = np.random.Philox(key=seed)
        # numpy's Philox is 4x64: one counter block = 4 uint64 draws.
        # Value v consumes u64s [2v, 2v+1]; jump to the block containing
        # this chunk's first u64 and discard the in-block remainder.
        off_u64 = 2 * (lo + start) * dim
        bg.advance(off_u64 // 4)
        skip = off_u64 % 4
        raw = bg.random_raw(skip + 2 * n * dim)[skip:]
        u = (raw >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
        u1 = np.maximum(u[0::2], 1e-12)
        u2 = u[1::2]
        z = np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)
        out[start:start + n] = (std * z).astype(np.float32).reshape(n, dim)
    return out


class _Shard:
    """This process's rows of one table: the contiguous id block
    [lo, hi) (reference placement: `ps_dispatcher.py`)."""

    def __init__(self, name: str, vocab: int, dim: int, rank: int,
                 world: int, lr: float, seed: int):
        self.name, self.vocab, self.dim = name, vocab, dim
        self.rank, self.world, self.lr = rank, world, lr
        self.lo, self.hi, self.block = _shard_bounds(vocab, world, rank)
        self.data = _rows_normal(seed, self.lo, self.hi - self.lo, dim,
                                 0.02)
        self._lock = threading.Lock()

    def pull(self, ids: np.ndarray) -> np.ndarray:
        with self._lock:
            return self.data[ids - self.lo]

    def push(self, ids: np.ndarray, grads: np.ndarray):
        """Server-side SGD (reference: optimizer runs in the table,
        `common_sparse_table.cc`); duplicate ids accumulate first."""
        with self._lock:
            # scatter-add duplicates, then one update per unique row
            uniq, inv = np.unique(ids - self.lo, return_inverse=True)
            acc = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(acc, inv, grads)
            self.data[uniq] -= self.lr * acc


class TableService:
    """Per-process PS node: hosts local shards, serves peers, and
    provides the client-side pull/push over all shards."""

    def __init__(self, rank: int, world: int, port_base: int):
        self.rank, self.world = rank, world
        self._ports = [port_base + _PORT_OFFSET + r for r in range(world)]
        # multi-host: peer hosts come from the launcher endpoint list
        # (PADDLE_TRAINER_ENDPOINTS "host:port,..."); single host (or no
        # launcher) stays loopback. The listener binds all interfaces so
        # remote peers can reach it.
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        hosts = [e.split(":")[0] for e in eps.split(",") if e]
        self._hosts = hosts if len(hosts) == world else \
            ["127.0.0.1"] * world
        self._bind_host = "" if len(set(self._hosts)) > 1 else "127.0.0.1"
        self._shards: Dict[str, _Shard] = {}
        self._conns: Dict[int, object] = {}
        self._conn_lock = threading.Lock()
        self._rpc_locks: Dict[int, threading.Lock] = {}
        self._stop = False
        self._async_q: "queue.Queue" = queue.Queue()
        self._listener = None
        self._threads = []
        # generic KV (rank 0 is the store) — backs elastic membership and
        # cross-rank barriers (reference: gloo HTTP-KV / etcd rendezvous)
        self._kv: Dict[str, bytes] = {}
        self._kv_lock = threading.Lock()
        # global-shuffle receive buffer (reference: DatasetImpl
        # GlobalShuffle exchanges records over brpc, `data_set.h:101`)
        self._shuffle_buf: list = []
        self._shuffle_lock = threading.Lock()
        # heter split-training function registry (reference:
        # `heter_server.cc` RegisterServiceHandler)
        self._heter_fns: Dict[str, object] = {}
        if world > 1:
            self._listener = Listener((self._bind_host, self._ports[rank]),
                                      authkey=_authkey())
            t = threading.Thread(target=self._accept_loop, daemon=True)
            t.start()
            self._threads.append(t)
        ta = threading.Thread(target=self._async_push_loop, daemon=True)
        ta.start()
        self._threads.append(ta)

    # ---- server side ----------------------------------------------------

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while not self._stop:
                try:
                    op, table, payload = recv_msg(conn)
                except (EOFError, OSError):
                    return
                except ValueError as e:
                    # malformed frame (wire.loads protocol error): drop
                    # THIS connection cleanly; the serve thread and the
                    # service survive a garbled/malicious peer
                    import sys
                    print(f"ps: dropping connection on malformed "
                          f"frame: {e}", file=sys.stderr)
                    return
                if op == "pull":
                    send_msg(conn, self._shards[table].pull(payload))
                elif op == "push":
                    ids, grads = payload
                    self._shards[table].push(ids, grads)
                    send_msg(conn, b"ok")
                elif op == "barrier_probe":
                    send_msg(conn, b"ok")
                elif op == "kv_put":
                    with self._kv_lock:
                        self._kv[table] = payload
                    send_msg(conn, b"ok")
                elif op == "kv_get":
                    with self._kv_lock:
                        send_msg(conn, self._kv.get(table))
                elif op == "kv_prefix":
                    with self._kv_lock:
                        send_msg(conn, {k: v for k, v in self._kv.items()
                                      if k.startswith(table)})
                elif op == "kv_del":
                    with self._kv_lock:
                        self._kv.pop(table, None)
                    send_msg(conn, b"ok")
                elif op == "shuffle_recv":
                    with self._shuffle_lock:
                        self._shuffle_buf.extend(payload)
                    send_msg(conn, b"ok")
                elif op == "heter_call":
                    # heterogeneous split training (reference:
                    # heter_client/server.cc): run a registered function
                    # (e.g. the jitted dense step on the device owner)
                    # on behalf of a CPU-side worker. Failures travel as
                    # a STRUCTURED ('err', kind, msg) tuple — the client
                    # dispatches on `kind`, never on message prefixes (a
                    # registered fn whose error text happens to start
                    # with "KeyError: heter fn" must stay a plain
                    # remote-failure, not an unregistered-fn KeyError)
                    fn = self._heter_fns.get(table)
                    if fn is None:
                        send_msg(conn, ("err", "unregistered",
                                        f"heter fn {table!r} not "
                                        f"registered on rank "
                                        f"{self.rank}"))
                    else:
                        try:
                            send_msg(conn, ("ok", fn(*payload)))
                        except Exception as e:  # noqa: BLE001
                            send_msg(conn, ("err", "exception", repr(e)))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ---- client side ----------------------------------------------------

    def _conn(self, peer: int, timeout_s: float = 60.0):
        with self._conn_lock:
            c = self._conns.get(peer)
            if c is None:
                # peers come up at their own pace (jax init can take
                # seconds) — retry with backoff like the reference's brpc
                # channel connect (`brpc_ps_client.cc` connect retries)
                import time
                deadline = time.time() + timeout_s
                delay = 0.05
                while True:
                    try:
                        c = Client((self._hosts[peer], self._ports[peer]),
                                   authkey=_authkey())
                        break
                    except (ConnectionRefusedError, OSError):
                        if time.time() > deadline:
                            raise
                        time.sleep(delay)
                        delay = min(delay * 2, 1.0)
                self._conns[peer] = c
                self._rpc_locks[peer] = threading.Lock()
            return c

    def _rpc(self, peer: int, op: str, table: str, payload):
        c = self._conn(peer)
        # one in-flight request per connection: the communicator thread's
        # async pushes must not interleave send/recv with the caller's
        # kv/barrier/pull RPCs (crossed replies otherwise)
        with self._rpc_locks[peer]:
            send_msg(c, (op, table, payload))
            return recv_msg(c)

    def register(self, name: str, vocab: int, dim: int, lr: float = 0.1,
                 seed: int = 0) -> "ShardedEmbeddingTable":
        self._shards[name] = _Shard(name, vocab, dim, self.rank,
                                    self.world, lr, seed)
        return ShardedEmbeddingTable(self, name, vocab, dim)

    def _owner(self, table: str, flat: np.ndarray) -> np.ndarray:
        block = self._shards[table].block
        return np.minimum(flat // block, self.world - 1)

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        """Gather rows for arbitrary global ids (reference:
        `brpc_ps_client` PullSparse)."""
        flat = np.asarray(ids).reshape(-1)
        dim = self._shards[table].dim
        owner = self._owner(table, flat)
        out = np.empty((flat.size, dim), np.float32)
        for peer in range(self.world):
            m = owner == peer
            if not m.any():
                continue
            sub = flat[m]
            rows = (self._shards[table].pull(sub) if peer == self.rank
                    else self._rpc(peer, "pull", table, sub))
            out[m] = rows
        return out.reshape(tuple(np.shape(ids)) + (dim,))

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             sync: bool = True):
        """Scatter row-grads to owners. sync=False queues the send on the
        communicator thread (reference: async `Communicator` batching,
        `service/communicator.cc`)."""
        flat = np.asarray(ids).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, -1)
        if not sync:
            self._async_q.put((table, flat, g))
            return
        self._push_now(table, flat, g)

    def _push_now(self, table, flat, g):
        owner = self._owner(table, flat)
        for peer in range(self.world):
            m = owner == peer
            if not m.any():
                continue
            if peer == self.rank:
                self._shards[table].push(flat[m], g[m])
            else:
                self._rpc(peer, "push", table, (flat[m], g[m]))

    def _async_push_loop(self):
        """Communicator thread: drains queued pushes and COALESCES
        same-table grads into one RPC per peer per drain (reference:
        async `Communicator` batching by send_queue,
        `service/communicator.cc` — merge then send)."""
        while True:
            item = self._async_q.get()
            if item is None:
                self._async_q.task_done()
                return
            batch = [item]
            stop = False
            try:
                while True:
                    nxt = self._async_q.get_nowait()
                    if nxt is None:
                        stop = True
                        self._async_q.task_done()
                        break
                    batch.append(nxt)
            except queue.Empty:
                pass
            try:
                self._drain(batch)
            except Exception:   # peer gone mid-push: drop the batch —
                pass            # task_done below keeps flush() unblocked
            finally:
                for _ in batch:
                    self._async_q.task_done()
            if stop:
                return

    def _drain(self, batch):
        by_table: Dict[str, list] = {}
        for table, flat, g in batch:
            by_table.setdefault(table, []).append((flat, g))
        for table, items in by_table.items():
            flat = np.concatenate([f for f, _ in items])
            g = np.concatenate([x for _, x in items])
            self._push_now(table, flat, g)

    def flush(self):
        """Drain queued async pushes (reference: Communicator barrier)."""
        self._async_q.join()

    # ---- heterogeneous split training (reference: N29
    # `heter_client.cc`/`heter_server.cc`, `heterxpu_trainer.cc`:
    # CPU-side workers drive sparse/PS work and RPC the heavy dense
    # compute to the accelerator owner) --------------------------------

    def register_heter_fn(self, name: str, fn):
        """Expose `fn(*numpy_args) -> pytree` to heter_call RPCs (run on
        THIS process — typically the rank that owns the TPU)."""
        self._heter_fns[name] = fn

    def heter_call(self, peer: int, name: str, *args):
        """Invoke a peer's registered heter function and return its
        result (reference: HeterClient::SendAndRecvAsync)."""
        if peer == self.rank:
            return self._heter_fns[name](*args)
        res = self._rpc(peer, "heter_call", name, args)
        if res[0] != "ok":
            # structured status: ('err', kind, msg). Dispatch on the
            # explicit kind — the pre-r6 contract matched the string
            # prefix "KeyError: heter fn", which misclassified any
            # registered fn failing with that exact message text
            _, kind, msg = res
            if kind == "unregistered":
                raise KeyError(msg)
            raise RuntimeError(f"heter_call {name!r} on rank {peer} "
                               f"failed: {msg}")
        return res[1]

    # ---- KV store (rank 0 hosts; reference: gloo HTTP-KV / etcd) --------

    def kv_put(self, key: str, value: bytes):
        if self.rank == 0:
            with self._kv_lock:
                self._kv[key] = value
        else:
            self._rpc(0, "kv_put", key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        if self.rank == 0:
            with self._kv_lock:
                return self._kv.get(key)
        return self._rpc(0, "kv_get", key, None)

    def kv_prefix(self, prefix: str) -> Dict[str, bytes]:
        if self.rank == 0:
            with self._kv_lock:
                return {k: v for k, v in self._kv.items()
                        if k.startswith(prefix)}
        return self._rpc(0, "kv_prefix", prefix, None)

    def kv_del(self, key: str):
        if self.rank == 0:
            with self._kv_lock:
                self._kv.pop(key, None)
        else:
            self._rpc(0, "kv_del", key, None)

    def barrier(self, name: str, timeout_s: float = 120.0):
        """KV-backed barrier (reference: `barrier_table.cc`). Each use of
        a name gets a fresh sequence number (all ranks must call barriers
        in the same order) so repeated barriers don't see stale keys."""
        import time
        if not hasattr(self, "_barrier_seq"):
            self._barrier_seq = {}
        seq = self._barrier_seq.get(name, 0)
        self._barrier_seq[name] = seq + 1
        full = f"__barrier__/{name}#{seq}/"
        self.kv_put(f"{full}{self.rank}", b"1")
        deadline = time.time() + timeout_s
        while True:
            n = len(self.kv_prefix(full))
            if n >= self.world:
                return
            if time.time() > deadline:
                raise TimeoutError(f"barrier {name!r}: {n}/{self.world}")
            time.sleep(0.01)

    # ---- global shuffle exchange (reference: DatasetImpl::GlobalShuffle,
    # `data_set.h:101` — records repartition over the PS RPC channel) ----

    def exchange_records(self, per_target: Dict[int, list],
                         tag: str) -> list:
        """Send each target rank its records; barrier; return everything
        this rank received (plus its own share)."""
        with self._shuffle_lock:
            self._shuffle_buf.extend(per_target.get(self.rank, []))
        for peer, recs in per_target.items():
            if peer != self.rank and recs:
                self._rpc(peer, "shuffle_recv", "", recs)
        self.barrier(f"shuffle/{tag}")
        with self._shuffle_lock:
            out, self._shuffle_buf = self._shuffle_buf, []
        # exit barrier: a fast peer must not start the NEXT exchange and
        # deposit records before this rank's pop above
        self.barrier(f"shuffle-exit/{tag}")
        return out

    def finalize(self, timeout_s: float = 60.0):
        """Coordinated shutdown: non-zero ranks announce 'bye' (their
        LAST rpc) before closing; rank 0 waits for every bye so no
        peer's final poll hits a closed listener."""
        import time
        self.flush()
        if self.world > 1:
            if self.rank != 0:
                self.kv_put(f"__bye__/{self.rank}", b"1")
            else:
                deadline = time.time() + timeout_s
                while len(self.kv_prefix("__bye__/")) < self.world - 1:
                    if time.time() > deadline:
                        break
                    time.sleep(0.01)
        self.shutdown()

    def shutdown(self):
        self._stop = True
        self._async_q.put(None)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()


class ShardedEmbeddingTable:
    """User handle: pull rows before the compiled dense step, push row
    grads after it (DownpourWorker dataflow, `device_worker.h:244`)."""

    def __init__(self, service: TableService, name: str, vocab: int,
                 dim: int):
        self._svc = service
        self.name, self.vocab, self.dim = name, vocab, dim

    def pull(self, ids) -> np.ndarray:
        return self._svc.pull(self.name, np.asarray(ids))

    def push(self, ids, grads, sync: bool = True):
        self._svc.push(self.name, np.asarray(ids), np.asarray(grads),
                       sync=sync)

    def flush(self):
        self._svc.flush()


_SERVICE: Optional[TableService] = None


def init_table_service() -> TableService:
    """Build the per-process PS node from the launcher env contract
    (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / MASTER_PORT — the same
    vars `the_one_ps.py:434 _init_server` reads)."""
    global _SERVICE
    if _SERVICE is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        port = int(os.environ.get("MASTER_PORT", "8476"))
        _SERVICE = TableService(rank, world, port)
    return _SERVICE


def shutdown_table_service():
    global _SERVICE
    if _SERVICE is not None:
        _SERVICE.finalize()
        _SERVICE = None
