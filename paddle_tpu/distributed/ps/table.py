"""Sharded embedding table + TCP table service.

See package docstring for the reference mapping. Wire protocol:
length-prefixed BINARY (op, table, payload) messages (`wire.py` tagged
encoding — ndarrays ship as dtype+dims+raw bytes, never pickle) over
`multiprocessing.connection` transports whose connect handshake is
HMAC-authenticated by authkey — the brpc `sendrecv.proto` equivalent
(reference: `distributed/service/brpc_ps_server.cc:1`).

Hot-path architecture (reference: the C++ brpc PS service,
`brpc_ps_server.cc` + `table/memory_sparse_table.cc`):

* row storage + the server-side optimizer live in the C runtime
  (`csrc/ptpu_ps_table.cc` via `core.native.NativePsTable`) when the
  native library is present — the numpy `_Shard` arrays remain the
  byte-parity fallback (``PTPU_PS_NATIVE=0`` forces it);
* each accepted connection is served from its own thread, so one slow
  client never serializes the service;
* pull/push ride the fixed-layout fast frames in `wire.py` — the
  server gathers rows straight into the preallocated reply frame;
* async pushes coalesce SERVER-side per table (flags bit0): the server
  acks immediately, an applier thread merges queued (ids, grads) into
  one scatter-update, and `push_drain` barriers the queue for flush();
* clients pipeline pulls (`pull_many` / `Channel`) with a bounded
  in-flight depth instead of paying a full round trip per request.
"""
from __future__ import annotations

import collections
import os
import queue
import threading
from multiprocessing.connection import Client, Listener
from typing import Dict, List, Optional

import numpy as np

from ...profiler import stats as pstats
from . import wire
from .wire import recv_msg, send_msg

_AUTHKEY_BASE = b"ptpu-ps-"
_PORT_OFFSET = 200  # launcher endpoints use MASTER_PORT+1+rank; stay clear


def _authkey() -> bytes:
    return _AUTHKEY_BASE + os.environ.get("MASTER_PORT", "0").encode()


class _DataConn:
    """Client side of the C data-plane socket (`csrc/ptpu_ps_server.cc`
    via `core.native.PsDataServer`): u32-LE length-prefixed wire.py
    fast frames over a TCP_NODELAY stream, opened with the HMAC-SHA256
    nonce handshake. API-compatible with the send_bytes/recv_bytes
    subset of multiprocessing Connection the fast paths use —
    `recv_bytes` returns a zero-copy view of a reused buffer, valid
    until the NEXT recv on this connection."""

    # Bounded connect retry: during server start (port advertised but
    # the listener not yet up) or drain (accept closed, RST/EOF before
    # the nonce) a dial sees transient ECONNREFUSED/ECONNRESET — retry
    # with backoff inside this budget instead of making every caller
    # sleep-and-hope. A REJECTED handshake (wrong key) never retries.
    CONNECT_RETRY_S = 5.0

    def __init__(self, host: str, port: int, authkey: bytes):
        import hmac
        import socket
        import struct
        import time
        self._struct = struct
        deadline = time.monotonic() + self.CONNECT_RETRY_S
        delay = 0.02
        while True:
            s = None
            try:
                s = socket.create_connection((host, port), timeout=60)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # match the server's buffer: pipelined replies keep MBs
                # in flight per connection
                s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 << 20)
                self._s = s
                nonce = self._recv_exact(bytearray(16))
                break
            except (ConnectionError, BrokenPipeError, EOFError) as e:
                if s is not None:
                    s.close()
                if time.monotonic() + delay > deadline:
                    raise ConnectionError(
                        f"PS data plane at {host}:{port} not reachable "
                        f"within {self.CONNECT_RETRY_S:.0f}s "
                        f"({type(e).__name__}: {e}) — server down or "
                        f"still starting") from e
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
        mac = hmac.new(authkey, bytes(nonce), "sha256").digest()
        s.sendall(struct.pack("<I", 32) + mac)
        ok = self._recv_exact(bytearray(1))
        if bytes(ok) != b"\x01":
            raise ConnectionError("PS data-plane handshake rejected")
        self._buf = bytearray(1 << 16)
        self._hdr = bytearray(4)

    def _recv_exact(self, buf: bytearray):
        view = memoryview(buf)
        while view:
            n = self._s.recv_into(view)
            if n == 0:
                raise EOFError("PS data-plane connection closed")
            view = view[n:]
        return buf

    def send_bytes(self, payload) -> None:
        hdr = self._struct.pack("<I", len(payload))
        # scatter-gather: header + body in one syscall, no concat copy
        sent = self._s.sendmsg((hdr, payload))
        if sent < 4:
            self._s.sendall(hdr[sent:])
            sent = 4
        if sent - 4 < len(payload):
            self._s.sendall(memoryview(payload)[sent - 4:])

    def recv_bytes(self):
        self._recv_exact(self._hdr)
        (n,) = self._struct.unpack("<I", self._hdr)
        if n > len(self._buf):
            self._buf = bytearray(n)   # old views keep the old buffer
        view = memoryview(self._buf)[:n]
        while view:
            got = self._s.recv_into(view)
            if got == 0:
                raise EOFError("PS data-plane connection closed")
            view = view[got:]
        return memoryview(self._buf)[:n]

    def recv_pull_into(self, out: np.ndarray) -> None:
        """Receive a PULL_REP with the body landing DIRECTLY in the
        C-contiguous float32 array `out` (n, dim): the kernel's
        copy-out is the only client-side move of row data. Raises
        RuntimeError for ERR replies, ValueError on shape mismatch."""
        self.recv_pull_into_seq([out])

    def recv_pull_into_seq(self, outs) -> None:
        """Receive ONE merged PULL_REP whose body is the concatenated
        rows of several logical pulls (the vectorized batch RPC reply),
        de-multiplexing the stream straight into each destination
        array — no combined staging buffer exists on either side."""
        self._recv_exact(self._hdr)
        (n,) = self._struct.unpack("<I", self._hdr)
        head = self._recv_exact(bytearray(2))
        if head[0] != wire.WIRE_VERSION:
            raise ValueError("PS wire: protocol version mismatch on "
                             "data plane")
        tag = head[1]
        if tag == wire.TAG_ERR:
            rest = self._recv_exact(bytearray(n - 2))
            raise RuntimeError("PS remote error: " +
                               bytes(rest[4:]).decode())
        if tag != wire.TAG_PULL_REP:
            self._recv_exact(bytearray(n - 2))
            raise ValueError(f"PS wire: expected PULL_REP, got tag "
                             f"{tag:#x}")
        dims = self._recv_exact(bytearray(8))
        cnt, dim = self._struct.unpack("<II", dims)
        body = n - 10
        want = sum(o.nbytes for o in outs)
        if body != want or cnt * dim * 4 != body:
            self._recv_exact(bytearray(body))
            raise ValueError(f"PS wire: pull reply {cnt}x{dim} does "
                             f"not match {len(outs)} merged outputs")
        for out in outs:
            view = memoryview(out).cast("B")
            while view:
                got = self._s.recv_into(view)
                if got == 0:
                    raise EOFError("PS data-plane connection closed")
                view = view[got:]

    def close(self):
        try:
            self._s.close()
        except OSError:
            pass


def _shard_bounds(vocab: int, world: int, rank: int):
    """Block partition (reference: `ps_dispatcher.py` HashName/RoundRobin →
    block here so each shard's rows are one contiguous id range and the
    seeded init can position a counter-based stream in O(1))."""
    block = -(-vocab // world)          # ceil
    lo = min(rank * block, vocab)
    hi = min(lo + block, vocab)
    return lo, hi, block


def _rows_normal(seed: int, lo: int, rows: int, dim: int,
                 std: float) -> np.ndarray:
    """Normal(0, std) values for global rows [lo, lo+rows) of the table.

    Counter-based (Philox) stream: row g's values always come from stream
    positions [g*dim, (g+1)*dim) — identical for every world size — and
    generating a shard touches ONLY its own positions (per-rank cost
    O(vocab/world), killing the r2 O(full-table) bring-up). Normals come
    from Box–Muller over two fixed-consumption uniform draws per value
    (ziggurat consumes data-dependently and would break row alignment).
    """
    out = np.empty((rows, dim), np.float32)
    CHUNK = 1 << 13   # bounds Box–Muller temps to ~CHUNK*dim*8B each
    for start in range(0, rows, CHUNK):
        n = min(CHUNK, rows - start)
        bg = np.random.Philox(key=seed)
        # numpy's Philox is 4x64: one counter block = 4 uint64 draws.
        # Value v consumes u64s [2v, 2v+1]; jump to the block containing
        # this chunk's first u64 and discard the in-block remainder.
        off_u64 = 2 * (lo + start) * dim
        bg.advance(off_u64 // 4)
        skip = off_u64 % 4
        raw = bg.random_raw(skip + 2 * n * dim)[skip:]
        u = (raw >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
        u1 = np.maximum(u[0::2], 1e-12)
        u2 = u[1::2]
        z = np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)
        out[start:start + n] = (std * z).astype(np.float32).reshape(n, dim)
    return out


_OPTIMIZERS = ("sgd", "adagrad", "adam")


def _native_wanted() -> bool:
    return os.environ.get("PTPU_PS_NATIVE", "1") != "0"


class _Shard:
    """This process's rows of one table: the contiguous id block
    [lo, hi) (reference placement: `ps_dispatcher.py`).

    Storage backend: `NativePsTable` (C-hosted rows + optimizer slots,
    its own reader/writer lock) when available; numpy arrays with the
    same update formulas otherwise. `self.data` is always a (rows, dim)
    float32 view of the live weights — for the native backend it views
    the C arena directly, so seeded init and parity inspection need no
    copies.
    """

    def __init__(self, name: str, vocab: int, dim: int, rank: int,
                 world: int, lr: float, seed: int,
                 optimizer: str = "sgd", beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8):
        if optimizer not in _OPTIMIZERS:
            raise ValueError(f"unknown PS optimizer {optimizer!r}; "
                             f"expected one of {_OPTIMIZERS}")
        self.name, self.vocab, self.dim = name, vocab, dim
        self.rank, self.world, self.lr = rank, world, lr
        self.optimizer = optimizer
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.lo, self.hi, self.block = _shard_bounds(vocab, world, rank)
        rows = self.hi - self.lo
        init = _rows_normal(seed, self.lo, rows, dim, 0.02)
        self._native = None
        if rows > 0 and _native_wanted():
            from ...core import native
            if native.ps_table_available():
                self._native = native.NativePsTable(
                    rows, dim, optimizer, lr, beta1, beta2, eps)
                self._native.data[:] = init
        if self._native is not None:
            self.data = self._native.data
        else:
            self.data = init
            if optimizer != "sgd":
                self._g2 = np.zeros((rows, dim), np.float32)
            if optimizer == "adam":
                self._m = self._g2   # slot0 doubles as adam m
                self._v = np.zeros((rows, dim), np.float32)
                self._t = np.zeros(rows, np.int64)
        self._lock = threading.Lock()
        # storage-level counters for the NUMPY backend only — the
        # native table counts inside C (same names), so stats() is one
        # contract whichever backend serves (csrc/ptpu_ps_table.cc)
        self._stats = pstats.Registry()

    @property
    def native(self) -> bool:
        return self._native is not None

    _STAT_NAMES = ("pull_ops", "pull_rows", "push_ops", "push_rows",
                   "push_coalesced_rows")

    def stats(self) -> dict:
        """Storage-level counters with the SAME names whichever backend
        holds the rows: the native table renders them from C
        (`ptpu_ps_table_stats_json`), the numpy fallback from its own
        registry — native-vs-fallback snapshots are comparable."""
        if self._native is not None:
            snap = self._native.stats() or {}
        else:
            snap = self._stats.snapshot()
        out = {"backend": "native" if self._native is not None
               else "numpy"}
        for k in self._STAT_NAMES:
            out[k] = int(snap.get(k, 0))
        return out

    def stats_reset(self) -> None:
        if self._native is not None:
            self._native.stats_reset()
        else:
            self._stats.reset()

    def _local(self, ids: np.ndarray) -> np.ndarray:
        local = np.asarray(ids, np.int64) - self.lo
        if self._native is None and local.size and (
                local.min() < 0 or local.max() >= self.hi - self.lo):
            # the native path bounds-checks in C; mirror it here so a
            # garbled/malicious id can't wrap around into another row
            raise ValueError(f"table {self.name!r}: id out of shard "
                             f"range [{self.lo}, {self.hi})")
        return local

    def pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((np.asarray(ids).size, self.dim), np.float32)
        self.pull_into(ids, out)
        return out

    def pull_into(self, ids: np.ndarray, out: np.ndarray) -> None:
        """Gather rows for global `ids` directly into `out` (n, dim) —
        the serve loop hands in the reply frame's body view, making the
        gather itself the serialization."""
        local = self._local(ids)
        if self._native is not None:
            self._native.pull_into(local, out)
            return
        with self._lock:
            np.take(self.data, local, axis=0, out=out)
        self._stats.counter("pull_ops").add(1)
        self._stats.counter("pull_rows").add(int(local.size))

    def push(self, ids: np.ndarray, grads: np.ndarray):
        """Server-side optimizer runs in the table (reference:
        `common_sparse_table.cc`); duplicate ids accumulate first."""
        local = self._local(ids)
        g = np.asarray(grads, np.float32).reshape(local.size, self.dim)
        if self._native is not None:
            self._native.push(local, g)
            return
        self._stats.counter("push_ops").add(1)
        self._stats.counter("push_rows").add(int(local.size))
        with self._lock:
            # scatter-add duplicates, then one update per unique row
            uniq, inv = np.unique(local, return_inverse=True)
            self._stats.counter("push_coalesced_rows").add(
                int(local.size) - len(uniq))
            acc = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(acc, inv, g)
            if self.optimizer == "sgd":
                self.data[uniq] -= self.lr * acc
            elif self.optimizer == "adagrad":
                g2 = self._g2[uniq] + acc * acc
                self._g2[uniq] = g2
                self.data[uniq] -= self.lr * acc / (np.sqrt(g2) +
                                                    self.eps)
            else:  # adam with per-row step counts (sparse-Adam rule)
                self._t[uniq] += 1
                t = self._t[uniq].astype(np.float32)[:, None]
                m = self.beta1 * self._m[uniq] + (1 - self.beta1) * acc
                v = self.beta2 * self._v[uniq] + \
                    (1 - self.beta2) * acc * acc
                self._m[uniq], self._v[uniq] = m, v
                mhat = m / (1 - self.beta1 ** t)
                vhat = v / (1 - self.beta2 ** t)
                self.data[uniq] -= self.lr * mhat / (np.sqrt(vhat) +
                                                     self.eps)


class TableService:
    """Per-process PS node: hosts local shards, serves peers, and
    provides the client-side pull/push over all shards."""

    def __init__(self, rank: int, world: int, port_base: int):
        self.rank, self.world = rank, world
        self._ports = [port_base + _PORT_OFFSET + r for r in range(world)]
        # multi-host: peer hosts come from the launcher endpoint list
        # (PADDLE_TRAINER_ENDPOINTS "host:port,..."); single host (or no
        # launcher) stays loopback. The listener binds all interfaces so
        # remote peers can reach it.
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        hosts = [e.split(":")[0] for e in eps.split(",") if e]
        self._hosts = hosts if len(hosts) == world else \
            ["127.0.0.1"] * world
        self._bind_host = "" if len(set(self._hosts)) > 1 else "127.0.0.1"
        self._shards: Dict[str, _Shard] = {}
        self._conns: Dict[int, object] = {}
        self._conn_lock = threading.Lock()
        self._rpc_locks: Dict[int, threading.Lock] = {}
        # C data-plane (csrc/ptpu_ps_server.cc): serves fast pull/push
        # frames for native shards without Python in the loop. Started
        # lazily by register(); peers learn the port over the control
        # plane ("data_port" op). Deterministic port: control ports use
        # [PORT_OFFSET, PORT_OFFSET+world), data uses the next block.
        self._data_server = None
        self._data_port_nominal = port_base + _PORT_OFFSET + world + rank
        self._data_ports: Dict[tuple, int] = {}    # (peer, table) -> port
        self._data_conns: Dict[int, _DataConn] = {}
        self._data_locks: Dict[int, threading.Lock] = {}
        self._stop = False
        self._async_q: "queue.Queue" = queue.Queue()
        self._listener = None
        self._threads = []
        # wire-level stats of the PYTHON serve plane — same counter
        # names as the C data-plane server's ServerStats
        # (csrc/ptpu_ps_server.cc), so stats_snapshot() merges the two
        # planes field-for-field; plus client-side pipelining counters
        self._wire_stats = pstats.Registry()
        self._client_stats = pstats.Registry()
        # server-side async-push coalescing (reference: the merge-then-
        # apply DenseOptimizer path of `service/communicator.cc`, here on
        # the RECEIVING side): async fast-frame pushes append to
        # _pending[table] and are acked immediately; _apply_loop (or the
        # next pull of that table — read-your-writes) merges the queued
        # (ids, grads) into ONE scatter-update.
        self._pending: Dict[str, list] = {}
        self._pending_cv = threading.Condition()
        self._applying = 0
        # peers holding coalesced pushes from us (flush barriers them)
        self._async_peers: set = set()
        self._async_peers_lock = threading.Lock()
        # generic KV (rank 0 is the store) — backs elastic membership and
        # cross-rank barriers (reference: gloo HTTP-KV / etcd rendezvous)
        self._kv: Dict[str, bytes] = {}
        self._kv_lock = threading.Lock()
        # global-shuffle receive buffer (reference: DatasetImpl
        # GlobalShuffle exchanges records over brpc, `data_set.h:101`)
        self._shuffle_buf: list = []
        self._shuffle_lock = threading.Lock()
        # heter split-training function registry (reference:
        # `heter_server.cc` RegisterServiceHandler)
        self._heter_fns: Dict[str, object] = {}
        if world > 1:
            self._listener = Listener((self._bind_host, self._ports[rank]),
                                      backlog=64, authkey=_authkey())
            t = threading.Thread(target=self._accept_loop, daemon=True)
            t.start()
            self._threads.append(t)
        ta = threading.Thread(target=self._async_push_loop, daemon=True)
        ta.start()
        self._threads.append(ta)
        tp = threading.Thread(target=self._apply_loop, daemon=True)
        tp.start()
        self._threads.append(tp)

    # ---- server side ----------------------------------------------------

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while not self._stop:
                try:
                    data = conn.recv_bytes()
                except (EOFError, OSError):
                    return
                try:
                    tag = wire.fast_tag(data)
                    if tag >= 0:
                        self._serve_fast(conn, tag, data)
                        continue
                    op, table, payload = wire.loads(data)
                except ValueError as e:
                    # malformed frame (wire protocol error): drop THIS
                    # connection cleanly; the serve thread and the
                    # service survive a garbled/malicious peer
                    import sys
                    self._wire_stats.counter("proto_errors").add(1)
                    print(f"ps: dropping connection on malformed "
                          f"frame: {e}", file=sys.stderr)
                    return
                if op == "pull":
                    self._wire_stats.counter("pull_ops").add(1)
                    self._wire_stats.counter("pull_rows").add(
                        int(np.asarray(payload).size))
                    send_msg(conn, self._shards[table].pull(payload))
                elif op == "push":
                    ids, grads = payload
                    self._wire_stats.counter("push_ops").add(1)
                    self._wire_stats.counter("push_rows").add(
                        int(np.asarray(ids).size))
                    self._shards[table].push(ids, grads)
                    send_msg(conn, b"ok")
                elif op == "push_drain":
                    # barrier for server-side coalescing: reply once the
                    # pending queue is empty and no apply is in flight
                    with self._pending_cv:
                        while (self._pending or self._applying) and \
                                not self._stop:
                            self._pending_cv.wait(0.5)
                    send_msg(conn, b"ok")
                elif op == "data_port":
                    # advertise the C data plane for `table` (None when
                    # the shard is numpy-hosted or the server is off)
                    port = None
                    if self._data_server is not None and \
                            table in self._data_server._tables:
                        port = self._data_server.port
                    send_msg(conn, port)
                elif op == "barrier_probe":
                    send_msg(conn, b"ok")
                elif op == "stats":
                    # live observability snapshot (tools/ps_stats.py
                    # polls this; ps_bench embeds it per phase)
                    send_msg(conn, self.stats_snapshot())
                elif op == "stats_reset":
                    self.stats_reset()
                    send_msg(conn, b"ok")
                elif op == "kv_put":
                    with self._kv_lock:
                        self._kv[table] = payload
                    send_msg(conn, b"ok")
                elif op == "kv_get":
                    with self._kv_lock:
                        send_msg(conn, self._kv.get(table))
                elif op == "kv_prefix":
                    with self._kv_lock:
                        send_msg(conn, {k: v for k, v in self._kv.items()
                                      if k.startswith(table)})
                elif op == "kv_del":
                    with self._kv_lock:
                        self._kv.pop(table, None)
                    send_msg(conn, b"ok")
                elif op == "shuffle_recv":
                    with self._shuffle_lock:
                        self._shuffle_buf.extend(payload)
                    send_msg(conn, b"ok")
                elif op == "heter_call":
                    # heterogeneous split training (reference:
                    # heter_client/server.cc): run a registered function
                    # (e.g. the jitted dense step on the device owner)
                    # on behalf of a CPU-side worker. Failures travel as
                    # a STRUCTURED ('err', kind, msg) tuple — the client
                    # dispatches on `kind`, never on message prefixes (a
                    # registered fn whose error text happens to start
                    # with "KeyError: heter fn" must stay a plain
                    # remote-failure, not an unregistered-fn KeyError)
                    fn = self._heter_fns.get(table)
                    if fn is None:
                        send_msg(conn, ("err", "unregistered",
                                        f"heter fn {table!r} not "
                                        f"registered on rank "
                                        f"{self.rank}"))
                    else:
                        try:
                            send_msg(conn, ("ok", fn(*payload)))
                        except Exception as e:  # noqa: BLE001
                            send_msg(conn, ("err", "exception", repr(e)))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _send_err(self, conn, msg: str) -> None:
        frame = wire.build_err(msg)
        self._wire_stats.counter("err_frames").add(1)
        self._wire_stats.counter("bytes_out").add(len(frame) + 4)
        conn.send_bytes(frame)

    def _serve_fast(self, conn, tag: int, data):
        """Fixed-layout pull/push frames — the hot path. Protocol-level
        garbage raises ValueError (dropping the connection, same as the
        generic decoder); application errors (unknown table, id out of
        range) travel back as ERR frames so the client can raise.
        Counters mirror the C data plane's ServerStats names
        (csrc/ptpu_ps_server.cc) so the planes merge."""
        import time
        t0 = time.perf_counter()
        ws = self._wire_stats
        ws.counter("bytes_in").add(len(data) + 4)
        try:
            if tag == wire.TAG_PULL_REQ:
                table, ids = wire.parse_pull_req(data)
            elif tag == wire.TAG_PUSH_REQ:
                table, ids, grads, is_async = wire.parse_push_req(data)
            else:
                raise ValueError(f"PS wire: unexpected fast request "
                                 f"tag {tag:#x}")
        except ValueError:
            ws.counter("proto_errors").add(1)
            raise
        except Exception as e:  # header garbage: uniform protocol error
            ws.counter("proto_errors").add(1)
            raise ValueError(f"PS wire: malformed fast frame "
                             f"({type(e).__name__}: {e})") from e
        shard = self._shards.get(table)
        if shard is None:
            self._send_err(conn,
                           f"unknown table {table!r} on rank {self.rank}")
            return
        if tag == wire.TAG_PULL_REQ:
            if self._pending:
                # read-your-writes: merge queued async pushes for this
                # table before serving rows from it. A bad queued batch
                # (async pushes were acked before validation) must not
                # take down this INNOCENT puller's connection — it is
                # dropped, the same fate the applier thread gives it.
                try:
                    self._apply_pending(table)
                except Exception:
                    pass
            frame, body = wire.alloc_pull_rep(ids.size, shard.dim)
            try:
                shard.pull_into(ids, body)
            except ValueError as e:
                self._send_err(conn, str(e))
                return
            conn.send_bytes(frame)
            ws.counter("pull_ops").add(1)
            ws.counter("pull_rows").add(int(ids.size))
            ws.counter("bytes_out").add(len(frame) + 4)
            ws.histogram("pull_us").observe(
                (time.perf_counter() - t0) * 1e6)
        else:
            if is_async:
                with self._pending_cv:
                    self._pending.setdefault(table, []).append(
                        (ids, grads))
                    self._pending_cv.notify_all()
                conn.send_bytes(wire.OK_FRAME)
                ws.counter("async_push_queued_frames").add(1)
            else:
                try:
                    shard.push(ids, grads)
                except ValueError as e:
                    self._send_err(conn, str(e))
                    return
                conn.send_bytes(wire.OK_FRAME)
            ws.counter("push_ops").add(1)
            ws.counter("push_rows").add(int(ids.size))
            ws.counter("bytes_out").add(len(wire.OK_FRAME) + 4)
            ws.histogram("push_us").observe(
                (time.perf_counter() - t0) * 1e6)

    def _apply_pending(self, table: str):
        with self._pending_cv:
            items = self._pending.pop(table, None)
            if items:
                self._applying += 1
        if not items:
            return
        try:
            flat = np.concatenate([i for i, _ in items])
            g = np.concatenate([x for _, x in items])
            # server-side coalescing: N queued frames became ONE
            # scatter-update (the merge the async ack bought)
            self._wire_stats.counter("async_push_applied_batches").add(1)
            self._wire_stats.counter("async_push_merged_frames").add(
                len(items) - 1)
            self._shards[table].push(flat, g)
        finally:
            with self._pending_cv:
                self._applying -= 1
                self._pending_cv.notify_all()

    def _apply_loop(self):
        """Applier thread: merges each table's queued async pushes into
        one scatter-update per drain."""
        while True:
            with self._pending_cv:
                while not self._pending and not self._stop:
                    self._pending_cv.wait(0.1)
                if self._stop and not self._pending:
                    return
                tables = list(self._pending)
            for table in tables:
                try:
                    self._apply_pending(table)
                except Exception:   # shard gone mid-shutdown: drop
                    pass

    # ---- client side ----------------------------------------------------

    def _dial(self, peer: int, timeout_s: float = 60.0):
        """Open a NEW connection to a peer, retrying while it comes up
        (jax init can take seconds) — the reference's brpc channel
        connect retries (`brpc_ps_client.cc`)."""
        import time
        deadline = time.time() + timeout_s
        delay = 0.05
        while True:
            try:
                return Client((self._hosts[peer], self._ports[peer]),
                              authkey=_authkey())
            except (ConnectionRefusedError, OSError):
                if time.time() > deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _conn(self, peer: int, timeout_s: float = 60.0):
        with self._conn_lock:
            c = self._conns.get(peer)
            if c is None:
                c = self._dial(peer, timeout_s)
                self._conns[peer] = c
                self._rpc_locks[peer] = threading.Lock()
            return c

    def _rpc(self, peer: int, op: str, table: str, payload):
        c = self._conn(peer)
        # one in-flight request per connection: the communicator thread's
        # async pushes must not interleave send/recv with the caller's
        # kv/barrier/pull RPCs (crossed replies otherwise)
        with self._rpc_locks[peer]:
            send_msg(c, (op, table, payload))
            return recv_msg(c)

    def _data_conn_for(self, peer: int, table: str):
        """The shared C data-plane connection for (peer, table), or None
        when the peer serves that table from Python. Positive answers
        cache; a None answer is re-asked (the peer may register the
        table on its data plane later)."""
        key = (peer, table)
        port = self._data_ports.get(key)
        if port is None:
            try:
                port = self._rpc(peer, "data_port", table, None)
            except (EOFError, OSError):
                return None
            if port is None:
                return None
            self._data_ports[key] = port
        with self._conn_lock:
            dc = self._data_conns.get(peer)
            if dc is None:
                dc = _DataConn(self._hosts[peer], port, _authkey())
                self._data_conns[peer] = dc
                self._data_locks[peer] = threading.Lock()
        return dc

    def _fast_conn(self, peer: int, table: str):
        """(conn, lock) for fast pull/push frames to `peer` — the C
        data-plane socket when the peer hosts `table` natively, else
        the cached control connection."""
        dc = self._data_conn_for(peer, table)
        if dc is not None:
            return dc, self._data_locks[peer]
        return self._conn(peer), self._rpc_locks[peer]

    def _new_fast_conn(self, peer: int, table: str):
        """A DEDICATED fast connection (Channel): its own socket, so
        concurrent client threads don't serialize."""
        port = None
        try:
            port = self._rpc(peer, "data_port", table, None)
        except (EOFError, OSError):
            pass
        if port is not None:
            return _DataConn(self._hosts[peer], port, _authkey())
        return self._dial(peer)

    def _rpc_pull_into(self, peer: int, table: str, sub: np.ndarray,
                       out: np.ndarray, mask) -> None:
        """Remote pull whose rows land in out[mask] (out[:] when mask is
        None). The reply view may alias the connection's reused receive
        buffer, so the copy into `out` happens under the conn lock."""
        c, lock = self._fast_conn(peer, table)
        req = wire.build_pull_req(table, sub)
        self._client_stats.counter("pull_frames").add(1)
        self._client_stats.counter("pull_reqs").add(1)
        with lock:
            c.send_bytes(req)
            if mask is None and isinstance(c, _DataConn):
                c.recv_pull_into(out)   # body lands straight in out
                return
            reply = c.recv_bytes()
            wire.check_reply(reply, wire.TAG_PULL_REP)
            rows = wire.parse_pull_rep(reply)
            if mask is None:
                out[:] = rows
            else:
                out[mask] = rows

    def _rpc_push(self, peer: int, table: str, sub: np.ndarray,
                  g: np.ndarray, is_async: bool = False):
        c, lock = self._fast_conn(peer, table)
        req = wire.build_push_req(table, sub, g, is_async)
        self._client_stats.counter("push_frames").add(1)
        with lock:
            c.send_bytes(req)
            reply = c.recv_bytes()
            wire.check_reply(reply, wire.TAG_OK)

    def register(self, name: str, vocab: int, dim: int, lr: float = 0.1,
                 seed: int = 0, optimizer: str = "sgd",
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8) -> "ShardedEmbeddingTable":
        shard = _Shard(name, vocab, dim, self.rank, self.world, lr,
                       seed, optimizer, beta1, beta2, eps)
        self._shards[name] = shard
        if shard.native and self.world > 1:
            from ...core import native
            if self._data_server is None and \
                    native.ps_server_available():
                try:
                    # bind scope mirrors the control plane: loopback
                    # unless the job spans hosts
                    self._data_server = native.PsDataServer(
                        self._data_port_nominal, _authkey(),
                        loopback_only=self._bind_host == "127.0.0.1")
                except OSError:
                    self._data_server = None   # port taken: Python plane
            if self._data_server is not None:
                self._data_server.register(name, shard._native,
                                           shard.lo)
        return ShardedEmbeddingTable(self, name, vocab, dim)

    def _owner(self, table: str, flat: np.ndarray) -> np.ndarray:
        block = self._shards[table].block
        return np.minimum(flat // block, self.world - 1)

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        """Gather rows for arbitrary global ids (reference:
        `brpc_ps_client` PullSparse)."""
        flat = np.asarray(ids).reshape(-1)
        dim = self._shards[table].dim
        owner = self._owner(table, flat)
        out = np.empty((flat.size, dim), np.float32)
        for peer in range(self.world):
            m = owner == peer
            if not m.any():
                continue
            sub = flat[m]
            if peer == self.rank:
                out[m] = self._shards[table].pull(sub)
            else:
                full = bool(m.all())
                self._rpc_pull_into(peer, table, sub, out,
                                    None if full else m)
        return out.reshape(tuple(np.shape(ids)) + (dim,))

    # rows per merged wire frame: big enough to amortize per-frame
    # syscall + header costs, small enough to bound in-flight memory
    # (depth * MERGE_ROWS * dim * 4 bytes per peer connection)
    MERGE_ROWS = int(os.environ.get("PTPU_PS_MERGE_ROWS", 4096))

    def pull_many(self, table: str, ids_list, depth: int = 16) -> List[
            np.ndarray]:
        """Pipelined, VECTORIZED batch of pulls (reference: the async
        Communicator merging queued requests per table +
        `brpc_ps_client.cc` keeping many RPCs in flight). Consecutive
        pulls bound for the same peer merge into one wire frame (up to
        MERGE_ROWS rows) whose reply streams straight back into each
        destination array, and up to `depth` frames ride each
        connection before the first reply is awaited — throughput is
        bounded by the wire, not by request latency or per-frame
        overhead. Results match `[pull(table, ids) for ids in
        ids_list]` exactly."""
        shard = self._shards[table]
        dim = shard.dim
        flats, outs, shapes = [], [], []
        per_peer: Dict[int, list] = collections.defaultdict(list)
        for i, ids in enumerate(ids_list):
            flat = np.asarray(ids).reshape(-1)
            flats.append(flat)
            shapes.append(tuple(np.shape(ids)))
            outs.append(np.empty((flat.size, dim), np.float32))
            owner = self._owner(table, flat)
            for peer in range(self.world):
                m = owner == peer
                if not m.any():
                    continue
                if peer == self.rank:
                    if m.all():
                        shard.pull_into(flat, outs[i])
                    else:
                        outs[i][m] = shard.pull(flat[m])
                else:
                    full = bool(m.all())
                    per_peer[peer].append(
                        (i, None if full else m, flat if full
                         else flat[m]))
        for peer, jobs in per_peer.items():
            c, lock = self._fast_conn(peer, table)
            direct = isinstance(c, _DataConn)
            # merge consecutive jobs into wire frames of <= MERGE_ROWS
            groups, cur, rows = [], [], 0
            for job in jobs:
                cur.append(job)
                rows += job[2].size
                if rows >= self.MERGE_ROWS:
                    groups.append(cur)
                    cur, rows = [], 0
            if cur:
                groups.append(cur)
            # pipeline-merge accounting: len(jobs) logical pulls rode
            # len(groups) wire frames on this connection
            self._client_stats.counter("pull_frames").add(len(groups))
            self._client_stats.counter("pull_reqs").add(len(jobs))
            self._client_stats.counter("pull_merged_reqs").add(
                len(jobs) - len(groups))
            with lock:
                inflight = collections.deque()

                def finish():
                    grp = inflight.popleft()
                    if direct and all(m is None for _, m, _ in grp):
                        c.recv_pull_into_seq([outs[i]
                                              for i, _, _ in grp])
                        return
                    reply = c.recv_bytes()
                    wire.check_reply(reply, wire.TAG_PULL_REP)
                    rows = wire.parse_pull_rep(reply)
                    off = 0
                    for i, m, sub in grp:
                        chunk = rows[off:off + sub.size]
                        off += sub.size
                        if m is None:
                            outs[i][:] = chunk
                        else:
                            outs[i][m] = chunk
                for grp in groups:
                    cat = grp[0][2] if len(grp) == 1 else \
                        np.concatenate([sub for _, _, sub in grp])
                    c.send_bytes(wire.build_pull_req(table, cat))
                    inflight.append(grp)
                    if len(inflight) >= depth:
                        finish()
                while inflight:
                    finish()
        return [o.reshape(s + (dim,)) for o, s in zip(outs, shapes)]

    def open_channel(self, peer: int, depth: int = 16) -> "Channel":
        """Dedicated pipelined client connection to one peer — each
        channel is independent of the cached RPC connection and of other
        channels, so concurrent client threads don't serialize on one
        socket (the server runs a thread per accepted connection)."""
        return Channel(self, peer, depth)

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             sync: bool = True):
        """Scatter row-grads to owners. sync=False queues the send on the
        communicator thread (reference: async `Communicator` batching,
        `service/communicator.cc`)."""
        flat = np.asarray(ids).reshape(-1)
        if flat.size == 0:
            return   # nothing to scatter (reshape(0, -1) would raise)
        g = np.asarray(grads, np.float32).reshape(flat.size, -1)
        if not sync:
            self._async_q.put((table, flat, g))
            return
        self._push_now(table, flat, g)

    def _push_now(self, table, flat, g, is_async: bool = False):
        owner = self._owner(table, flat)
        for peer in range(self.world):
            m = owner == peer
            if not m.any():
                continue
            if peer == self.rank:
                self._shards[table].push(flat[m], g[m])
            else:
                self._rpc_push(peer, table, flat[m], g[m], is_async)
                if is_async:
                    with self._async_peers_lock:
                        self._async_peers.add(peer)

    def _async_push_loop(self):
        """Communicator thread: drains queued pushes and COALESCES
        same-table grads into one RPC per peer per drain (reference:
        async `Communicator` batching by send_queue,
        `service/communicator.cc` — merge then send). Remote sends carry
        the async flag, so the receiving server coalesces further and
        acks without waiting for the update."""
        while True:
            item = self._async_q.get()
            if item is None:
                self._async_q.task_done()
                return
            batch = [item]
            stop = False
            try:
                while True:
                    nxt = self._async_q.get_nowait()
                    if nxt is None:
                        stop = True
                        self._async_q.task_done()
                        break
                    batch.append(nxt)
            except queue.Empty:
                pass
            try:
                self._drain(batch)
            except Exception:   # peer gone mid-push: drop the batch —
                pass            # task_done below keeps flush() unblocked
            finally:
                for _ in batch:
                    self._async_q.task_done()
            if stop:
                return

    def _drain(self, batch):
        by_table: Dict[str, list] = {}
        for table, flat, g in batch:
            by_table.setdefault(table, []).append((flat, g))
        for table, items in by_table.items():
            flat = np.concatenate([f for f, _ in items])
            g = np.concatenate([x for _, x in items])
            self._push_now(table, flat, g, is_async=True)

    # ---- observability (control-plane "stats" op; tools/ps_stats.py
    # polls it, tools/ps_bench.py embeds it per phase) -----------------

    def stats_snapshot(self) -> dict:
        """Everything this PS node can observe, as one plain dict:

        * ``tables`` — per-shard storage counters (pull/push ops, rows,
          coalesced rows), same names for native and numpy backends;
          native shards exposed on the C data plane also carry their
          wire-level view under ``wire_native``.
        * ``wire`` — Python-plane + C-data-plane serve counters MERGED
          (pull/push ops/rows, bytes in/out, err/proto counters,
          pull_us/push_us log2 latency histograms).
        * ``wire_py`` / ``wire_native`` — the unmerged halves.
        * ``client`` — this node's client-side pipelining counters
          (frames sent, logical pulls merged into frames).
        """
        native_srv = self._data_server.stats() \
            if self._data_server is not None else None
        wire_py = self._wire_stats.snapshot()
        tables = {}
        for name, shard in self._shards.items():
            t = shard.stats()
            if native_srv and name in native_srv.get("tables", {}):
                t["wire_native"] = native_srv["tables"][name]["wire"]
            tables[name] = t
        return {
            "rank": self.rank,
            "world": self.world,
            "native_data_plane": self._data_server is not None,
            "wire": pstats.merge(wire_py,
                                 (native_srv or {}).get("server")),
            "wire_py": wire_py,
            "wire_native": (native_srv or {}).get("server"),
            "client": self._client_stats.snapshot(),
            "tables": tables,
        }

    def stats_reset(self) -> None:
        """Zero every counter this node owns (wire, client, storage —
        both planes)."""
        self._wire_stats.reset()
        self._client_stats.reset()
        if self._data_server is not None:
            self._data_server.stats_reset()
        for shard in self._shards.values():
            shard.stats_reset()

    def flush(self):
        """Drain queued async pushes (reference: Communicator barrier):
        wait for the local communicator queue, then barrier every peer
        holding our server-side-coalesced pushes."""
        self._async_q.join()
        with self._async_peers_lock:
            peers = sorted(self._async_peers)
            self._async_peers.clear()
        for peer in peers:
            self._rpc(peer, "push_drain", "", None)

    # ---- heterogeneous split training (reference: N29
    # `heter_client.cc`/`heter_server.cc`, `heterxpu_trainer.cc`:
    # CPU-side workers drive sparse/PS work and RPC the heavy dense
    # compute to the accelerator owner) --------------------------------

    def register_heter_fn(self, name: str, fn):
        """Expose `fn(*numpy_args) -> pytree` to heter_call RPCs (run on
        THIS process — typically the rank that owns the TPU)."""
        self._heter_fns[name] = fn

    def heter_call(self, peer: int, name: str, *args):
        """Invoke a peer's registered heter function and return its
        result (reference: HeterClient::SendAndRecvAsync)."""
        if peer == self.rank:
            return self._heter_fns[name](*args)
        res = self._rpc(peer, "heter_call", name, args)
        if res[0] != "ok":
            # structured status: ('err', kind, msg). Dispatch on the
            # explicit kind — the pre-r6 contract matched the string
            # prefix "KeyError: heter fn", which misclassified any
            # registered fn failing with that exact message text
            _, kind, msg = res
            if kind == "unregistered":
                raise KeyError(msg)
            raise RuntimeError(f"heter_call {name!r} on rank {peer} "
                               f"failed: {msg}")
        return res[1]

    # ---- KV store (rank 0 hosts; reference: gloo HTTP-KV / etcd) --------

    def kv_put(self, key: str, value: bytes):
        if self.rank == 0:
            with self._kv_lock:
                self._kv[key] = value
        else:
            self._rpc(0, "kv_put", key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        if self.rank == 0:
            with self._kv_lock:
                return self._kv.get(key)
        return self._rpc(0, "kv_get", key, None)

    def kv_prefix(self, prefix: str) -> Dict[str, bytes]:
        if self.rank == 0:
            with self._kv_lock:
                return {k: v for k, v in self._kv.items()
                        if k.startswith(prefix)}
        return self._rpc(0, "kv_prefix", prefix, None)

    def kv_del(self, key: str):
        if self.rank == 0:
            with self._kv_lock:
                self._kv.pop(key, None)
        else:
            self._rpc(0, "kv_del", key, None)

    def barrier(self, name: str, timeout_s: float = 120.0):
        """KV-backed barrier (reference: `barrier_table.cc`). Each use of
        a name gets a fresh sequence number (all ranks must call barriers
        in the same order) so repeated barriers don't see stale keys."""
        import time
        if not hasattr(self, "_barrier_seq"):
            self._barrier_seq = {}
        seq = self._barrier_seq.get(name, 0)
        self._barrier_seq[name] = seq + 1
        full = f"__barrier__/{name}#{seq}/"
        self.kv_put(f"{full}{self.rank}", b"1")
        deadline = time.time() + timeout_s
        while True:
            n = len(self.kv_prefix(full))
            if n >= self.world:
                return
            if time.time() > deadline:
                raise TimeoutError(f"barrier {name!r}: {n}/{self.world}")
            time.sleep(0.01)

    # ---- global shuffle exchange (reference: DatasetImpl::GlobalShuffle,
    # `data_set.h:101` — records repartition over the PS RPC channel) ----

    def exchange_records(self, per_target: Dict[int, list],
                         tag: str) -> list:
        """Send each target rank its records; barrier; return everything
        this rank received (plus its own share)."""
        with self._shuffle_lock:
            self._shuffle_buf.extend(per_target.get(self.rank, []))
        for peer, recs in per_target.items():
            if peer != self.rank and recs:
                self._rpc(peer, "shuffle_recv", "", recs)
        self.barrier(f"shuffle/{tag}")
        with self._shuffle_lock:
            out, self._shuffle_buf = self._shuffle_buf, []
        # exit barrier: a fast peer must not start the NEXT exchange and
        # deposit records before this rank's pop above
        self.barrier(f"shuffle-exit/{tag}")
        return out

    def finalize(self, timeout_s: float = 60.0):
        """Coordinated shutdown: non-zero ranks announce 'bye' (their
        LAST rpc) before closing; rank 0 waits for every bye so no
        peer's final poll hits a closed listener."""
        import time
        self.flush()
        if self.world > 1:
            if self.rank != 0:
                self.kv_put(f"__bye__/{self.rank}", b"1")
            else:
                deadline = time.time() + timeout_s
                while len(self.kv_prefix("__bye__/")) < self.world - 1:
                    if time.time() > deadline:
                        break
                    time.sleep(0.01)
        self.shutdown()

    def shutdown(self):
        self._stop = True
        self._async_q.put(None)
        with self._pending_cv:
            self._pending_cv.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
            for dc in self._data_conns.values():
                dc.close()
            self._data_conns.clear()
        if self._data_server is not None:
            self._data_server.stop()
            self._data_server = None


class Channel:
    """A dedicated pipelined client connection to one peer (reference:
    one brpc Channel per communication thread). Keeps up to `depth`
    pull requests in flight; `pull` drains outstanding traffic first so
    results are always consistent. NOT thread-safe — one channel per
    client thread is the intended shape."""

    def __init__(self, svc: TableService, peer: int, depth: int = 16):
        if peer == svc.rank:
            raise ValueError("channels connect to REMOTE peers; local "
                             "shards are called directly")
        self._svc, self.peer, self.depth = svc, peer, depth
        self._c = None   # dialed on first use, once the table is known
        self._inflight: collections.deque = collections.deque()

    def _ensure(self, table: str):
        if self._c is None:
            self._c = self._svc._new_fast_conn(self.peer, table)
        return self._c

    def pull_nowait(self, table: str, ids, out: np.ndarray):
        """Issue a pull whose rows land in `out` (n, dim); blocks only
        when `depth` requests are already outstanding."""
        self._ensure(table).send_bytes(wire.build_pull_req(
            table, np.asarray(ids).reshape(-1)))
        self._inflight.append(("pull", out))
        while len(self._inflight) > self.depth:
            self._finish_one()

    def push_async(self, table: str, ids, grads):
        """Fire-and-forget push: the server acks after enqueueing into
        its coalescer (data plane: after applying); the ack is
        collected lazily."""
        self._ensure(table).send_bytes(wire.build_push_req(
            table, np.asarray(ids).reshape(-1),
            np.asarray(grads, np.float32), True))
        self._inflight.append(("push", None))
        while len(self._inflight) > self.depth:
            self._finish_one()

    def _finish_one(self):
        kind, out = self._inflight.popleft()
        if kind == "pull" and isinstance(self._c, _DataConn):
            self._c.recv_pull_into(out)
            return
        reply = self._c.recv_bytes()
        if kind == "pull":
            wire.check_reply(reply, wire.TAG_PULL_REP)
            out[:] = wire.parse_pull_rep(reply)
        else:
            wire.check_reply(reply, wire.TAG_OK)

    def drain(self):
        """Collect every outstanding reply."""
        while self._inflight:
            self._finish_one()

    def pull(self, table: str, ids) -> np.ndarray:
        flat = np.asarray(ids).reshape(-1)
        self.drain()
        out = np.empty((flat.size, self._svc._shards[table].dim),
                       np.float32)
        self.pull_nowait(table, flat, out)
        self.drain()
        return out.reshape(tuple(np.shape(ids)) + out.shape[-1:])

    def close(self):
        try:
            self.drain()
        except (EOFError, OSError, ValueError, RuntimeError):
            pass
        if self._c is not None:
            try:
                self._c.close()
            except OSError:
                pass


class ShardedEmbeddingTable:
    """User handle: pull rows before the compiled dense step, push row
    grads after it (DownpourWorker dataflow, `device_worker.h:244`)."""

    def __init__(self, service: TableService, name: str, vocab: int,
                 dim: int):
        self._svc = service
        self.name, self.vocab, self.dim = name, vocab, dim

    def pull(self, ids) -> np.ndarray:
        return self._svc.pull(self.name, np.asarray(ids))

    def push(self, ids, grads, sync: bool = True):
        self._svc.push(self.name, np.asarray(ids), np.asarray(grads),
                       sync=sync)

    def flush(self):
        self._svc.flush()


_SERVICE: Optional[TableService] = None


def init_table_service() -> TableService:
    """Build the per-process PS node from the launcher env contract
    (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / MASTER_PORT — the same
    vars `the_one_ps.py:434 _init_server` reads)."""
    global _SERVICE
    if _SERVICE is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        port = int(os.environ.get("MASTER_PORT", "8476"))
        _SERVICE = TableService(rank, world, port)
    return _SERVICE


def shutdown_table_service():
    global _SERVICE
    if _SERVICE is not None:
        _SERVICE.finalize()
        _SERVICE = None
