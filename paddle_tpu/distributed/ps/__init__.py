"""Parameter server — host-resident sharded embedding tables.

TPU-native core of the reference's "the one PS" stack (N22):
  * brpc RPC service (`distributed/service/brpc_ps_server.cc`,
    `brpc_ps_client.cc`) → a lightweight authenticated TCP message
    service per trainer process (`multiprocessing.connection`), riding
    the same host network (DCN) the reference's brpc does;
  * sparse tables (`distributed/table/common_sparse_table.cc`) →
    `ShardedEmbeddingTable`: rows sharded round-robin over processes,
    host-resident numpy storage, server-side optimizer update on push;
  * async `Communicator` grad sends (`service/communicator.cc`) →
    `push(..., sync=False)` fire-and-forget worker thread;
  * `TheOnePSRuntime._init_server/_init_worker` (`the_one_ps.py:434`) →
    `init_table_service()` from the launcher env contract.

Design note: the dense model trains on-device via the normal compiled
step; the PS embedding lives OUTSIDE jit — pull rows → jitted dense step
→ push row grads, exactly the reference's DownpourWorker dataflow
(`device_worker.h:244`). This is the right split on TPU too: giant
embedding tables don't fit HBM, and the sparse gather/scatter is
host-memory-bound, not MXU work.
"""
from .table import (ShardedEmbeddingTable, TableService,
                    init_table_service, shutdown_table_service)
from .advanced import GeoTable, GraphTable, SSDTable  # noqa: F401
from .heter import HeterServer, HeterWorker  # noqa: F401

__all__ = ["ShardedEmbeddingTable", "TableService", "init_table_service",
           "shutdown_table_service", "GeoTable", "SSDTable", "GraphTable",
           "HeterServer", "HeterWorker"]
