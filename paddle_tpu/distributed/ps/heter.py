"""Heterogeneous split training (reference N29: `heter_client.cc`,
`heter_server.cc`, `heterxpu_trainer.cc`, `hetercpu_worker.cc`).

The reference splits one model between CPU parameter-server workers
(sparse embedding lookup/update, data feeding) and accelerator services
(the heavy dense layers), exchanging activations/grads over brpc.

TPU-native mapping: the process that owns the TPU registers its jitted
dense step as a heter function on the `TableService` wire protocol; CPU
worker ranks pull embedding rows from the sharded host table, RPC the
dense forward/backward to the device owner, and push the returned
embedding-row grads back to the table. The accelerator never blocks on
sparse work and the CPU never traces XLA.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from .table import ShardedEmbeddingTable, TableService


class HeterWorker:
    """CPU-side worker (reference: `hetercpu_worker.cc` DeviceWorker
    loop): per batch — pull rows, heter_call the dense step, push row
    grads."""

    def __init__(self, svc: TableService, table: ShardedEmbeddingTable,
                 device_rank: int, step_name: str = "dense_step"):
        self._svc = svc
        self._table = table
        self._device_rank = device_rank
        self._step_name = step_name

    def train_batch(self, ids, labels, sync_push: bool = True):
        """One DownpourWorker-style tick through the heter service.
        Returns the loss reported by the device owner."""
        rows = self._table.pull(ids)
        loss, row_grads = self._svc.heter_call(
            self._device_rank, self._step_name,
            np.asarray(rows, np.float32), np.asarray(labels))
        self._table.push(ids, row_grads, sync=sync_push)
        return float(loss)


class HeterServer:
    """Accelerator-side service (reference: `heter_server.cc`): wraps a
    jitted dense step `fn(rows, labels) -> (loss, row_grads)` and serves
    it to CPU workers."""

    def __init__(self, svc: TableService, fn: Callable,
                 step_name: str = "dense_step"):
        svc.register_heter_fn(step_name, fn)
        self._svc = svc
        self._name = step_name
