"""Sequence/context parallelism — ring attention + Ulysses (all-to-all).

BEYOND-REFERENCE capability (SURVEY.md §5 "Long-context / sequence
parallelism: Absent ... The TPU build must therefore add SP/CP"). The only
reference hook is the `alltoall` collective
(`operators/collective/alltoall_op.cc`), which is the Ulysses building
block.

Two schemes over the 'sequence' mesh axis, both used inside
`jax.shard_map`:

* **ring_attention** — q/k/v sharded on the sequence dim; K/V blocks
  rotate around the ring via `lax.ppermute` over ICI while each chip
  accumulates its queries' attention in flash style (running max /
  normalizer — the S×S score matrix never materializes globally).
  Communication overlaps compute; memory per chip is O(S/sp · S/sp).
* **ulysses_attention** — `lax.all_to_all` reshards [B, S/sp, H, D] →
  [B, S, H/sp, D], runs dense per-head attention locally, then reshards
  back. Cheaper collectives for moderate S; requires heads % sp == 0.

Both are reverse-differentiable (scan + ppermute/all_to_all transpose
rules) so they drop straight into training.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, axis_name: str = "sequence",
                   causal: bool = False, scale: Optional[float] = None,
                   positions=None):
    """Blockwise ring attention on per-chip shards.

    q, k, v: [b, s_local, h, d] — the local sequence shard (call inside
    shard_map with in_specs sharding dim 1 over `axis_name`).
    Returns [b, s_local, h, d].

    `positions` ([s_local] int32, optional): GLOBAL sequence position of
    each local token, for non-contiguous layouts — zigzag load balancing
    (`zigzag_permutation`) hands every rank an early and a late chunk so
    the causal mask wastes no rank. Defaults to the contiguous layout
    rank*s + arange(s). K positions travel around the ring with their
    K/V blocks.
    """
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    # [b, h, s, d] compute layout
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
    kh0 = jnp.swapaxes(k, 1, 2)
    vh0 = jnp.swapaxes(v, 1, 2)

    if positions is None:
        q_pos = idx * s + jnp.arange(s)                  # global q positions
    else:
        q_pos = jnp.asarray(positions, jnp.int32)
    k_pos0 = q_pos

    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(carry, i):
        o, m, l, kh, vh, k_pos = carry
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh,
                            kh.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]       # [sq, sk]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_blk = jnp.max(scores, axis=-1)                  # [b,h,sq]
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (all -inf): keep m finite
        m_new = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_new[..., None])            # masked → exp(-inf)=0
        corr = jnp.exp(m - m_new)                         # rescale old acc
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
        kh_n = lax.ppermute(kh, axis_name, perm)
        vh_n = lax.ppermute(vh, axis_name, perm)
        kp_n = lax.ppermute(k_pos, axis_name, perm)
        return (o_new, m_new, l_new, kh_n, vh_n, kp_n), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (o, m, l, _, _, _), _ = lax.scan(step, (o0, m0, l0, kh0, vh0, k_pos0),
                                     jnp.arange(sp))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def zigzag_permutation(seq_len: int, sp: int):
    """Zigzag sequence layout for causal ring attention load balance.

    Contiguous sharding gives rank 0 almost no unmasked work and rank
    sp-1 nearly all of it. The zigzag order hands rank r chunks r and
    2*sp-1-r (seq split into 2*sp chunks), so every rank sees the same
    causal-mask density. Returns an int32 numpy array `order` of length
    seq_len: token j of the zigzag layout is original position order[j];
    rank r's shard is order[r*seq_len//sp : (r+1)*seq_len//sp].
    """
    import numpy as np
    if seq_len % (2 * sp):
        raise ValueError(f"seq_len {seq_len} must be a multiple of "
                         f"2*sp={2 * sp}")
    chunk = seq_len // (2 * sp)
    order = []
    for r in range(sp):
        order.extend(range(r * chunk, (r + 1) * chunk))
        order.extend(range((2 * sp - 1 - r) * chunk,
                           (2 * sp - r) * chunk))
    return np.asarray(order, np.int32)


def ulysses_attention(q, k, v, axis_name: str = "sequence",
                      causal: bool = False, scale: Optional[float] = None,
                      attn_fn=None):
    """DeepSpeed-Ulysses resharding attention on per-chip shards.

    q, k, v: [b, s_local, h, d]; requires h % sp == 0.
    """
    sp = lax.psum(1, axis_name)   # axis size — static at trace time
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({sp}); use ring attention instead")

    def to_seq(x):   # [b, s/sp, h, d] -> [b, s, h/sp, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_heads(x):  # [b, s, h/sp, d] -> [b, s/sp, h, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    if attn_fn is None:
        from ...nn.functional.attention import _xla_attention
        out = _xla_attention(qs, ks, vs, None, 0.0, causal, False, scale)
    else:
        out = attn_fn(qs, ks, vs)
    return to_heads(out)


def make_sp_attention(mesh, mode: str = "ring", causal: bool = False,
                      axis_name: str = "sequence", zigzag: bool = False,
                      jit: bool = True):
    """Wrap ring/ulysses attention as a global-view function on sequence-
    sharded [b, s, h, d] arrays via shard_map (other mesh axes stay auto).

    zigzag (ring+causal only): inputs are expected in the zigzag layout
    (`zigzag_permutation` applied along the sequence dim); positions are
    threaded through the ring so the causal mask is exact. `jit=False`
    returns the raw shard_map for embedding inside an outer jit trace
    (e.g. models.gpt.build_train_step)."""
    if mode not in ("ring", "ulysses"):
        raise ValueError(f"mode must be 'ring' or 'ulysses', got {mode!r}")
    if zigzag and mode != "ring":
        raise ValueError("zigzag layout applies to ring attention")
    from jax.sharding import PartitionSpec as P
    spec = P(None, axis_name, None, None)
    sp = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    if mode == "ulysses":
        inner = partial(ulysses_attention, axis_name=axis_name,
                        causal=causal)
        wrapped = jax.shard_map(
            lambda q, k, v: inner(q, k, v),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={axis_name}, check_vma=False)
        # partial-manual shard_map (axis_names ⊂ mesh axes) only resolves
        # inside a jit trace; eager calls misread the unmentioned axes
        return jax.jit(wrapped) if jit else wrapped

    ring = jax.shard_map(
        lambda q, k, v, pos: ring_attention(q, k, v, axis_name=axis_name,
                                            causal=causal, positions=pos),
        mesh=mesh, in_specs=(spec, spec, spec, P(axis_name)),
        out_specs=spec, axis_names={axis_name}, check_vma=False)

    def call(q, k, v):
        s = q.shape[1]
        if zigzag:
            pos = jnp.asarray(zigzag_permutation(s, sp), jnp.int32)
        else:
            pos = jnp.arange(s, dtype=jnp.int32)
        return ring(q, k, v, pos)

    return jax.jit(call) if jit else call
