"""Hybrid-parallel (dygraph "meta_parallel") stack — TPU-native.

Mirrors `python/paddle/distributed/fleet/meta_parallel/` of the reference:
tensor parallel layers (`parallel_layers/mp_layers.py`), pipeline layers +
schedule (`parallel_layers/pp_layers.py`, `pipeline_parallel.py`), sharding
(`sharding/`), and the model wrappers dispatched by
`fleet.distributed_model` (`fleet_base.py:836`).

Design: the reference implements each strategy with explicit NCCL
collectives (identity-fwd/allreduce-bwd ops, send_v2/recv_v2 P2P). Here the
primary mechanism is GSPMD: layers annotate weights/activations with
`PartitionSpec`s over the global mesh and XLA inserts the matching
collectives over ICI. Pipeline parallelism — which GSPMD does not express —
uses `jax.shard_map` over the 'pipe' axis with `lax.ppermute` microbatch
shifting (see pipeline_parallel.py).
"""
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .tensor_parallel import (  # noqa: F401
    ShardingParallel,
    TensorParallel,
    shard_parameters,
)
from .sharding_optimizer import DygraphShardingOptimizer  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    make_sp_attention,
    ring_attention,
    ulysses_attention,
)
from .stacked_pipeline import (  # noqa: F401
    gpipe,
    pipelined_apply,
    stack_stage_params,
    unstack_stage_params,
)
from ...framework.random import (  # noqa: F401
    RNGStatesTracker,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from .moe import MoEMLP, top2_gating  # noqa: F401
