"""Mixture-of-Experts with expert parallelism (beyond-reference).

The reference snapshot has NO MoE layers (SURVEY §2.3: expert parallel ✗;
its only hook is the `alltoall` collective, `operators/collective/
alltoall_op.cc`). This module adds the capability TPU-first, GShard
style: expert weights carry a PartitionSpec over an expert axis and
token dispatch/combine are einsums against a capacity-bounded dispatch
mask — under GSPMD those einsums lower to exactly the all-to-all the
reference would have hand-written.

Gating follows GShard top-2: top-1 expert + probabilistic second expert,
position-in-expert capacity enforcement via cumsum (tokens over capacity
are dropped — dense shapes, no sorting, XLA-friendly).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from .mp_layers import _constrain


def top2_gating(logits, capacity: int):
    """GShard top-2 gating. logits [g, s, e] fp32 →
    (dispatch [g, s, e, c] bool-ish, combine [g, s, e, c] fp32, aux)."""
    g, s, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    # top-1
    idx1 = jnp.argmax(probs, axis=-1)                      # [g, s]
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)
    # top-2: mask out the winner, argmax again
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    # load-balancing auxiliary loss (GShard eq. 4 / Switch aux)
    density = jnp.mean(mask1, axis=1)                      # [g, e]
    density_proxy = jnp.mean(probs, axis=1)
    aux = jnp.mean(density * density_proxy) * (e * e)
    # capacity positions (top-1 tokens first, then top-2)
    pos1 = jnp.cumsum(mask1, axis=1) * mask1               # 1-based
    pos2 = (jnp.cumsum(mask2, axis=1) +
            jnp.sum(mask1, axis=1, keepdims=True)) * mask2
    keep1 = mask1 * (pos1 <= capacity)
    keep2 = mask2 * (pos2 <= capacity)
    w1 = jnp.sum(probs * keep1, axis=-1)                   # [g, s]
    w2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(w1 + w2, 1e-9)
    w1, w2 = w1 / denom, w2 / denom

    def to_cap(keep, pos, w):
        # [g, s, e] one-hot rows at capacity slot pos-1 → [g, s, e, c]
        slot = jax.nn.one_hot((pos - 1.0) * keep, capacity,
                              dtype=keep.dtype) * keep[..., None]
        return slot * w[..., None, None]

    combine = to_cap(keep1, pos1, w1) + to_cap(keep2, pos2, w2)
    dispatch = (combine > 0.0).astype(logits.dtype)
    return dispatch, combine, aux


class MoEMLP(Layer):
    """Expert-parallel FFN block: gate → dispatch → per-expert MLP →
    combine. Expert weights are sharded over `expert_axis` (defaults to
    the 'model' mesh axis — expert parallelism rides the TP axis the way
    alltoall-based MoE rides NCCL groups)."""

    def __init__(self, d_model: int, d_ff: int, num_experts: int,
                 capacity_factor: float = 1.25,
                 expert_axis: str = "model", compute_dtype=None):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        init = I.Normal(0.0, 0.02)
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=init)
        self.w1 = self.create_parameter((num_experts, d_model, d_ff),
                                        default_initializer=init)
        self.w2 = self.create_parameter((num_experts, d_ff, d_model),
                                        default_initializer=init)
        self.w1.sharding_spec = P(expert_axis, None, None)
        self.w2.sharding_spec = P(expert_axis, None, None)
        self._axis = expert_axis
        self._cdt = compute_dtype
        # aux loss rides a BUFFER so it survives functional_call/jit
        # (a plain attribute would hold a leaked tracer); jitted steps
        # read it from the returned new_buffers, eager from .value
        self.register_buffer("aux_loss", jnp.zeros((), jnp.float32))

    def forward(self, x):
        b, s, d = x.shape
        e = self.num_experts
        cap = max(1, int(self.capacity_factor * s * 2 / e))
        xf = x.astype(jnp.float32)
        logits = xf @ jnp.asarray(self.gate_weight).astype(jnp.float32)
        dispatch, combine, aux = top2_gating(logits, cap)
        self.aux_loss.value = aux
        dt = self._cdt or x.dtype
        # dispatch: [b,s,d] x [b,s,e,c] -> [e,b,c,d] — under GSPMD with
        # tokens sharded on 'data' and experts on the expert axis this
        # IS the all-to-all (`alltoall_op.cc` equivalent)
        xin = jnp.einsum("bsd,bsec->ebcd", x.astype(dt),
                         dispatch.astype(dt))
        xin = _constrain(xin, self._axis, None, None, None)
        w1 = jnp.asarray(self.w1).astype(dt)
        w2 = jnp.asarray(self.w2).astype(dt)
        h = jnp.einsum("ebcd,edf->ebcf", xin, w1)
        h = F.gelu(h, approximate=True)
        out = jnp.einsum("ebcf,efd->ebcd", h, w2)
        out = _constrain(out, self._axis, None, None, None)
        y = jnp.einsum("ebcd,bsec->bsd", out.astype(jnp.float32),
                       combine)
        return y.astype(x.dtype)
