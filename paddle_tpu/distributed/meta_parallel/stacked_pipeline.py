"""GSPMD pipeline-parallel engine (GPipe / F-then-B schedule).

TPU-native replacement for the reference's pipeline runtime — static
`SectionWorker::TrainFiles` F-then-B / 1F1B schedules
(`framework/section_worker.cc:130-156`) and dygraph
`PipelineParallel.train_batch` (`meta_parallel/pipeline_parallel.py:109`)
with NCCL `send_v2/recv_v2` P2P between stages.

Mechanism: instead of per-stage processes exchanging tensors, the S
pipeline stages are expressed as ONE stacked computation:

  * per-stage block parameters are stacked on a leading dim of size S and
    sharded over the 'pipe' mesh axis — each pipe device materializes only
    its own stage's weights;
  * a rolling activation buffer [S, microbatch, ...], also 'pipe'-sharded,
    holds the in-flight microbatch of every stage;
  * each tick: shift the buffer one stage forward (`jnp.roll` on the
    sharded dim → XLA CollectivePermute over ICI = the send/recv pair),
    inject the next microbatch at stage 0, then `vmap` the block over the
    stage dim — each pipe device computes exactly its stage.

`jax.grad` through the `lax.scan` of ticks yields the reverse schedule
(B after all F — GPipe). The bubble is the classic (S-1)/(T) fraction.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(per_stage_trees):
    """[tree_0, ..., tree_{S-1}] (identical structure) → tree with leaves
    stacked on a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_trees)


def unstack_stage_params(stacked, num_stages):
    return [jax.tree.map(lambda x, i=i: x[i], stacked)
            for i in range(num_stages)]


def pipeline_spec(spec_tree):
    """Prefix every PartitionSpec in a per-stage spec tree with 'pipe' for
    the stacked layout."""
    from jax.sharding import PartitionSpec as P
    return jax.tree.map(
        lambda s: P("pipe", *s) if s is not None else P("pipe"),
        spec_tree, is_leaf=lambda s: s is None or isinstance(s, tuple))


def _mb_key(base, i, s):
    """Per-(microbatch, stage) dropout key. Both schedules derive keys
    through this so gpipe and 1F1B draw IDENTICAL masks for the same
    (microbatch, stage) — loss parity between schedules is exact."""
    return jax.random.fold_in(jax.random.fold_in(base, i), s)


def gpipe(block_fn: Callable[[Any, Any], Any],
          stacked_params,
          microbatches,
          *,
          num_stages: int,
          remat: bool = False,
          rng_key=None):
    """Run the F-then-B pipeline forward.

    block_fn(stage_params, x) -> y : one stage's computation (same code for
    every stage — heterogeneous first/last layers, e.g. embedding/head,
    belong OUTSIDE the pipelined trunk, where GSPMD replicates them over
    the 'pipe' axis).

    microbatches: [M, mb, ...] input activation stream.
    With rng_key set, block_fn is called as block_fn(stage_params, x, key)
    with a distinct key per (microbatch, stage) — dropout masks decorrelate
    across ticks and stages (a plain closure draw would bake ONE mask into
    the scanned tick).
    Returns [M, mb, ...] outputs of the last stage, microbatch order
    preserved.
    """
    S = num_stages
    M = microbatches.shape[0]
    fn = jax.checkpoint(block_fn) if remat else block_fn
    sidx = jnp.arange(S)

    state = jnp.zeros((S,) + tuple(microbatches.shape[1:]),
                      microbatches.dtype)
    # pad the input stream with S-1 drain ticks
    pad = jnp.zeros((S - 1,) + tuple(microbatches.shape[1:]),
                    microbatches.dtype) if S > 1 else \
        jnp.zeros((0,) + tuple(microbatches.shape[1:]), microbatches.dtype)
    stream = jnp.concatenate([microbatches, pad], axis=0)

    def tick(state, xs):
        x_t, t = xs
        shifted = jnp.roll(state, 1, axis=0)          # CollectivePermute
        shifted = shifted.at[0].set(x_t)               # inject at stage 0
        if rng_key is None:
            y = jax.vmap(fn)(stacked_params, shifted)  # each device: 1 stage
        else:
            # microbatch index at stage s on tick t is i = t - s
            keys = jax.vmap(lambda s: _mb_key(rng_key, t - s, s))(sidx)
            y = jax.vmap(fn)(stacked_params, shifted, keys)
        return y, y[S - 1]                             # emit last stage

    _, outs = lax.scan(tick, state, (stream, jnp.arange(stream.shape[0])))
    return outs[S - 1:] if S > 1 else outs


def one_f_one_b(block_fn, stacked_params, microbatches, head_grad_fn,
                head_params, head_aux, *, num_stages: int, rng_key=None):
    """1F1B pipeline schedule: one combined forward+backward tick per scan
    step.

    TPU-native equivalent of the reference's `SectionWorker` 1F1B mode
    (`framework/section_worker.cc:144-156`): in steady state every stage
    runs one microbatch forward and one microbatch backward per tick, so
    the stashed-activation residency is bounded by the stash ring (depth
    2S-1 ticks) instead of growing with the number of microbatches M the
    way GPipe's B-after-all-F does.

    Mechanics (pure SPMD — the 'pipe' mesh axis shards the stage dim of
    every buffer; `jnp.roll` on that dim lowers to CollectivePermute):

      * forward: rolling activation buffer [S, mb, ...] as in `gpipe`;
        each tick's stage inputs are stashed into a circular ring
        [2S-1, S, mb, ...].
      * head: the microbatch leaving the last stage gets its loss AND
        loss-cotangent immediately via `head_grad_fn` — this is what
        makes B start S-1 ticks after F, not after all M forwards.
      * backward: a second rolling buffer carries cotangents toward
        stage 0; each stage recomputes its forward from the stashed
        input (`jax.vjp`, i.e. remat) and emits (dparams, dx).
        Invalid slots carry zero cotangents, and vjps are linear in the
        cotangent, so no per-stage masking is needed.

    Timeline: microbatch i is forward at stage s on tick i+s, backward at
    stage s on tick i + 2(S-1) - s; total ticks T = M + 2S - 2.

    Args:
      block_fn(stage_params, x) -> y: one stage's computation.
      stacked_params: stage-stacked param tree (leaves [S, ...]).
      microbatches: [M, mb, ...] stage-0 input stream.
      head_grad_fn(head_params, y_last, aux_t) -> (loss_t, dy_t, dhead_t):
        loss, its cotangent w.r.t. y_last, and head-param grads for ONE
        microbatch (caller seeds the vjp with its own scale, e.g. 1/M).
      head_params: pytree differentiated by head_grad_fn.
      head_aux: [M, ...] pytree of per-microbatch aux (labels, masks).

    Returns (loss_sum, dx_stream [M, mb, ...], d_stacked, d_head) where
    dx_stream holds the cotangents w.r.t. `microbatches` (feed them to the
    embedding vjp outside), in microbatch order.
    """
    S = num_stages
    M = microbatches.shape[0]
    T = M + 2 * S - 2
    D = 2 * S - 1            # stash ring depth: max retention 2(S-1) ticks
    mb_shape = tuple(microbatches.shape[1:])
    dtype = microbatches.dtype
    sidx = jnp.arange(S)

    # tick-aligned streams: x valid on ticks [0, M); head on [S-1, S-1+M)
    pad = jnp.zeros((S - 1,) + mb_shape, dtype)
    x_stream = jnp.concatenate([microbatches, pad, pad], 0)
    aux_stream = jax.tree.map(
        lambda a: jnp.concatenate(
            [jnp.zeros((S - 1,) + tuple(a.shape[1:]), a.dtype), a,
             jnp.zeros((S - 1,) + tuple(a.shape[1:]), a.dtype)], 0),
        head_aux)

    def stage_bwd(stage_p, x_saved, ct, *key):
        def f(sp, xs):
            return block_fn(sp, xs, *key)
        _, vjp_fn = jax.vjp(f, stage_p, x_saved)
        dp, dx = vjp_fn(ct)
        return dp, dx

    def tick(carry, xs):
        fwd, bwd, stash, gs, gh, loss_acc = carry
        t, x_t, aux_t = xs
        # ---- forward ----
        f_in = jnp.roll(fwd, 1, axis=0).at[0].set(x_t)
        stash = stash.at[t % D].set(f_in)
        if rng_key is None:
            y = jax.vmap(block_fn)(stacked_params, f_in)
        else:
            # stage s runs microbatch i = t - s forward on tick t
            keys_f = jax.vmap(lambda s: _mb_key(rng_key, t - s, s))(sidx)
            y = jax.vmap(block_fn)(stacked_params, f_in, keys_f)
        # ---- head: loss + cotangent for the mb leaving the last stage ----
        valid_h = jnp.logical_and(t >= S - 1, t <= S + M - 2)
        loss_t, dy_t, dh_t = head_grad_fn(head_params, y[S - 1], aux_t)
        loss_acc = loss_acc + jnp.where(valid_h, loss_t,
                                        0.0).astype(loss_acc.dtype)
        dy_t = jnp.where(valid_h, dy_t, jnp.zeros_like(dy_t))
        gh = jax.tree.map(
            lambda a, d: a + jnp.where(valid_h, d,
                                       jnp.zeros_like(d)).astype(a.dtype),
            gh, dh_t)
        # ---- backward ----
        b_in = jnp.roll(bwd, -1, axis=0).at[S - 1].set(
            dy_t.astype(dtype))
        read = stash[(t - 2 * (S - 1 - sidx)) % D, sidx]
        if rng_key is None:
            dps, dxs = jax.vmap(stage_bwd)(stacked_params, read, b_in)
        else:
            # recompute with the SAME key the forward of that microbatch
            # used: stage s backs up microbatch i = t - 2(S-1) + s here
            keys_b = jax.vmap(
                lambda s: _mb_key(rng_key, t - 2 * (S - 1) + s, s))(sidx)
            dps, dxs = jax.vmap(stage_bwd)(stacked_params, read, b_in,
                                           keys_b)
        gs = jax.tree.map(lambda a, d: a + d.astype(a.dtype), gs, dps)
        return (y, dxs, stash, gs, gh, loss_acc), dxs[0]

    carry0 = (
        jnp.zeros((S,) + mb_shape, dtype),           # fwd buffer
        jnp.zeros((S,) + mb_shape, dtype),           # bwd buffer
        jnp.zeros((D, S) + mb_shape, dtype),         # stash ring
        jax.tree.map(jnp.zeros_like, stacked_params),
        jax.tree.map(jnp.zeros_like, head_params),
        jnp.zeros((), jnp.float32),
    )
    xs = (jnp.arange(T), x_stream, aux_stream)
    (_, _, _, gs, gh, loss_sum), dx_ticks = lax.scan(tick, carry0, xs)
    dx_stream = dx_ticks[2 * S - 2:] if S > 1 else dx_ticks
    return loss_sum, dx_stream, gs, gh


def pipelined_apply(block_fn, stacked_params, x, *, num_stages: int,
                    num_microbatches: int, remat: bool = False,
                    rng_key=None):
    """Batch-level wrapper: split [B, ...] into M microbatches, pipeline,
    re-merge. Identity to `for each block: x = block(x)` (modulo fp
    reassociation) — tested against the sequential reference."""
    B = x.shape[0]
    M = num_microbatches
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = x.reshape((M, B // M) + tuple(x.shape[1:]))
    out = gpipe(block_fn, stacked_params, mb, num_stages=num_stages,
                remat=remat, rng_key=rng_key)
    return out.reshape((B,) + tuple(out.shape[2:]))
