"""GSPMD pipeline-parallel engine (GPipe / F-then-B schedule).

TPU-native replacement for the reference's pipeline runtime — static
`SectionWorker::TrainFiles` F-then-B / 1F1B schedules
(`framework/section_worker.cc:130-156`) and dygraph
`PipelineParallel.train_batch` (`meta_parallel/pipeline_parallel.py:109`)
with NCCL `send_v2/recv_v2` P2P between stages.

Mechanism: instead of per-stage processes exchanging tensors, the S
pipeline stages are expressed as ONE stacked computation:

  * per-stage block parameters are stacked on a leading dim of size S and
    sharded over the 'pipe' mesh axis — each pipe device materializes only
    its own stage's weights;
  * a rolling activation buffer [S, microbatch, ...], also 'pipe'-sharded,
    holds the in-flight microbatch of every stage;
  * each tick: shift the buffer one stage forward (`jnp.roll` on the
    sharded dim → XLA CollectivePermute over ICI = the send/recv pair),
    inject the next microbatch at stage 0, then `vmap` the block over the
    stage dim — each pipe device computes exactly its stage.

`jax.grad` through the `lax.scan` of ticks yields the reverse schedule
(B after all F — GPipe). The bubble is the classic (S-1)/(T) fraction.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(per_stage_trees):
    """[tree_0, ..., tree_{S-1}] (identical structure) → tree with leaves
    stacked on a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_trees)


def unstack_stage_params(stacked, num_stages):
    return [jax.tree.map(lambda x, i=i: x[i], stacked)
            for i in range(num_stages)]


def pipeline_spec(spec_tree):
    """Prefix every PartitionSpec in a per-stage spec tree with 'pipe' for
    the stacked layout."""
    from jax.sharding import PartitionSpec as P
    return jax.tree.map(
        lambda s: P("pipe", *s) if s is not None else P("pipe"),
        spec_tree, is_leaf=lambda s: s is None or isinstance(s, tuple))


def gpipe(block_fn: Callable[[Any, Any], Any],
          stacked_params,
          microbatches,
          *,
          num_stages: int,
          remat: bool = False):
    """Run the F-then-B pipeline forward.

    block_fn(stage_params, x) -> y : one stage's computation (same code for
    every stage — heterogeneous first/last layers, e.g. embedding/head,
    belong OUTSIDE the pipelined trunk, where GSPMD replicates them over
    the 'pipe' axis).

    microbatches: [M, mb, ...] input activation stream.
    Returns [M, mb, ...] outputs of the last stage, microbatch order
    preserved.
    """
    S = num_stages
    M = microbatches.shape[0]
    fn = jax.checkpoint(block_fn) if remat else block_fn

    state = jnp.zeros((S,) + tuple(microbatches.shape[1:]),
                      microbatches.dtype)
    # pad the input stream with S-1 drain ticks
    pad = jnp.zeros((S - 1,) + tuple(microbatches.shape[1:]),
                    microbatches.dtype) if S > 1 else \
        jnp.zeros((0,) + tuple(microbatches.shape[1:]), microbatches.dtype)
    stream = jnp.concatenate([microbatches, pad], axis=0)

    def tick(state, x_t):
        shifted = jnp.roll(state, 1, axis=0)          # CollectivePermute
        shifted = shifted.at[0].set(x_t)               # inject at stage 0
        y = jax.vmap(fn)(stacked_params, shifted)      # each device: 1 stage
        return y, y[S - 1]                             # emit last stage

    _, outs = lax.scan(tick, state, stream)
    return outs[S - 1:] if S > 1 else outs


def pipelined_apply(block_fn, stacked_params, x, *, num_stages: int,
                    num_microbatches: int, remat: bool = False):
    """Batch-level wrapper: split [B, ...] into M microbatches, pipeline,
    re-merge. Identity to `for each block: x = block(x)` (modulo fp
    reassociation) — tested against the sequential reference."""
    B = x.shape[0]
    M = num_microbatches
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = x.reshape((M, B // M) + tuple(x.shape[1:]))
    out = gpipe(block_fn, stacked_params, mb, num_stages=num_stages,
                remat=remat)
    return out.reshape((B,) + tuple(out.shape[2:]))
