"""ZeRO sharded-optimizer — TPU-native.

Reference:
  * dygraph ZeRO-1: `DygraphShardingOptimizer` greedy param partition by
    size + reduce-to-owner + post-step broadcast
    (`dygraph_sharding_optimizer.py:27,90,147`);
  * static ZeRO-2(+offload): `ShardingOptimizer`
    (`sharding_optimizer.py:87-1385`).

TPU mechanism: optimizer-state (and optionally gradient) tensors are placed
with a PartitionSpec over the 'sharding' mesh axis instead of being
physically scattered to owner ranks. XLA's partitioner then performs the
reduce-scatter of grads into the sharded update and the all-gather of fresh
params — exactly the ZeRO dataflow — as part of the one compiled step.
`shard_spec_for` implements the greedy largest-dim choice; states whose
shapes can't split evenly stay replicated (same fallback the reference
takes for odd-sized params).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...optimizer.optimizer import Optimizer
from ..topology import get_mesh_or_none


def shard_spec_for(shape, axis_size: int, axis: str = "sharding",
                   base_spec=None) -> P:
    """Pick the largest dim divisible by `axis_size` that isn't already
    sharded by `base_spec`; replicate if none qualifies."""
    base = tuple(base_spec) if base_spec else ()
    best, best_size = None, 0
    for i, d in enumerate(shape):
        taken = i < len(base) and base[i] is not None
        if not taken and d % axis_size == 0 and d >= axis_size \
                and d > best_size:
            best, best_size = i, d
    if best is None:
        return P(*base) if base else P()
    spec = list(base) + [None] * (len(shape) - len(base))
    spec[best] = axis
    return P(*spec)


def sharded_state_specs(params: Dict[str, jax.Array],
                        opt_state: Dict[str, Any],
                        param_specs: Optional[Dict[str, Any]] = None,
                        axis: str = "sharding") -> Dict[str, Any]:
    """PartitionSpec tree matching `Optimizer.init_state` output: every
    per-param slot gets the ZeRO spec; the step counter is replicated."""
    mesh = get_mesh_or_none()
    size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis] \
        if mesh is not None and axis in mesh.axis_names else 1
    specs: Dict[str, Any] = {"step": P(), "slots": {}}
    for name, slots in opt_state["slots"].items():
        base = (param_specs or {}).get(name)
        s = {}
        for sname, v in slots.items():
            if jnp.ndim(v) == 0:
                s[sname] = P()
            elif size > 1:
                s[sname] = shard_spec_for(v.shape, size, axis, base)
            else:
                s[sname] = P(*base) if base else P()
        specs["slots"][name] = s
    return specs


def place_sharded_state(opt_state, specs, memory_kind=None):
    """device_put the optimizer state per the spec tree (eager path).
    memory_kind="pinned_host" keeps slots resident in host memory (the
    reference's sharding offload, offload_helper.py)."""
    mesh = get_mesh_or_none()
    if mesh is None:
        return opt_state
    kw = {"memory_kind": memory_kind} if memory_kind else {}
    return jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s, **kw)),
        opt_state, specs,
        is_leaf=lambda v: isinstance(v, jax.Array) or isinstance(v, P))


class DygraphShardingOptimizer:
    """Reference: `dygraph_sharding_optimizer.py:27` — wraps an inner
    optimizer; state lives sharded over the 'sharding' axis.

    API parity: `step(grads)`, `minimize`, `state_dict` delegate to the
    inner optimizer; the wrapper's only job is placing the state shards
    (the reduce/broadcast of the reference collapses into GSPMD).
    """

    def __init__(self, hcg=None, user_defined_strategy=None,
                 params=None, inner_optimizer_class=None,
                 inner_opt: Optional[Optimizer] = None, offload=None,
                 **inner_kw):
        if inner_opt is None:
            inner_opt = inner_optimizer_class(parameters=params, **inner_kw)
        self._inner = inner_opt
        self._hcg = hcg
        self._placed = False
        if offload is None and user_defined_strategy is not None:
            offload = getattr(user_defined_strategy, "sharding_configs",
                              {}).get("offload", False)
        self._offload = bool(offload)
        self._specs = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _place(self):
        if self._placed:
            return
        self._inner._ensure_state()
        params = {n: p.value for n, p in self._inner._params.items()}
        pspecs = {n: getattr(p, "sharding_spec", None)
                  for n, p in self._inner._params.items()}
        specs = sharded_state_specs(params, self._inner._accumulators,
                                    pspecs)
        self._inner._accumulators = place_sharded_state(
            self._inner._accumulators, specs,
            memory_kind="pinned_host" if self._offload else None)
        self._specs = specs
        self._placed = True

    def _offload_roundtrip(self, run):
        """Stream slots host -> device for the update, then back —
        the eager-mode analogue of build_train_step(offload=True)."""
        self._inner._accumulators = place_sharded_state(
            self._inner._accumulators, self._specs)
        try:
            return run()
        finally:
            self._inner._accumulators = place_sharded_state(
                self._inner._accumulators, self._specs,
                memory_kind="pinned_host")

    def step(self, grads=None):
        self._place()
        if self._offload:
            return self._offload_roundtrip(lambda: self._inner.step(grads))
        return self._inner.step(grads)

    def minimize(self, loss_fn, *args):
        self._place()
        if self._offload:
            return self._offload_roundtrip(
                lambda: self._inner.minimize(loss_fn, *args))
        return self._inner.minimize(loss_fn, *args)
