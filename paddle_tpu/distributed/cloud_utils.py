"""Cluster bring-up helpers from cloud environment variables.

Reference: `python/paddle/distributed/cloud_utils.py` (PaddleCloud env →
cluster/pod objects for the launcher). TPU-native: the launcher contract
is plain env vars (`distributed/launch.py`), so these helpers parse the
same variables and return the endpoint layout.
"""
from __future__ import annotations

import os


def get_cluster_and_pod(args=None):
    """Parse PADDLE_* env into (trainer_endpoints, current_endpoint,
    rank, world_size) — the pieces the reference's cluster/pod carry."""
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
    endpoints = [e for e in endpoints if e]
    current = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                             endpoints[0] if endpoints else "")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               str(max(len(endpoints), 1))))
    return endpoints, current, rank, world


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=None, selected_devices=None):
    return get_cluster_and_pod()


def use_paddlecloud() -> bool:
    return os.environ.get("PADDLE_RUNNING_ENV") == "PADDLE_CLOUD"
