"""`paddle.distributed` equivalent namespace.

The reference's four comm stacks (NCCL/BKCL/HCCL/Gloo + brpc PS) collapse
into XLA collectives over a `jax.sharding.Mesh` (ICI/DCN) plus the jax
coordination service for bootstrap. See SURVEY.md §5 "Distributed
communication backend".
"""
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all_single,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    p2p_push,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    build_mesh,
    get_hybrid_communicate_group,
    get_mesh,
    named_sharding,
    set_mesh,
)
from .parallel import DataParallel  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import fleet  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import cloud_utils  # noqa: F401
from . import utils  # noqa: F401
from .entry_attr import CountFilterEntry, ProbabilityEntry  # noqa: F401
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401


class BoxPSDataset(InMemoryDataset):
    """Reference: fleet/dataset/dataset.py BoxPSDataset — the BoxPS
    (GPU-accelerated PS) variant of InMemoryDataset. The TPU stack has one
    memory hierarchy, so this is InMemoryDataset plus the BoxPS method
    surface (begin/end_pass, wait preload)."""

    def begin_pass(self):
        pass

    def end_pass(self, need_save_delta=False):
        pass

    def wait_preload_done(self):
        pass

    def preload_into_memory(self):
        self.load_into_memory()


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference: `paddle.distributed.split` (collective.py:1282) — model-
    parallel embedding / row-linear / column-linear over num_partitions.

    TPU-native: delegates to the GSPMD mp layers
    (`meta_parallel/mp_layers.py`) over the current mesh's model axis —
    the mesh partitioner handles the sharding the reference does by hand.
    Creates the parallel layer and applies it (parameters are created per
    call, like the reference's functional form); prefer the layer classes
    for repeated use.
    """
    from .meta_parallel.mp_layers import (ColumnParallelLinear,
                                          RowParallelLinear,
                                          VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"unsupported operation {operation!r}: expected "
                         "'linear' or 'embedding'")
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=False)
    elif axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out)
    else:
        raise ValueError(f"axis must be 0 or 1, got {axis}")
    return layer(x)
