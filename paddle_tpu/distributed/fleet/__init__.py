"""`paddle.distributed.fleet` equivalent."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    Fleet,
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
    is_first_worker,
    worker_index,
    worker_num,
)
from ..topology import HybridCommunicateGroup  # noqa: F401
from .recompute import recompute  # noqa: F401
from ..random import get_rng_state_tracker  # noqa: F401
from . import elastic  # noqa: F401
from . import utils  # noqa: F401
from .dataset import (DatasetBase, InMemoryDataset,  # noqa: F401
                      QueueDataset, train_from_dataset)
from ..topology import CommunicateTopology  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
)


class UtilBase:
    """Reference: fleet/base/util_factory.py UtilBase — cross-rank helper
    ops (all_reduce/barrier over the CPU rendezvous) + filesystem hooks.
    Here collectives ride `distributed.collective` (jax.distributed CPU
    backend, the Gloo replacement) and fs is the fleet FS abstraction."""

    def __init__(self):
        from .utils.fs import LocalFS
        self.fs_client = LocalFS()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import jax.numpy as jnp
        import numpy as np
        from .. import collective
        ops = {"sum": collective.ReduceOp.SUM,
               "max": collective.ReduceOp.MAX,
               "min": collective.ReduceOp.MIN}
        if mode not in ops:
            raise ValueError(f"all_reduce mode must be one of {set(ops)},"
                             f" got {mode!r}")
        out = collective.all_reduce(jnp.asarray(input), op=ops[mode])
        return np.asarray(out)

    def barrier(self, comm_world="worker"):
        from .. import collective
        collective.barrier()

    def all_gather(self, input, comm_world="worker"):
        """Returns a list with one entry per rank (eager collectives are
        identity in a one-process world — see distributed/collective.py;
        inside compiled steps use collective.all_gather directly)."""
        import numpy as np
        from .fleet_base import worker_num
        return [np.asarray(input)] * max(worker_num(), 1)

    def get_file_shard(self, files):
        """Shard a file list over workers (reference: util_factory
        get_file_shard)."""
        from .fleet_base import worker_index, worker_num
        n, i = worker_num(), worker_index()
        return [f for j, f in enumerate(files) if j % n == i]
