"""`paddle.distributed.fleet` equivalent."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    Fleet,
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
    is_first_worker,
    worker_index,
    worker_num,
)
from ..topology import HybridCommunicateGroup  # noqa: F401
from .recompute import recompute  # noqa: F401
from ..random import get_rng_state_tracker  # noqa: F401
from . import elastic  # noqa: F401
from . import utils  # noqa: F401
from .dataset import (DatasetBase, InMemoryDataset,  # noqa: F401
                      QueueDataset, train_from_dataset)
from ..topology import CommunicateTopology  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
)


class UtilBase:
    """Reference: fleet/base/util_factory.py UtilBase — cross-rank helper
    ops (all_reduce/barrier over the CPU rendezvous) + filesystem hooks.
    Here collectives ride `distributed.collective` (jax.distributed CPU
    backend, the Gloo replacement) and fs is the fleet FS abstraction."""

    def __init__(self):
        from .utils.fs import LocalFS
        self.fs_client = LocalFS()

    _ar_seq = 0

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """Host-side cross-rank reduce. Single process: identity (a
        world of one). Multi-process: rides the PS KV store + barrier
        (the repo's Gloo replacement) — NOT the eager XLA collectives,
        which raise outside a trace in multi-process jobs precisely
        because they cannot communicate there."""
        import numpy as np
        reducers = {"sum": lambda a: a.sum(axis=0),
                    "max": lambda a: a.max(axis=0),
                    "min": lambda a: a.min(axis=0)}
        if mode not in reducers:
            raise ValueError(f"all_reduce mode must be one of "
                             f"{set(reducers)}, got {mode!r}")
        arr = np.asarray(input)
        from .fleet_base import worker_num
        if max(worker_num(), 1) <= 1:
            return arr
        from ..ps import wire
        from ..ps.table import init_table_service
        svc = init_table_service()
        seq = UtilBase._ar_seq
        UtilBase._ar_seq += 1
        prefix = f"__util_allreduce__/{seq}/"
        svc.kv_put(prefix + str(svc.rank), wire.dumps(arr))
        svc.barrier(f"util-allreduce/{seq}")
        vals = [wire.loads(v)
                for _, v in sorted(svc.kv_prefix(prefix).items())]
        out = reducers[mode](np.stack(vals))
        # all ranks have read before anyone cleans its key up
        svc.barrier(f"util-allreduce-exit/{seq}")
        svc.kv_del(prefix + str(svc.rank))
        return out

    def barrier(self, comm_world="worker"):
        from .. import collective
        collective.barrier()

    def all_gather(self, input, comm_world="worker"):
        """Returns a list with one entry per rank (eager collectives are
        identity in a one-process world — see distributed/collective.py;
        inside compiled steps use collective.all_gather directly)."""
        import numpy as np
        from .fleet_base import worker_num
        return [np.asarray(input)] * max(worker_num(), 1)

    def get_file_shard(self, files):
        """Shard a file list over workers (reference: util_factory
        get_file_shard)."""
        from .fleet_base import worker_index, worker_num
        n, i = worker_num(), worker_index()
        return [f for j, f in enumerate(files) if j % n == i]
