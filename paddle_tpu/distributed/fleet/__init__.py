"""`paddle.distributed.fleet` equivalent."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    Fleet,
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
    is_first_worker,
    worker_index,
    worker_num,
)
from ..topology import HybridCommunicateGroup  # noqa: F401
from .recompute import recompute  # noqa: F401
from ..random import get_rng_state_tracker  # noqa: F401
from . import elastic  # noqa: F401
from . import utils  # noqa: F401
from .dataset import (DatasetBase, InMemoryDataset,  # noqa: F401
                      QueueDataset, train_from_dataset)
