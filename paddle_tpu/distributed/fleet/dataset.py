"""Fleet datasets: InMemoryDataset / QueueDataset + the epoch driver.

Reference mapping:
  * `DatasetImpl::LoadIntoMemory` / `LocalShuffle` / `GlobalShuffle`
    (`paddle/fluid/framework/data_set.h:101`) — C++ record store fed by
    MultiSlotDataFeed parsing slot text files (`data_feed.h:120`);
  * Python wrappers `fleet/dataset/dataset.py:24,253`
    (DatasetBase/InMemoryDataset/QueueDataset);
  * `Executor::RunFromDataset` + Trainer/DeviceWorker
    (`framework/trainer.h:57-292`, `executor.cc:152`) — the epoch driver.

TPU-native shape: records are host-side numpy structures (the device step
is one compiled function — there is no per-op DeviceWorker to mirror), and
GlobalShuffle rides the PS TCP service (`..ps.table.TableService`) the way
the reference rides brpc. The driver (`train_from_dataset`) feeds batches
to a user step callable — the jitted train step IS the trainer thread.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def _native_parse_numeric(path: str):
    """Bulk-parse a plain numeric slot file through the C++ runtime
    (reference: MultiSlotDataFeed's native parse loop,
    `framework/data_feed.cc` — Python tokenization is the LoadIntoMemory
    bottleneck). Returns a list of per-line float32 arrays, or None when
    the native lib is unavailable or the file isn't plain numeric
    (slot-name syntax etc. — caller falls back to the Python parser)."""
    import ctypes

    from ...core import native
    if not native.available():
        return None
    lib = native.lib()
    if not getattr(lib, "_ptpu_has_feed", False):
        return None          # stale prebuilt .so without the feed symbols
    # single allocation: file bytes + trailing NUL (strtof needs it)
    size = os.path.getsize(path)
    ba = bytearray(size + 1)
    with open(path, "rb") as f:
        f.readinto(memoryview(ba)[:size])
    if b":" in ba:           # named-slot format: python parser handles it
        return None
    cbuf = (ctypes.c_char * len(ba)).from_buffer(ba)
    n_vals = ctypes.c_int64()
    n_lines = ctypes.c_int64()
    if lib.ptpu_feed_count(cbuf, size, ctypes.byref(n_vals),
                           ctypes.byref(n_lines)) != 0:
        return None
    vals = np.empty(n_vals.value, np.float32)
    starts = np.empty(n_lines.value + 1, np.int64)
    parsed = ctypes.c_int64()
    rc = lib.ptpu_feed_parse(
        ctypes.cast(cbuf, ctypes.c_void_p), size,
        vals.ctypes.data_as(ctypes.c_void_p), n_vals.value,
        starts.ctypes.data_as(ctypes.c_void_p), n_lines.value,
        ctypes.byref(parsed))
    # STRICT count verification: an early stop (embedded NUL, locale
    # surprises) must fall back to the python parser rather than hand
    # back records spanning uninitialized memory
    if rc != n_lines.value or parsed.value != n_vals.value:
        return None
    starts[rc] = n_vals.value
    return [vals[starts[i]:starts[i + 1]] for i in range(rc)]


def _default_parse(line: str):
    """Default slot parser: whitespace-separated `name:v1,v2,...` slots or
    plain numbers (one record per line)."""
    line = line.strip()
    if not line:
        return None
    if ":" in line:
        rec = {}
        for tok in line.split():
            name, _, vals = tok.partition(":")
            rec[name] = np.array([float(v) for v in vals.split(",") if v],
                                 np.float32)
        return rec
    # commas are separators like whitespace (matches the native parser)
    vals = [float(v) for v in line.replace(",", " ").split()]
    # separator-only lines produce no record on EITHER parser path
    return np.array(vals, np.float32) if vals else None


class DatasetBase:
    """Reference: `fleet/dataset/dataset.py:24 DatasetBase`."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_var: List[str] = []
        self.pipe_command = "cat"
        self.parse_fn: Callable = _default_parse
        self._seed = 0

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kw):
        self.batch_size = batch_size
        self.thread_num = thread_num
        self.use_var = use_var or []
        self.pipe_command = pipe_command
        return self

    # reference setters (set_* API parity)
    def set_batch_size(self, b):
        self.batch_size = b

    def set_thread(self, t):
        self.thread_num = t

    def set_filelist(self, files: Sequence[str]):
        self.filelist = list(files)

    def set_use_var(self, var_list):
        self.use_var = list(var_list)

    def set_pipe_command(self, cmd):
        self.pipe_command = cmd

    def set_parse_ins(self, fn: Callable):
        """TPU-native replacement for the C++ DataFeed parser plugins."""
        self.parse_fn = fn

    # bulk native parsing is for load-into-memory datasets; streaming
    # datasets (QueueDataset) keep the O(1)-memory line path
    _bulk_native = False

    def _read_lines(self, path: str):
        if self._bulk_native and self.parse_fn is _default_parse:
            recs = _native_parse_numeric(path)
            if recs is not None:
                yield from recs
                return
        with open(path, "r") as f:
            for line in f:
                rec = self.parse_fn(line)
                if rec is not None:
                    yield rec


class InMemoryDataset(DatasetBase):
    """Reference: `DatasetImpl` with `LoadIntoMemory`/`GlobalShuffle`
    (`data_set.h:101`); Python `fleet/dataset/dataset.py:253`."""

    _bulk_native = True    # LoadIntoMemory wants the C++ parse hot path

    def __init__(self):
        super().__init__()
        self._records: List = []
        self._loaded = False

    # -- loading ----------------------------------------------------------

    def load_into_memory(self):
        """Parse the rank's filelist into host memory. With a launcher
        world, each rank loads its own (disjoint) filelist slice exactly
        like the reference's per-node file assignment."""
        self._records = []
        for path in self.filelist:
            self._records.extend(self._read_lines(path))
        self._loaded = True

    def set_sample_list(self, samples: Sequence):
        """Directly install records (tests / in-process producers)."""
        self._records = list(samples)
        self._loaded = True

    # -- shuffle ----------------------------------------------------------

    def local_shuffle(self, seed: Optional[int] = None):
        rs = np.random.RandomState(self._seed if seed is None else seed)
        rs.shuffle(self._records)
        self._seed += 1

    def global_shuffle(self, fleet=None, thread_num: int = 12,
                       seed: Optional[int] = None):
        """Cross-rank repartition + shuffle (reference:
        `DatasetImpl::GlobalShuffle` exchanging records over brpc).

        Every record is assigned a uniformly random target rank; records
        ship over the PS TCP service; each rank locally shuffles what it
        received. Single-process (no service/world=1) degrades to
        local_shuffle like the reference does.
        """
        from ..ps.table import init_table_service
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if world <= 1:
            self.local_shuffle(seed)
            return
        svc = init_table_service()
        rank = svc.rank
        rs = np.random.RandomState(
            (self._seed if seed is None else seed) * 7919 + rank)
        targets = rs.randint(0, world, size=len(self._records))
        per_target: Dict[int, list] = {}
        for rec, t in zip(self._records, targets):
            per_target.setdefault(int(t), []).append(rec)
        try:
            self._records = svc.exchange_records(per_target,
                                                 tag=f"ds{self._seed}")
        except TypeError as e:
            # the PS wire moves DATA (arrays/scalars/str/bytes/
            # lists/tuples/dicts), never pickled objects; custom record
            # classes from set_parse_ins must be converted to tuples of
            # arrays before a multi-rank global_shuffle
            raise TypeError(
                "global_shuffle records must be wire-encodable data "
                "(tuples/lists of numpy arrays, scalars, str/bytes) — "
                f"{e}") from e
        self.local_shuffle(seed)

    # -- sizes ------------------------------------------------------------

    def get_memory_data_size(self, fleet=None) -> int:
        """Local record count; with fleet/world>1, the GLOBAL count
        (reference: returns allreduced size)."""
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if fleet is None or world <= 1:
            return len(self._records)
        from ..ps.table import init_table_service
        svc = init_table_service()
        svc.kv_put(f"__dsize__/{svc.rank}", str(len(self._records)).encode())
        svc.barrier("dsize")
        sizes = svc.kv_prefix("__dsize__/")
        return sum(int(v.decode()) for v in sizes.values())

    get_shuffle_data_size = get_memory_data_size

    def release_memory(self):
        self._records = []
        self._loaded = False

    # -- iteration --------------------------------------------------------

    def __len__(self):
        return len(self._records)

    def batch_iter(self, drop_last: bool = False):
        n = len(self._records)
        bs = self.batch_size
        end = (n // bs) * bs if drop_last else n
        for i in range(0, end, bs):
            yield self._records[i:i + bs]

    def __iter__(self):
        return self.batch_iter()


class QueueDataset(DatasetBase):
    """Streaming dataset: no LoadIntoMemory; files are read on the fly
    (reference: `QueueDataset` / MultiSlotDataFeed streaming mode)."""

    def batch_iter(self, drop_last: bool = False):
        batch = []
        for path in self.filelist:
            for rec in self._read_lines(path):
                batch.append(rec)
                if len(batch) == self.batch_size:
                    yield batch
                    batch = []
        if batch and not drop_last:
            yield batch

    def __iter__(self):
        return self.batch_iter()


def train_from_dataset(step_fn: Callable, dataset,
                       epochs: int = 1,
                       collate_fn: Optional[Callable] = None,
                       print_period: int = 100,
                       debug: bool = False):
    """Epoch driver (reference: `Executor.train_from_dataset` →
    `Executor::RunFromDataset` spinning DeviceWorkers, `executor.cc:152`).

    TPU-native: the compiled `step_fn(batch) -> loss/metrics` IS the
    device worker; this loop is the Trainer. Returns the list of per-epoch
    mean losses (floats) for anything step_fn returns that is castable.
    """
    epoch_means = []
    for ep in range(epochs):
        losses = []
        for i, batch in enumerate(dataset.batch_iter()):
            if collate_fn is not None:
                batch = collate_fn(batch)
            out = step_fn(batch)
            try:
                losses.append(float(np.asarray(out).mean()))
            except (TypeError, ValueError):
                pass
            if debug and print_period and (i + 1) % print_period == 0:
                print(f"epoch {ep} step {i + 1}: "
                      f"loss={losses[-1] if losses else 'n/a'}")
        epoch_means.append(float(np.mean(losses)) if losses else 0.0)
    return epoch_means
