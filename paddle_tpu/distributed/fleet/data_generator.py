"""Data generators for the fleet dataset pipeline.

Reference: `python/paddle/distributed/fleet/data_generator/
data_generator.py` — user subclasses override `generate_sample`; the
base class renders samples into the slot line format the DataFeed parser
consumes (`count v1 v2 ...` per slot, slots in declaration order —
the plain-numeric layout the native C++ parser hot path reads). The native C++ parser here is
`csrc` `ptpu_feed_*` (see `distributed/fleet/dataset.py`).
"""
from __future__ import annotations

import sys


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a no-arg iterator yielding
        [(slot_name, [values...]), ...] per sample."""
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample")

    def generate_batch(self, samples):
        """Override for batch-level transforms (shuffle/pad): receives
        the accumulated samples of one batch, returns a no-arg iterator
        over (possibly rewritten) samples. Default: pass-through."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        for out in self._batched(
                item for line in sys.stdin
                for item in self.generate_sample(line)()):
            sys.stdout.write(out)

    def run_from_memory(self):
        return list(self._batched(self.generate_sample(None)()))

    def _batched(self, sample_iter):
        """Group samples into batches of `batch_size_`, route each group
        through generate_batch (the reference pipeline), format lines."""
        buf = []
        for item in sample_iter:
            buf.append(item)
            if len(buf) == self.batch_size_:
                for s in self.generate_batch(buf)():
                    yield self._gen_str(s)
                buf = []
        if buf:
            for s in self.generate_batch(buf)():
                yield self._gen_str(s)


class MultiSlotDataGenerator(DataGenerator):
    """Reference: MultiSlotDataGenerator._gen_str — slot lines
    `count v1 v2 ... count v1 ...` with a fixed slot order."""

    def _gen_str(self, item):
        parts = []
        for _name, values in item:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """Reference: MultiSlotStringDataGenerator — values pass through as
    strings (ids already tokenized upstream); same line format."""
