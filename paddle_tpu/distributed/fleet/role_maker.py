"""Role makers — who am I in the cluster.

Reference: `python/paddle/distributed/fleet/base/role_maker.py`
(PaddleCloudRoleMaker parses the launcher/PaddleCloud env into
worker/server roles; UserDefinedRoleMaker takes them explicitly). The
launcher env contract here is `distributed/launch.py` (PADDLE_* vars) and
PS roles come from the table-service env (`distributed/ps`).
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_num = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    # -- identity
    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def role_id(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return self._worker_num

    def server_num(self) -> int:
        return self._server_num

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def to_string(self):
        return (f"role={self._role} id={self._current_id} "
                f"workers={self._worker_num} servers={self._server_num}")


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parse the launcher env (reference: role_maker.py
    `PaddleCloudRoleMaker._ps_env`/`_collective_env`)."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        env = os.environ
        self._worker_endpoints = [
            e for e in env.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
            if e]
        self._server_endpoints = [
            e for e in env.get("PADDLE_PSERVERS_IP_PORT_LIST",
                               "").split(",") if e]
        self._worker_num = int(env.get(
            "PADDLE_TRAINERS_NUM", str(max(len(self._worker_endpoints),
                                           1))))
        self._server_num = len(self._server_endpoints)
        training_role = env.get("TRAINING_ROLE", "TRAINER")
        if training_role == "PSERVER":
            self._role = Role.SERVER
            # reference contract: derive the server index from
            # POD_IP:PADDLE_PORT against the pserver endpoint list;
            # PADDLE_PSERVER_ID (this repo's launcher contract) wins
            # when set explicitly
            if "PADDLE_PSERVER_ID" in env:
                self._current_id = int(env["PADDLE_PSERVER_ID"])
            else:
                cur = (f"{env.get('POD_IP', '127.0.0.1')}:"
                       f"{env.get('PADDLE_PORT', '')}")
                if cur in self._server_endpoints:
                    self._current_id = self._server_endpoints.index(cur)
                elif len(self._server_endpoints) <= 1:
                    self._current_id = 0
                else:
                    raise ValueError(
                        f"cannot locate this server ({cur!r}) in "
                        f"PADDLE_PSERVERS_IP_PORT_LIST="
                        f"{self._server_endpoints}; set POD_IP/"
                        "PADDLE_PORT or PADDLE_PSERVER_ID")
        elif training_role == "HETER_TRAINER":
            self._role = Role.HETER_WORKER
            self._current_id = int(env.get("PADDLE_TRAINER_ID", "0"))
        else:
            self._role = Role.WORKER
            self._current_id = int(env.get("PADDLE_TRAINER_ID", "0"))


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit roles (reference: role_maker.py UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__()
        self._role = kwargs.get("role", Role.WORKER)
        self._current_id = kwargs.get("current_id", 0)
        self._worker_endpoints = list(kwargs.get("worker_endpoints", []))
        self._server_endpoints = list(kwargs.get("server_endpoints", []))
        self._worker_num = kwargs.get("worker_num",
                                      max(len(self._worker_endpoints), 1))
        self._server_num = kwargs.get("server_num",
                                      len(self._server_endpoints))
