"""Elastic training manager (reference: `fleet/elastic.py:90` —
`ElasticManager` registers nodes in etcd3, watches membership, and
relaunches `paddle.distributed.launch` on scale events; fault-tolerance
level via PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL).

TPU-native reality: slice membership is fixed by the TPU runtime — scale
events mean re-acquiring a slice and restarting from auto-checkpoint
(incubate/checkpoint.py), which jax.distributed detects as coordinator
loss. This manager keeps the reference's state machine (register/watch/
exit codes) over a pluggable KV store: etcd3 when importable, else a
local-file store (single-host tests and the common TPU case where the
platform's own scheduler handles replacement).
"""
from __future__ import annotations

import json
import os
import signal
import time
from typing import Callable, List, Optional

ELASTIC_EXIT_CODE = 101


class _FileKV:
    """Local-file fallback store with the tiny subset of etcd3 used."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key: str, value: bytes, lease=None):
        p = os.path.join(self.root, key.replace("/", "__"))
        with open(p, "wb") as f:
            f.write(value)

    def get_prefix(self, prefix: str):
        out = []
        pfx = prefix.replace("/", "__")
        for fn in os.listdir(self.root):
            if fn.startswith(pfx):
                with open(os.path.join(self.root, fn), "rb") as f:
                    out.append((f.read(), type("M", (), {
                        "key": fn.replace("__", "/").encode()})()))
        return out

    def delete(self, key: str):
        p = os.path.join(self.root, key.replace("/", "__"))
        if os.path.exists(p):
            os.remove(p)


class _TCPKV:
    """Multi-node KV over the PS TCP table service (rank 0 hosts the
    store) — the etcd3-equivalent for launcher worlds where etcd isn't
    deployed. Reference analogue: gloo HTTP-KV rendezvous
    (`parallel.py:48,150`); fixes the r2 single-node _FileKV limitation."""

    def __init__(self):
        from ..ps.table import init_table_service
        self._svc = init_table_service()

    def put(self, key: str, value: bytes, lease=None):
        self._svc.kv_put(key, value)

    def get_prefix(self, prefix: str):
        out = []
        for k, v in self._svc.kv_prefix(prefix).items():
            out.append((v, type("M", (), {"key": k.encode()})()))
        return out

    def delete(self, key: str):
        self._svc.kv_del(key)


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Reference: elastic.py:90."""

    def __init__(self, args=None, etcd_client=None):
        server = os.environ.get("PADDLE_ELASTIC_SERVER")
        self.job_id = os.environ.get("PADDLE_ELASTIC_JOB_ID", "default")
        self.np = int(os.environ.get("PADDLE_ELASTIC_NP", "1"))
        self.host = os.environ.get("POD_IP", "127.0.0.1")
        self.fault_tolerance_level = int(
            os.environ.get("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "0"))
        flag = os.environ.get("PADDLE_ELASTIC_ENABLE", "").lower()
        self.enable = bool(server) or flag in ("1", "true", "yes", "on")
        self._etcd = None
        if etcd_client is not None:
            self._etcd = etcd_client
        elif server:
            try:
                import etcd3
                h, p = server.split(":")
                self._etcd = etcd3.client(host=h, port=int(p))
            except ImportError:
                self._etcd = _FileKV(
                    f"/tmp/paddle_tpu_elastic/{self.job_id}")
        self.prefix = f"/paddle/{self.job_id}"
        self.stopped = False
        self._watches: List[Callable] = []

    @property
    def etcd(self):
        """KV store, created LAZILY on first use: a disabled manager must
        not bind ports or spin service threads as a construction side
        effect. Launcher worlds without etcd get the PS-TCP KV (reaches
        every node via the endpoint list); otherwise the local file
        store."""
        if self._etcd is None:
            if int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
                self._etcd = _TCPKV()
            else:
                self._etcd = _FileKV(
                    f"/tmp/paddle_tpu_elastic/{self.job_id}")
        return self._etcd

    # --- membership -------------------------------------------------
    # node key includes the PID so several workers per host stay distinct;
    # entries carry a heartbeat time and go stale after _TTL seconds
    # (the file store has no leases — etcd3 expiry is emulated by
    # filtering on read)
    _TTL = 60.0

    def _node_key(self):
        return f"{self.prefix}/nodes/{self.host}-{os.getpid()}"

    def register(self):
        if not self.enable:
            return
        self.etcd.put(self._node_key(), json.dumps(
            {"host": self.host, "time": time.time()}).encode())

    def nodes(self) -> List[str]:
        out = []
        now = time.time()
        for val, meta in self.etcd.get_prefix(f"{self.prefix}/nodes"):
            rec = json.loads(val.decode())
            if now - rec.get("time", now) <= self._TTL:
                out.append(rec["host"])
        return sorted(out)

    def exit(self, completed=False):
        self.stopped = True
        self.etcd.delete(self._node_key())

    # --- health → status machine (reference: elastic.py watch loop) --
    def wait(self):
        if not self.enable:
            return
        while not self.stopped:
            self.register()  # refresh heartbeat — emulates etcd lease keepalive
            n = len(self.nodes())
            if n >= self.np:
                return
            time.sleep(1)

    def watch(self, procs_alive: Callable[[], bool]) -> str:
        """Poll children + membership; returns an ElasticStatus."""
        if not self.enable:
            return ElasticStatus.HOLD if procs_alive() \
                else ElasticStatus.COMPLETED
        # re-put the node key with a fresh timestamp on every poll so a
        # healthy job running past _TTL never loses its own membership
        # entry (reference refreshes via the etcd lease keepalive thread,
        # fleet/elastic.py:125-164)
        self.register()
        if not procs_alive():
            return ElasticStatus.COMPLETED
        if len(self.nodes()) != self.np:
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def signal_handler(self, sigint, frame):
        self.exit()
        self.stopped = True
