"""fleet.utils (reference: python/paddle/distributed/fleet/utils/)."""
from ..recompute import recompute  # noqa: F401
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
from .hybrid_parallel_util import (  # noqa: F401
    broadcast_dp_parameters,
    broadcast_input_data,
    broadcast_mp_parameters,
    fused_allreduce_gradients,
)


class DistributedInfer:
    """Reference: fleet/utils/ps_util.py DistributedInfer — run inference
    against the PS sparse tables: pull the latest rows for the ids the
    pass touches, run locally. Table transport: `distributed/ps`."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        pass  # params live with the program / PS tables already

    def get_dist_infer_program(self):
        if self._main is None:
            from ....static import default_main_program
            return default_main_program()
        return self._main
