"""Gradient-compression / communication meta-optimizers.

Reference (`python/paddle/distributed/fleet/meta_optimizers/`):
  * `dgc_optimizer.py` + `operators/dgc_op.cc` — Deep Gradient
    Compression: top-k sparsification with momentum correction and
    error feedback (Lin et al. 2018);
  * `localsgd_optimizer.py` — local steps + periodic parameter
    averaging;
  * `fp16_allreduce_optimizer.py` — cast grads to fp16 for the
    allreduce, restore after.

TPU-native shape: these are *pure transforms* around any inner
`Optimizer`, not program rewrites.

  * DGC keeps (velocity u, error residual v) per param; per step it
    returns the sparsified "sent" gradient and the updated state.
    Semantics (convergence behavior, error feedback) are exactly the
    reference's; on ICI the bandwidth saving would additionally need a
    sparse collective, which XLA does not expose — the transform is
    still the right building block (and the masked grads compress
    losslessly in fp16/int schemes stacked on top).
  * LocalSGD runs W logically-diverging model replicas as a stacked
    leading dim (shard it over 'data' on a mesh: each worker owns its
    slice), vmaps the inner update, and averages every `k_steps` —
    `lax.cond`-gated so the whole loop stays one compiled program.
  * fp16_allreduce casts grads through fp16 (or bf16) — inside a
    compiled DP step this pins the reduction operand dtype, which IS
    the bandwidth saving on ICI.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer


# ---------------------------------------------------------------------------
# DGC
# ---------------------------------------------------------------------------

class DGCMomentumOptimizer:
    """Reference `DGCMomentumOptimizer` (`dgc_optimizer.py`,
    `fluid/optimizer.py:1452`).

    Usage (functional):
        dgc = DGCMomentumOptimizer(inner, momentum=0.9,
                                   rampup_begin_step=0, sparsity=0.999)
        state = dgc.init_state(params)               # inner + dgc slots
        sent, state = dgc.compress(grads, state)     # sparsified grads
        params, state = dgc.apply(params, sent, state)
    """

    def __init__(self, inner: Optimizer, momentum: float = 0.9,
                 rampup_begin_step: int = 0,
                 sparsity: float = 0.999):
        self._inner = inner
        self.momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self.sparsity = float(sparsity)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        st = self._inner.init_state(params)
        st["dgc"] = {
            "u": {n: jnp.zeros_like(p) for n, p in params.items()},
            "v": {n: jnp.zeros_like(p) for n, p in params.items()},
            "k": jnp.zeros((), jnp.int32),
        }
        return st

    def compress(self, grads: Dict[str, jax.Array], state: Dict[str, Any]
                 ) -> Tuple[Dict[str, jax.Array], Dict[str, Any]]:
        """One DGC round: returns (sent_grads, new_state). Pure / jits."""
        dgc = state["dgc"]
        k = dgc["k"] + 1
        ramped = k > self.rampup_begin_step
        new_u, new_v, sent = {}, {}, {}
        for n, g in grads.items():
            u = self.momentum * dgc["u"][n] + g       # momentum correction
            v = dgc["v"][n] + u                        # error feedback acc
            if jnp.ndim(v) == 0:
                thr = jnp.zeros((), v.dtype)
            else:
                thr = jnp.quantile(jnp.abs(v).astype(jnp.float32).ravel(),
                                   self.sparsity).astype(v.dtype)
            mask = jnp.abs(v) >= thr
            mask = jnp.logical_or(mask, jnp.logical_not(ramped))
            s = jnp.where(mask, v, 0)
            sent[n] = s
            new_v[n] = jnp.where(mask, 0, v)
            new_u[n] = jnp.where(mask, 0, u)
        out = dict(state)
        out["dgc"] = {"u": new_u, "v": new_v, "k": k}
        return sent, out

    def apply(self, params, sent_grads, state):
        """Inner update on the sent (sparsified) grads. The DP allreduce
        of `sent` happens wherever the caller's step reduces grads."""
        dgc = state["dgc"]
        inner_st = {k: v for k, v in state.items() if k != "dgc"}
        new_params, new_inner = self._inner.apply(params, sent_grads,
                                                  inner_st)
        new_inner["dgc"] = dgc
        return new_params, new_inner

    def step_fn(self, params, grads, state):
        """compress + apply in one call (drop-in for Optimizer.apply)."""
        sent, state = self.compress(grads, state)
        return self.apply(params, sent, state)


# ---------------------------------------------------------------------------
# LocalSGD
# ---------------------------------------------------------------------------

class LocalSGDOptimizer:
    """Reference `LocalSGDOptimizer` (`localsgd_optimizer.py`): every
    worker takes `k_steps` local optimizer steps, then parameters are
    averaged across workers.

    Functional form over STACKED replicas: params/grads carry a leading
    worker dim [W, ...] (shard it over 'data' on a mesh — the average is
    then an ICI all-reduce). `apply` vmaps the inner update and
    `lax.cond`-averages when step % k_steps == 0."""

    def __init__(self, inner: Optimizer, k_steps: int = 4):
        self._inner = inner
        self.k_steps = int(k_steps)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def stack_params(self, params: Dict[str, jax.Array], num_workers: int):
        return {n: jnp.broadcast_to(p[None], (num_workers,) + p.shape)
                for n, p in params.items()}

    def init_state(self, stacked_params: Dict[str, jax.Array]):
        one = {n: p[0] for n, p in stacked_params.items()}
        inner = self._inner.init_state(one)
        W = next(iter(stacked_params.values())).shape[0]
        # per-worker inner slots (vmapped axis 0)
        inner["slots"] = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (W,) + v.shape),
            inner["slots"])
        return inner

    def apply(self, stacked_params, stacked_grads, state):
        step = state["step"] + 1

        def one_update(p, g, slots):
            st = {"step": state["step"], "slots": slots}
            new_p, new_st = self._inner.apply(p, g, st)
            return new_p, new_st["slots"]

        new_p, new_slots = jax.vmap(one_update)(stacked_params,
                                                stacked_grads,
                                                state["slots"])
        sync = (step % self.k_steps) == 0
        new_p = jax.tree.map(
            lambda p: jnp.where(sync,
                                jnp.broadcast_to(p.mean(axis=0,
                                                        keepdims=True),
                                                 p.shape),
                                p),
            new_p)
        return new_p, {"step": step, "slots": new_slots}


# ---------------------------------------------------------------------------
# FP16 allreduce
# ---------------------------------------------------------------------------

def fp16_allreduce(grads, dtype=jnp.float16):
    """Reference `FP16AllReduceOptimizer` (`fp16_allreduce_optimizer.py`):
    compress grads to fp16 for the reduction. Use INSIDE the compiled
    step, around the point where grads cross the data axis — XLA then
    runs the all-reduce on fp16 operands (half the ICI bytes)."""
    return jax.tree.map(
        lambda g: g.astype(dtype).astype(g.dtype)
        if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)


class FP16AllReduceOptimizer:
    """Wrapper form: casts grads through fp16 before the inner update."""

    def __init__(self, inner: Optimizer, dtype=jnp.float16):
        self._inner = inner
        self._dtype = dtype

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def apply(self, params, grads, state):
        return self._inner.apply(params, fp16_allreduce(grads,
                                                        self._dtype),
                                 state)
