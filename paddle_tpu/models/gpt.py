"""GPT — decoder-only LM, the hybrid-parallel flagship (BASELINE config 3).

Reference model: PaddleNLP GPT (`examples/language_model/gpt`), built on the
reference's meta-parallel layers (`mp_layers.py`, `pp_layers.py`). Here the
same architecture is built TPU-first:

  * uniform pre-LN decoder blocks → stackable: one traced block, `lax.scan`
    over the layer dim (fast compile) or the GSPMD pipeline engine
    (`stacked_pipeline.gpipe`) when a 'pipe' mesh axis exists;
  * TP via the GSPMD mp_layers (weights carry PartitionSpecs; XLA inserts
    the ICI collectives);
  * tied embedding/output head; vocab-parallel softmax CE;
  * everything bf16-friendly: matmuls hit the MXU, softmax/CE in fp32.
"""
from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import (Layer, functional_call, load_state, trainable_state)
from ..nn.layer_common import Dropout, Embedding, LayerList
from ..nn.layer_conv_norm import LayerNorm
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, _constrain)
from ..distributed.meta_parallel.stacked_pipeline import (
    one_f_one_b, pipelined_apply, stack_stage_params)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_hidden: Optional[int] = None          # default 4*hidden
    max_position_embeddings: int = 1024
    dropout: float = 0.0                       # pretraining bench default
    dtype: Any = jnp.bfloat16                  # activation/weight dtype
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.ffn_hidden is None:
            self.ffn_hidden = 4 * self.hidden_size


def gpt_tiny(**kw) -> GPTConfig:
    # preset values are DEFAULTS: callers may override any of them
    # (e.g. max_position_embeddings for long-context decode exports)
    d = dict(vocab_size=512, hidden_size=64, num_layers=4,
             num_heads=4, max_position_embeddings=128)
    d.update(kw)
    return GPTConfig(**d)


def gpt_345m(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, **kw)


def gpt_760m(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=1536, num_layers=24,
                     num_heads=16, **kw)


def gpt_1p3b(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=32, **kw)


def gpt_2p6b(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=2560, num_layers=32,
                     num_heads=32, **kw)


def gpt_6p7b(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=4096, num_layers=32,
                     num_heads=32, **kw)


def ernie_10b(**kw) -> GPTConfig:
    """ERNIE-3.0 10B-class decoder config (BASELINE config 5): train with
    zero_stage=3 + sharding axis so per-chip param residency is
    params/shard_axis (reference bar: static ShardingOptimizer ZeRO-2 +
    offload, `sharding_optimizer.py:87-1385`)."""
    kw.setdefault("max_position_embeddings", 2048)
    return GPTConfig(vocab_size=50304, hidden_size=4096, num_layers=48,
                     num_heads=64, **kw)


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block. TP layout: fused QKV column-parallel, attention
    output row-parallel; MLP column→row (Megatron pattern, reference
    mp_layers usage in PaddleNLP GPTDecoderLayer)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        d = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = d // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        dt = cfg.dtype
        self.ln1 = LayerNorm(d)
        self.qkv = ColumnParallelLinear(d, 3 * d, weight_attr=init,
                                        gather_output=False,
                                        compute_dtype=dt)
        self.out_proj = RowParallelLinear(d, d, weight_attr=init,
                                          input_is_parallel=True,
                                          compute_dtype=dt)
        self.ln2 = LayerNorm(d)
        self.fc1 = ColumnParallelLinear(d, cfg.ffn_hidden, weight_attr=init,
                                        gather_output=False,
                                        compute_dtype=dt)
        self.fc2 = RowParallelLinear(cfg.ffn_hidden, d, weight_attr=init,
                                     input_is_parallel=True,
                                     compute_dtype=dt)
        self.dropout = Dropout(cfg.dropout)
        self._dtype_ = dt

    def forward(self, x):
        b, s, d = x.shape
        h, hd = self.num_heads, self.head_dim
        dt = x.dtype
        res = x
        qkv = self.qkv(self.ln1(x))   # LN in fp32, matmul in compute dtype
        qkv = jnp.reshape(qkv, (b, s, 3, h, hd))
        # heads sharded over 'model' (column shards = contiguous head groups)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        sp_attn = getattr(self, "_sp_attention", None)
        if sp_attn is not None:
            # sequence-parallel ring attention over the 'sequence' mesh
            # axis (set by build_train_step when the mesh has one)
            attn = sp_attn(q, k, v)
        else:
            attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                  training=self.training)
        # named so the "dots_attn" remat policy can SAVE it (skips the
        # flash-kernel forward replay in the backward pass)
        from jax.ad_checkpoint import checkpoint_name
        attn = checkpoint_name(attn, "attn_out")
        attn = jnp.reshape(attn, (b, s, d))
        x = res + self.dropout(self.out_proj(attn)).astype(dt)
        res = x
        y = self.fc2(F.gelu(self.fc1(self.ln2(x)), approximate=True))
        return res + self.dropout(y).astype(dt)


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        # position table is small — plain replicated Embedding
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init)
        self.dropout = Dropout(cfg.dropout)
        self._dtype_ = cfg.dtype

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[-1], dtype=jnp.int32)
            position_ids = jnp.broadcast_to(position_ids, input_ids.shape)
        x = (F.embedding(input_ids, self.word_embeddings.weight) +
             F.embedding(position_ids, self.position_embeddings.weight))
        return self.dropout(x.astype(self._dtype_))


class GPTModel(Layer):
    """Decoder-only trunk; returns final hidden states [b, s, d]."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = LayerList([GPTDecoderLayer(cfg)
                                 for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        x = _constrain(x, ("data", "sharding"), None, None)
        for blk in self.layers:
            x = blk(x)
        return self.ln_f(x)


class GPTPretrainingCriterion(Layer):
    """Vocab-parallel softmax CE over tied-logits (reference:
    GPTPretrainingCriterion + `c_softmax_with_cross_entropy`)."""

    def __init__(self):
        super().__init__()
        self.ce = ParallelCrossEntropy(ignore_index=-1)

    def forward(self, logits, labels, loss_mask=None):
        loss = self.ce(logits, labels)[..., 0]
        if loss_mask is not None:
            m = loss_mask.astype(jnp.float32)
            return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(loss)


class GPTForPretraining(Layer):
    def __init__(self, cfg_or_model):
        super().__init__()
        if isinstance(cfg_or_model, GPTModel):
            self.gpt = cfg_or_model
        else:
            self.gpt = GPTModel(cfg_or_model)
        self.criterion = GPTPretrainingCriterion()

    @property
    def config(self):
        return self.gpt.config

    def logits(self, hidden):
        # tied head: [b,s,d] @ [V,d]^T — vocab dim sharded over 'model'.
        # bf16 operands on the MXU, fp32 accumulation (fp32 operands would
        # run the biggest matmul in the model at 1/4 MXU rate)
        cdt = self.config.dtype
        w = jnp.asarray(self.gpt.embeddings.word_embeddings.weight)
        logits = jnp.einsum("bsd,vd->bsv", hidden.astype(cdt),
                            w.astype(cdt),
                            preferred_element_type=jnp.float32)
        return _constrain(logits, ("data", "sharding"), None, "model")

    def forward(self, input_ids, labels=None, loss_mask=None,
                position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        logits = self.logits(hidden)
        if labels is None:
            return logits
        return self.criterion(logits, labels, loss_mask)


# --------------------------------------------------------------------------
# Distributed train-step builder (bench.py / __graft_entry__ entrypoint)
# --------------------------------------------------------------------------

def _split_params(model: GPTForPretraining):
    """Partition trainable state into stacked block params + outer params.

    Returns (outer: {name: arr}, blocks: [per-block {relname: arr}],
    relnames keyed to one template block).
    """
    all_params = trainable_state(model)
    nl = model.config.num_layers
    blocks = [dict() for _ in range(nl)]
    outer = {}
    for name, v in all_params.items():
        if ".layers." in name:
            head, rest = name.split(".layers.", 1)
            idx, rel = rest.split(".", 1)
            blocks[int(idx)][rel] = v
        else:
            outer[name] = v
    return outer, blocks


def _block_specs(model: GPTForPretraining):
    tmpl = model.gpt.layers[0]
    return {n: (p.sharding_spec or P())
            for n, p in tmpl.named_parameters() if p.trainable}


def _outer_specs(model: GPTForPretraining):
    out = {}
    for name, p in model.named_parameters():
        if ".layers." in name or not p.trainable:
            continue
        out[name] = p.sharding_spec or P()
    return out


def build_train_step(model: GPTForPretraining, optimizer, mesh,
                     num_microbatches: int = 1, remat: bool = True,
                     donate: bool = True, pipeline_schedule: str = "gpipe",
                     remat_policy: str = "dots", loss_chunks: int = 0,
                     zero_stage: int = 2, sequence_zigzag: bool = True,
                     sequence_mode: str = "ring", offload: bool = False,
                     offload_memory_kind: str = "pinned_host",
                     param_dtype=None):
    """Build the one compiled hybrid-parallel training step.

    Parallelism comes entirely from the mesh axes: 'data' (DP — batch dim),
    'model' (TP — weight PartitionSpecs), 'pipe' (PP — stacked blocks via
    the CollectivePermute schedule), 'sharding' (ZeRO — optimizer-state
    specs), 'sequence' (SP — activations sharded on the seq dim with
    zigzag-balanced causal ring attention in every decoder layer;
    composes with dp×tp×zero AND pp — the schedules split the batch
    dim into microbatches, orthogonal to the sequence shard). This
    replaces the reference's whole meta-optimizer chain
    (`fleet_base.py:1288` → StrategyCompiler → program rewriting).

    Returns (step_fn, state) where state = (outer, stacked_blocks,
    opt_state) and step_fn(state, batch) -> (state, loss);
    batch = (input_ids, labels) int32 [B, S]. When cfg.dropout > 0 the
    signature is step_fn(state, batch, rng_key) — pass a fresh key per
    step.

    offload=True keeps the optimizer slots (Adam m/v, master weights) at
    rest in HOST memory (`memory_kind="pinned_host"`): the step streams
    them to device for the update and back out, trading PCIe bandwidth
    for ~2/3 of optimizer HBM — the reference's sharding offload
    (`fleet/meta_optimizers/sharding/offload_helper.py:1`) re-designed
    as XLA host-offload shardings instead of program rewriting. The
    chunked design keeps all COMPUTE in device memory space (transfers
    happen between the compiled programs), so it runs on the CPU
    backend too — CI proves step parity there.
    """
    cfg = model.config
    axis = dict(zip(mesh.axis_names, mesh.devices.shape))
    pp = axis.get("pipe", 1)
    sp = axis.get("sequence", 1)
    assert cfg.num_layers % pp == 0, "num_layers must divide pipe axis"
    layers_per_stage = cfg.num_layers // pp
    if pp > 1 and num_microbatches < pp:
        warnings.warn(
            f"num_microbatches={num_microbatches} < pipeline stages "
            f"{pp}: the schedule needs at least one microbatch per stage; "
            f"using {pp}", stacklevel=2)
    if sp > 1:
        # sequence parallelism composes with dp x tp x zero AND pp: the
        # pipeline schedules split the BATCH dim into microbatches while
        # SP shards the SEQUENCE dim — orthogonal. Ring attention is a
        # shard_map over only the 'sequence' axis, so it vmaps over the
        # stacked stage dim inside the schedules; the 1F1B path applies
        # the same zigzag layout + position-id threading as loss_fn.
        if loss_chunks > 1:
            warnings.warn("loss_chunks disabled under sequence "
                          "parallelism (the chunk scan would re-slice the "
                          "sequence-sharded dim)", stacklevel=2)
            loss_chunks = 0

    outer, block_list = _split_params(model)
    stacked = stack_stage_params(block_list)  # leaves [L, ...]
    master_src = (outer, stacked)  # pre-cast fp32 leaves for master init
    if param_dtype is not None:
        # O2-style residency: params rest in param_dtype (bf16 halves
        # param+grad HBM — the 2.6B offload point exists because of
        # this); pair with optimizer multi_precision=True so fp32
        # master weights live in the (host-offloadable) slots.
        # Reference: pure-fp16 + master weights
        # (`contrib/mixed_precision/decorator.py`, adam multi-precision)
        cast = lambda v: (v.astype(param_dtype)  # noqa: E731
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
        outer = {n: cast(v) for n, v in outer.items()}
        stacked = {n: cast(v) for n, v in stacked.items()}
        if not getattr(optimizer, "_multi_precision", False):
            warnings.warn(
                "param_dtype set without optimizer multi_precision=True: "
                "no fp32 master weights — low-precision updates will "
                "accumulate rounding error", stacklevel=2)
    template = model.gpt.layers[0]

    def block_apply(bparams, x):
        # _sp_attention is scoped to THIS trace (set/restore, not a
        # permanent template mutation): the model stays usable eagerly
        # and under other meshes after the step is built
        template._sp_attention = sp_attn_fn
        try:
            out, _ = functional_call(template, bparams, x)
        finally:
            template._sp_attention = None
        return out

    if remat_policy == "full":
        ckpt_policy = None            # rematerialize everything
    elif remat_policy == "dots":
        # selective remat: keep the weight-matmul outputs (no batch dims in
        # the dot), recompute elementwise + attention (whose einsums carry
        # batch dims) — the VERDICT r2 lever: full per-block checkpoint
        # alone cost ~25% of achievable MFU
        ckpt_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif remat_policy == "dots_attn":
        # dots + the named attention output: +16MB/layer of residency
        # buys skipping the flash-forward replay in the backward
        ckpt_policy = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"))
    else:
        raise ValueError(f"unknown remat_policy {remat_policy!r}")

    def block_apply_key(bparams, x, key):
        # rng_guard must sit INSIDE the checkpointed function: the guard
        # pushes/pops the scoped key within one trace, so no inner-trace
        # key tracer survives in the thread-local scope (leak otherwise)
        from ..framework.random import rng_guard
        template._sp_attention = sp_attn_fn
        try:
            with rng_guard(key):
                out, _ = functional_call(template, bparams, x)
        finally:
            template._sp_attention = None
        return out

    def stage_blocks(stage_p, h, key=None):
        """One pipeline stage = scan over its L/pp blocks (shared by the
        gpipe and 1f1b schedules). `key` (when dropout > 0) is split into
        one sub-key per block so masks decorrelate across layers — a
        closure draw would bake a single mask into the scanned body."""
        if key is None:
            fn = (jax.checkpoint(block_apply, policy=ckpt_policy)
                  if remat else block_apply)

            def body(carry, bp):
                return fn(bp, carry), None
            out, _ = jax.lax.scan(body, h, stage_p)
        else:
            fnk = (jax.checkpoint(block_apply_key, policy=ckpt_policy)
                   if remat else block_apply_key)
            n_local = jax.tree.leaves(stage_p)[0].shape[0]
            keys = jax.random.split(key, n_local)

            def body(carry, xs):
                bp, k = xs
                return fnk(bp, carry, k), None
            out, _ = jax.lax.scan(body, h, (stage_p, keys))
        return out

    def to_staged(stacked_p):
        """Leaves [L, ...] -> [pp, L/pp, ...]."""
        return jax.tree.map(
            lambda a: a.reshape((pp, layers_per_stage) + a.shape[1:]),
            stacked_p)

    seq_axis = "sequence" if sp > 1 else None

    def embed_fwd(input_ids, position_ids=None):
        x = model.gpt.embeddings(input_ids, position_ids)
        return _constrain(x, ("data", "sharding"), seq_axis, None)

    if sp > 1:
        from ..distributed.meta_parallel.sequence_parallel import (
            make_sp_attention, zigzag_permutation)
        if sequence_mode == "ulysses":
            # all-to-all resharding: every chip sees the FULL sequence
            # for its head slice, so the contiguous layout is already
            # causal-balanced — no zigzag
            sequence_zigzag = False
        sp_attn_fn = make_sp_attention(
            mesh, mode=sequence_mode, causal=True,
            zigzag=sequence_zigzag, jit=False)

        def sp_layout(input_ids, labels):
            """Zigzag-reorder tokens so each rank gets an equal share of
            causal-mask work; position ids carry the original positions
            (loss is a position-wise mean — invariant to the reorder)."""
            if not sequence_zigzag:
                return input_ids, labels, None
            zperm = jnp.asarray(
                zigzag_permutation(input_ids.shape[1], sp), jnp.int32)
            ids_z = jnp.take(input_ids, zperm, axis=1)
            labels_z = jnp.take(labels, zperm, axis=1)
            pos = jnp.broadcast_to(zperm[None, :], ids_z.shape)
            return ids_z, labels_z, pos
    else:
        sp_attn_fn = None

        def sp_layout(input_ids, labels):
            return input_ids, labels, None

    def trunk(stacked_p, x, key=None):
        """Apply all L blocks: scan over layers (and pipeline over stages
        when pp > 1)."""
        if pp == 1:
            return stage_blocks(stacked_p, x, key)
        return pipelined_apply(stage_blocks, to_staged(stacked_p), x,
                               num_stages=pp,
                               num_microbatches=max(num_microbatches, pp),
                               remat=False, rng_key=key)

    def lm_loss(hidden, labels):
        """ln_f → tied-head logits → CE. With loss_chunks > 1 the [B,S,V]
        fp32 logits tensor never materializes: a checkpointed scan over
        sequence chunks computes logits+CE per chunk and the backward
        rematerializes each chunk's logits (VERDICT r2 lever: the full
        tied-head logit tensor was the largest HBM round-trip in the
        step)."""
        hidden = model.gpt.ln_f(hidden)
        if loss_chunks <= 1:
            logits = model.logits(hidden)
            return model.criterion(logits, labels)
        b, s, d = hidden.shape
        c = loss_chunks
        assert s % c == 0, f"seq {s} not divisible by loss_chunks {c}"
        hs = jnp.moveaxis(hidden.reshape(b, c, s // c, d), 1, 0)
        ls = jnp.moveaxis(labels.reshape(b, c, s // c), 1, 0)

        def chunk(tot, xs):
            h, lab = xs
            logits = model.logits(h)
            loss = model.criterion.ce(logits, lab)[..., 0]
            return tot + jnp.sum(loss.astype(jnp.float32)), None

        tot, _ = jax.lax.scan(jax.checkpoint(chunk),
                              jnp.zeros((), jnp.float32), (hs, ls))
        return tot / (b * s)

    def loss_fn(params, batch):
        outer_p, stacked_p = params
        input_ids, labels, pos_ids = sp_layout(*batch)
        # embeddings + ln_f + head run via functional_call on the model with
        # outer params; trunk handled functionally
        def fwd():
            if cfg.dropout > 0.0:
                # derive one base key from the ambient rng_guard scope and
                # key embed/trunk masks explicitly — the SAME derivation
                # value_and_grad_1f1b uses, so gpipe and 1f1b draw
                # identical masks (exact loss parity between schedules)
                from ..framework.random import next_key, rng_guard
                base = next_key()
                with rng_guard(jax.random.fold_in(base, 0)):
                    x = embed_fwd(input_ids, pos_ids)
                x = trunk(stacked_p, x, key=jax.random.fold_in(base, 1))
            else:
                x = embed_fwd(input_ids, pos_ids)
                x = trunk(stacked_p, x)
            return lm_loss(x, labels)
        out, _ = functional_call_outer(model, outer_p, fwd)
        return out

    def functional_call_outer(mdl, outer_p, thunk):
        from ..nn.layer import _slots
        slots = _slots(mdl)
        saved = {n: s.value for n, s in slots.items()}
        try:
            for n, v in outer_p.items():
                if n in slots:
                    slots[n].value = v
            return thunk(), None
        finally:
            for n, s in slots.items():
                s.value = saved[n]

    # optimizer state over combined pytree
    params0 = (outer, stacked)
    flatname_params = dict(outer)
    flatname_params.update({f"blocks.{n}": v for n, v in stacked.items()})

    if offload:
        # structure only: materializing the full [L, ...] slot zeros on
        # device before moving them to host would transiently cost the
        # whole optimizer HBM the offload exists to avoid
        opt_state0 = jax.eval_shape(optimizer.init_state, flatname_params)
    else:
        opt_state0 = optimizer.init_state(flatname_params)
        if param_dtype is not None:
            # masters must come from the PRE-cast fp32 weights — fp32
            # (bf16(w)) throws away the mantissa bits the masters exist
            # to keep
            m_outer, m_stacked = master_src
            for n, slots in opt_state0["slots"].items():
                if "master" in slots:
                    src = (m_stacked[n[len("blocks."):]]
                           if n.startswith("blocks.") else m_outer[n])
                    slots["master"] = src.astype(jnp.float32)

    def value_and_grad_1f1b(params, batch, rng=None):
        """Loss + grads via the 1F1B schedule (SectionWorker mode 1,
        `section_worker.cc:144-156`): embedding vjp outside the schedule,
        per-microbatch head (ln_f + tied logits + CE) inside it so
        backward starts S-1 ticks after forward. With rng set, dropout
        keys are threaded per (microbatch, stage) through the schedule
        (reference 1F1B runs real configs with dropout)."""
        outer_p, stacked_p = params
        # same sequence-parallel layout as loss_fn: zigzag-reorder tokens
        # and thread the original positions (no-op when sp == 1)
        input_ids, labels, pos_ids = sp_layout(*batch)
        B = input_ids.shape[0]
        M = max(num_microbatches, pp)
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

        if rng is not None:
            from ..framework.random import next_key, rng_guard
            with rng_guard(rng):
                base = next_key()   # same derivation as loss_fn's fwd
        else:
            base = None

        def embed_fn(op):
            def thunk():
                if base is None:
                    return embed_fwd(input_ids, pos_ids)
                from ..framework.random import rng_guard
                with rng_guard(jax.random.fold_in(base, 0)):
                    return embed_fwd(input_ids, pos_ids)
            out, _ = functional_call_outer(model, op, thunk)
            return out

        x, embed_vjp = jax.vjp(embed_fn, outer_p)
        mb = x.reshape((M, B // M) + tuple(x.shape[1:]))
        labels_mb = labels.reshape((M, B // M) + tuple(labels.shape[1:]))

        def head_grad(op, y, lab):
            def h(op_, y_):
                def fwd():
                    return lm_loss(y_, lab)
                out, _ = functional_call_outer(model, op_, fwd)
                return out
            loss_v, vjp_fn = jax.vjp(h, op, y)
            # global loss = mean over microbatches → seed cotangent 1/M
            dop, dy = vjp_fn(jnp.asarray(1.0 / M, loss_v.dtype))
            return loss_v, dy, dop

        loss_sum, dx_stream, g_staged, g_outer_head = one_f_one_b(
            stage_blocks, to_staged(stacked_p), mb, head_grad, outer_p,
            labels_mb, num_stages=pp,
            rng_key=(jax.random.fold_in(base, 1) if base is not None
                     else None))
        dx = dx_stream.reshape((B,) + tuple(x.shape[1:]))
        (g_outer_embed,) = embed_vjp(dx)
        g_outer = jax.tree.map(jnp.add, g_outer_head, g_outer_embed)
        g_stacked = jax.tree.map(
            lambda a: a.reshape((pp * layers_per_stage,) + a.shape[2:]),
            g_staged)
        return loss_sum / M, (g_outer, g_stacked)

    use_1f1b = pipeline_schedule == "1f1b" and pp > 1
    if pipeline_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule {pipeline_schedule!r}")

    def _loss_and_grads(params_pair, batch, rng):
        if use_1f1b:
            return value_and_grad_1f1b(params_pair, batch, rng)
        if rng is None:
            return jax.value_and_grad(loss_fn)(params_pair, batch)
        # scope the traced key so Dropout draws fresh masks per step
        # (an unscoped next_key() inside jit would bake one constant
        # mask into the compiled program)
        from ..framework.random import rng_guard

        def lf(params, batch_):
            with rng_guard(rng):
                return loss_fn(params, batch_)
        return jax.value_and_grad(lf)(params_pair, batch)

    def step(state, batch, rng=None):
        if cfg.dropout > 0.0 and rng is None:
            # without a key the dropout draws would fall back to the
            # process-global RNG: one constant mask baked into the
            # compiled program + a tracer leaked into eager state
            raise ValueError(
                "cfg.dropout > 0 requires step(state, batch, rng_key) — "
                "pass a fresh jax.random key every step")
        outer_p, stacked_p, opt_state = state
        loss, grads = _loss_and_grads((outer_p, stacked_p), batch, rng)
        g_outer, g_stacked = grads
        flat_p = dict(outer_p)
        flat_p.update({f"blocks.{n}": v for n, v in stacked_p.items()})
        flat_g = dict(g_outer)
        flat_g.update({f"blocks.{n}": v for n, v in g_stacked.items()})
        if shard_axis > 1:
            # ZeRO-2: pin gradients to the optimizer-state layout so XLA
            # reduce-scatters them over 'sharding' (instead of all-reduce)
            # and runs the update sharded; fresh params all-gather on the
            # way out. Reference bar: grad sharding in static
            # ShardingOptimizer (`sharding_optimizer.py:87-1385`).
            flat_g = {n: (jax.lax.with_sharding_constraint(
                              v, ns(opt_spec(n, v)))
                          if jnp.ndim(v) else v)
                      for n, v in flat_g.items()}
        new_flat, new_opt = optimizer.apply(flat_p, flat_g, opt_state)
        new_outer = {n: new_flat[n] for n in outer_p}
        new_stacked = {n: new_flat[f"blocks.{n}"] for n in stacked_p}
        return (new_outer, new_stacked, new_opt), loss

    # ---- shardings ----
    bspecs = _block_specs(model)
    stacked_specs = {n: P("pipe", *s) if pp > 1 else P(None, *s)
                     for n, s in bspecs.items()}
    outer_specs = _outer_specs(model)
    shard_axis = axis.get("sharding", 1)

    def ns(spec):
        return NamedSharding(mesh, spec)

    from ..distributed.meta_parallel.sharding_optimizer import shard_spec_for

    def opt_spec(pname, v):
        if jnp.ndim(v) == 0:
            return P()
        base = (stacked_specs.get(pname[7:]) if pname.startswith("blocks.")
                else outer_specs.get(pname)) or P()
        if shard_axis > 1:
            return shard_spec_for(v.shape, shard_axis, "sharding", base)
        return base

    opt_state_specs = {
        "step": P(),
        "slots": {pname: {sname: opt_spec(pname, v)
                          for sname, v in slots.items()}
                  for pname, slots in opt_state0["slots"].items()}}

    # ZeRO-3: the PARAMETERS themselves rest sharded over 'sharding' (same
    # spec as their optimizer state); XLA all-gathers each layer's weights
    # at its use site inside the layer scan — gather-on-use, param memory
    # at rest = 1/shard_axis. Reference bar: static ShardingOptimizer is
    # only ZeRO-2+offload (`sharding_optimizer.py:87-1385`) — this goes
    # one stage further.
    if zero_stage >= 3 and shard_axis > 1:
        outer_param_specs = {
            n: opt_spec(n, outer[n]) for n in outer_specs}
        stacked_param_specs = {
            n: opt_spec(f"blocks.{n}", stacked[n]) for n in stacked_specs}
    else:
        outer_param_specs = outer_specs
        stacked_param_specs = stacked_specs

    # ZeRO semantics: the 'sharding' axis IS data parallelism with sharded
    # states — the batch splits over data×sharding jointly (reference:
    # sharding_degree multiplies dp for the data split,
    # sharding_optimizer.py:968 _build_groups)
    batch_sharding = (ns(P(("data", "sharding"), seq_axis)),
                      ns(P(("data", "sharding"), seq_axis)))

    if offload:
        # pinned_host is the reference-offload default (DMA-able); some
        # workers cap the pinned pool well below their RAM — 'unpinned_host'
        # rests slots in ordinary host memory instead (staged transfers)
        def ns_host(spec):
            return NamedSharding(mesh, spec,
                                 memory_kind=offload_memory_kind)
        return _build_offload_chunked_step(
            cfg=cfg, optimizer=optimizer, outer=outer, stacked=stacked,
            opt_state0=opt_state0, opt_spec=opt_spec, ns=ns,
            ns_host=ns_host, shard_axis=shard_axis,
            loss_and_grads=_loss_and_grads,
            outer_param_specs=outer_param_specs,
            stacked_param_specs=stacked_param_specs,
            batch_sharding=batch_sharding, donate=donate, pp=pp,
            master_src=master_src)

    is_spec = lambda s: isinstance(s, P)  # noqa: E731
    opt_state_shardings = jax.tree.map(ns, opt_state_specs,
                                       is_leaf=is_spec)

    state_shardings = (
        {n: ns(s) for n, s in outer_param_specs.items()},
        {n: ns(s) for n, s in stacked_param_specs.items()},
        opt_state_shardings)

    if cfg.dropout > 0.0:
        step_jit = jax.jit(
            step,
            in_shardings=(state_shardings, batch_sharding, None),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate else ())
    else:
        step_jit = jax.jit(
            functools.partial(step, rng=None),
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate else ())

    # place initial state
    state0 = jax.device_put(
        (outer, stacked, opt_state0), state_shardings)
    return step_jit, state0


# per-chunk optimizer-slot bytes allowed on device at once in the
# offloaded update (the streaming window, not a model-size limit)
_OFFLOAD_CHUNK_BYTES = 1 << 30


def _build_offload_chunked_step(*, cfg, optimizer, outer, stacked,
                                opt_state0, opt_spec, ns, ns_host,
                                shard_axis, loss_and_grads,
                                outer_param_specs, stacked_param_specs,
                                batch_sharding, donate, pp,
                                master_src=None):
    """Host-offloaded train step with a CHUNKED optimizer update.

    The reference's sharding offload (`fleet/meta_optimizers/sharding/
    offload_helper.py:1`) keeps Adam slots in host memory and streams
    them through device memory parameter-group by parameter-group. A
    single-jit version of that (slots device_put'd in one go) is
    useless: XLA counts the whole optimizer state against peak HBM and
    an ERNIE-1.3B step OOMs exactly as if there were no offload. This
    builds three compiled programs instead:

      1. grad phase — loss + grads (+ global-norm clip, + ZeRO grad
         layout), params resident, slots untouched;
      2. one chunk-update program, reused for every chunk of k decoder
         blocks: dynamic-slice the [L, ...] param/grad stacks at a
         TRACED offset (one compile for all chunks), stream that
         chunk's slots host->device, update, write params back with
         dynamic-update-slice, stream new slots back out;
      3. outer update — embeddings/final-LN slots streamed the same way.

    Peak HBM = params + grads + up to ~TWO chunks of slots: the
    backpressure sync below waits on chunk ci-2, deliberately leaving
    two chunks' transfers in flight to overlap copy with compute, and
    chunk sizing uses the conservative UNSHARDED byte estimate — so
    budget ~2x `_OFFLOAD_CHUNK_BYTES` of slot residency when capacity
    planning at 10B-class sizes. The largest trainable size is still
    bounded by params+grads+activations — the offload promise. Slots
    at rest are tuples of per-chunk arrays in `pinned_host` memory;
    they never exist stacked on device.
    """
    import numpy as onp

    L = cfg.num_layers
    if pp != 1:
        raise ValueError(
            "offload=True requires pipe=1: the chunked update slices the "
            "block stack, which the pipeline axis partitions")
    if not optimizer._elementwise_update:
        raise ValueError(
            f"offload=True cannot stream {type(optimizer).__name__}: its "
            "update is a whole-tensor norm (trust ratio), so per-chunk "
            "streaming would change the numerics. Use an elementwise "
            "rule (Adam/AdamW/Momentum/...) or offload=False")

    slot_struct = opt_state0["slots"]
    # conservative (unsharded) byte estimate: shard_spec_for may leave a
    # leaf replicated, so dividing by shard_axis here could pick a chunk
    # shard_axis x over budget on some device
    per_layer = sum(
        int(onp.prod(v.shape[1:])) * v.dtype.itemsize
        for n, slots in slot_struct.items() if n.startswith("blocks.")
        for v in slots.values())
    k = 1
    for d in range(1, L + 1):
        if L % d == 0 and d * per_layer <= _OFFLOAD_CHUNK_BYTES:
            k = d
    n_chunks = L // k
    starts = [onp.int32(ci * k) for ci in range(n_chunks)]

    # ---- host-resident initial slots, built without an HBM detour ----
    # _init_slot runs on the CPU backend so non-zero initial values
    # (e.g. Adagrad's initial_accumulator_value) are honored exactly as
    # in the resident path, without materializing [L, ...] on the TPU
    try:
        cpu0 = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu0 = None  # no CPU backend: chunk-sized device transient is fine

    def init_slot_values(shape, dtype):
        if cpu0 is not None:
            with jax.default_device(cpu0):
                vals = optimizer._init_slot(jnp.zeros(shape, dtype))
        else:
            vals = optimizer._init_slot(jnp.zeros(shape, dtype))
        return {sn: onp.asarray(v) for sn, v in vals.items()}

    stacked_slot_names = [n for n in slot_struct if n.startswith("blocks.")]
    outer_slot_names = [n for n in slot_struct
                        if not n.startswith("blocks.")]
    # master weights init from the PRE-param_dtype-cast fp32 leaves
    m_outer, m_stacked = master_src if master_src is not None \
        else (outer, stacked)

    chunk_slot_shardings = {}   # pname -> {sname: host sharding (chunk)}
    chunk_slot_dev = {}         # same specs, device memory (stream target)
    slots_host = {}             # pname -> {sname: tuple of n_chunks arrays}
    for pname in stacked_slot_names:
        # slot template from the RESIDENT (possibly cast) params so
        # moment dtypes match slot_struct; masters from the fp32 source
        src_cast = stacked[pname[len("blocks."):]]
        src_master = m_stacked[pname[len("blocks."):]]
        init_vals = init_slot_values((k,) + tuple(src_cast.shape[1:]),
                                     src_cast.dtype)
        per_shard, per_chunks, per_dev = {}, {}, {}
        for sname, sd in slot_struct[pname].items():
            cshape = (k,) + tuple(sd.shape[1:])
            cstruct = jax.ShapeDtypeStruct(cshape, sd.dtype)
            hshard = ns_host(opt_spec(pname, cstruct))
            per_shard[sname] = hshard
            per_dev[sname] = ns(opt_spec(pname, cstruct))
            if sname == "master":
                # master weights initialize FROM the params, not zeros
                per_chunks[sname] = tuple(
                    jax.device_put(
                        onp.asarray(jax.device_get(
                            src_master[ci * k:(ci + 1) * k]),
                            onp.float32),
                        hshard)
                    for ci in range(n_chunks))
            else:
                # one transfer, shared by every chunk slot: jax arrays
                # are immutable and each slot is wholesale-replaced by
                # the first step's update
                v0 = jax.device_put(init_vals[sname], hshard)
                per_chunks[sname] = (v0,) * n_chunks
        chunk_slot_shardings[pname] = per_shard
        chunk_slot_dev[pname] = per_dev
        slots_host[pname] = per_chunks

    outer_slot_shardings = {}
    outer_slot_dev = {}
    for pname in outer_slot_names:
        init_vals = init_slot_values(tuple(outer[pname].shape),
                                     outer[pname].dtype)
        per_shard, per, per_dev = {}, {}, {}
        for sname, sd in slot_struct[pname].items():
            hshard = ns_host(opt_spec(pname, sd))
            per_shard[sname] = hshard
            per_dev[sname] = ns(opt_spec(pname, sd))
            if sname == "master":
                per[sname] = jax.device_put(
                    onp.asarray(jax.device_get(m_outer[pname]),
                                onp.float32), hshard)
            else:
                per[sname] = jax.device_put(init_vals[sname], hshard)
        outer_slot_shardings[pname] = per_shard
        outer_slot_dev[pname] = per_dev
        slots_host[pname] = per

    # ---- compiled programs ----
    outer_shardings = {n: ns(s) for n, s in outer_param_specs.items()}
    stacked_shardings = {n: ns(s) for n, s in stacked_param_specs.items()}
    g_outer_shardings = {n: ns(opt_spec(n, outer[n])) for n in outer}
    g_stacked_shardings = {n: ns(opt_spec(f"blocks.{n}", stacked[n]))
                           for n in stacked}

    def grad_phase(params_pair, opt_step, batch, rng=None):
        loss, (g_outer, g_stacked) = loss_and_grads(params_pair, batch,
                                                    rng)
        flat_g = dict(g_outer)
        flat_g.update({f"blocks.{n}": v for n, v in g_stacked.items()})
        if shard_axis > 1:
            flat_g = {n: (jax.lax.with_sharding_constraint(
                              v, ns(opt_spec(n, v)))
                          if jnp.ndim(v) else v)
                      for n, v in flat_g.items()}
        if optimizer._grad_clip is not None:
            # global-norm clip sees the FULL grad set here; the per-chunk
            # updates below must not clip again
            flat_g = optimizer._grad_clip(flat_g)
        g_outer = {n: flat_g[n] for n in g_outer}
        g_stacked = {n: flat_g[f"blocks.{n}"] for n in g_stacked}
        return loss, g_outer, g_stacked, opt_step + 1

    grad_kwargs = dict(
        in_shardings=((outer_shardings, stacked_shardings), ns(P()),
                      batch_sharding),
        out_shardings=(None, g_outer_shardings, g_stacked_shardings,
                       ns(P())))
    if cfg.dropout > 0.0:
        grad_kwargs["in_shardings"] = grad_kwargs["in_shardings"] + (None,)
        grad_jit = jax.jit(grad_phase, **grad_kwargs)
    else:
        grad_jit = jax.jit(functools.partial(grad_phase, rng=None),
                           **grad_kwargs)

    # smallest block param: its updated value doubles as a 4-byte
    # completion probe the orchestrator can ACTUALLY sync on — through
    # the axon tunnel block_until_ready returns early, so backpressure
    # must ride a real host transfer (bench.py's float(loss) trick)
    import numpy as _np
    probe_name = min(stacked,
                     key=lambda n: int(_np.prod(stacked[n].shape[1:])))

    def chunk_update(stacked_p, g_stacked, slots_chunk, new_step, start):
        p_c = {f"blocks.{n}": jax.lax.dynamic_slice_in_dim(v, start, k, 0)
               for n, v in stacked_p.items()}
        g_c = {f"blocks.{n}":
               jax.lax.dynamic_slice_in_dim(g_stacked[n], start, k, 0)
               for n in stacked_p}
        new_p_c, new_slots = optimizer.apply_named(p_c, g_c, slots_chunk,
                                                   new_step)
        new_stacked = {
            n: jax.lax.dynamic_update_slice_in_dim(
                stacked_p[n], new_p_c[f"blocks.{n}"].astype(
                    stacked_p[n].dtype), start, 0)
            for n in stacked_p}
        probe = jnp.sum(new_p_c[f"blocks.{probe_name}"]).astype(
            jnp.float32)
        return new_stacked, new_slots, probe

    # slots cross the host<->device boundary OUTSIDE the jits, as plain
    # transfers in the orchestrator below: in-jit memory-space changes
    # (annotate_device_placement) break the SPMD partitioner on
    # multi-device meshes, and outside-jit copies dispatch async anyway,
    # pipelining chunk i+1's upload behind chunk i's compute
    chunk_jit = jax.jit(
        chunk_update,
        in_shardings=(stacked_shardings, g_stacked_shardings,
                      chunk_slot_dev, ns(P()), None),
        out_shardings=(stacked_shardings, chunk_slot_dev, ns(P())),
        donate_argnums=(0, 2) if donate else ())

    def outer_update(outer_p, g_outer, outer_slots, new_step):
        return optimizer.apply_named(outer_p, g_outer, outer_slots,
                                     new_step)

    outer_jit = jax.jit(
        outer_update,
        in_shardings=(outer_shardings, g_outer_shardings,
                      outer_slot_dev, ns(P())),
        out_shardings=(outer_shardings, outer_slot_dev),
        donate_argnums=(0, 2) if donate else ())

    import os as _os
    _sync = _os.environ.get("PTPU_OFFLOAD_SYNC") == "1"

    def _trace(tag, value):
        if _sync:
            jax.block_until_ready(value)
            print(f"offload-step: {tag} done", flush=True)

    def step_fn(state, batch, rng=None):
        if cfg.dropout > 0.0 and rng is None:
            raise ValueError(
                "cfg.dropout > 0 requires step(state, batch, rng_key) — "
                "pass a fresh jax.random key every step")
        outer_p, stacked_p, opt_state = state
        if cfg.dropout > 0.0:
            loss, g_outer, g_stacked, new_step = grad_jit(
                (outer_p, stacked_p), opt_state["step"], batch, rng)
        else:
            loss, g_outer, g_stacked, new_step = grad_jit(
                (outer_p, stacked_p), opt_state["step"], batch)
        _trace("grad", loss)
        slots = opt_state["slots"]
        new_stacked = stacked_p
        chunk_results = []
        probes = []
        for ci in range(n_chunks):
            if ci >= 2:
                # backpressure: dispatch is async, so without this the
                # Python loop uploads EVERY chunk's slots before the
                # first update frees any — the whole optimizer state
                # lands on device at once and the step OOMs exactly
                # like the unchunked version. The probe read is a REAL
                # 4-byte host transfer (block_until_ready returns early
                # through the axon tunnel): once chunk ci-2's update
                # has executed, its donated slot buffers are free, so
                # at most ~2 chunks of slots are in flight on device
                float(probes[ci - 2])
            slots_chunk = jax.device_put(
                {n: {sname: slots[n][sname][ci] for sname in slots[n]}
                 for n in stacked_slot_names}, chunk_slot_dev)
            new_stacked, new_chunk, probe = chunk_jit(
                new_stacked, g_stacked, slots_chunk, new_step, starts[ci])
            probes.append(probe)
            # back to host residence; dropping the device ref frees the
            # chunk's HBM before chunk ci+2 uploads
            chunk_results.append(
                jax.device_put(new_chunk, chunk_slot_shardings))
            _trace(f"chunk {ci}/{n_chunks}", chunk_results[-1])
        outer_slots = jax.device_put(
            {n: slots[n] for n in outer_slot_names}, outer_slot_dev)
        new_outer, new_outer_slots = outer_jit(outer_p, g_outer,
                                               outer_slots, new_step)
        _trace("outer", new_outer_slots)
        new_outer_slots = jax.device_put(new_outer_slots,
                                         outer_slot_shardings)
        new_slots = {n: {sname: tuple(cr[n][sname]
                                      for cr in chunk_results)
                         for sname in slots[n]}
                     for n in stacked_slot_names}
        new_slots.update(new_outer_slots)
        return (new_outer, new_stacked,
                {"step": new_step, "slots": new_slots}), loss

    state0 = (jax.device_put(outer, outer_shardings),
              jax.device_put(stacked, stacked_shardings),
              {"step": jax.device_put(jnp.zeros((), jnp.int32), ns(P())),
               "slots": slots_host})
    return step_fn, state0


# --------------------------------------------------------------------------
# KV-cached decode-step export (native serving DECODE workload)
# --------------------------------------------------------------------------

def make_gpt_decode_step(model: GPTForPretraining, context: int,
                         width: int = 1):
    """Build the decode-step function for the native predictor's
    KV-cache convention (csrc/ptpu_predictor.cc kv_plan/kv_attach):

      step(ids[B,W] i32, pos[B] i32, k0, v0, ..., k_{L-1}, v_{L-1})
        -> (logits, nk0, nv0, ..., nk_{L-1}, nv_{L-1})

    ``W = width`` is the number of positions fed per session per step:
    width 1 is the classic autoregressive step (logits ``[B, V]``, the
    shape the r9 engine pinned); width k+1 is the speculative-decoding
    VERIFY artifact — the target model scores a draft's k proposals
    plus the bonus position in ONE pass (logits ``[B, W, V]``, one row
    per fed position). Cache operands are ``[B, context, heads,
    head_dim]`` float32; each ``nk``/``nv`` is the fed window's
    ``[B, W, heads, head_dim]`` projection, which the C runtime
    appends into the session at positions ``pos .. pos+W-1``.
    Attention runs over ``concat(cache, window)``: cache positions
    ``j < pos`` are live, the zero tail ``[pos, P)`` is masked, and
    the window is causal (window key w' attends from window query
    ``w >= w'``) — a fixed-shape graph, so it loads onto the planned
    zero-alloc arena and the attention block fuses into PtpuAttention
    (and onto the block-table PtpuPagedAttention under kv_attach)
    exactly like the width-1 export."""
    cfg = model.config
    if width < 1:
        raise ValueError(f"width must be >= 1 (got {width})")
    if context < 1 or context + width > cfg.max_position_embeddings:
        raise ValueError(
            f"context {context} + width {width} needs "
            f"max_position_embeddings > context + width - 1 "
            f"(got {cfg.max_position_embeddings})")
    W = width

    def block_step(blk, x, k_cache, v_cache, pos):
        b = x.shape[0]
        h, hd = blk.num_heads, blk.head_dim
        res = x
        qkv = blk.qkv(blk.ln1(x))
        qkv = jnp.reshape(qkv, (b, W, 3, h, hd))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        kcat = jnp.concatenate([k_cache, k], axis=1)   # [b, P+W, h, hd]
        vcat = jnp.concatenate([v_cache, v], axis=1)
        P = k_cache.shape[1]
        j = jnp.arange(P + W, dtype=jnp.int32)
        wq = jnp.arange(W, dtype=jnp.int32)
        # [b, W, P+W]: cache keys below the session length, plus the
        # causal lower triangle of the fed window itself
        valid = (j[None, None, :] < pos[:, None, None]) | \
            ((j[None, None, :] >= P) &
             (j[None, None, :] - P <= wq[None, :, None]))
        attn = F.scaled_dot_product_attention(
            q, kcat, vcat, attn_mask=valid[:, None, :, :],
            training=False)
        attn = jnp.reshape(attn, (b, W, h * hd))
        x = res + blk.out_proj(attn)
        res = x
        y = blk.fc2(F.gelu(blk.fc1(blk.ln2(x)), approximate=True))
        return res + y, k, v

    def step(ids, pos, *caches):
        wq = jnp.arange(W, dtype=jnp.int32)
        x = model.gpt.embeddings(ids, pos[:, None] + wq[None, :])
        news = []
        for li, blk in enumerate(model.gpt.layers):
            x, nk, nv = block_step(blk, x, caches[2 * li],
                                   caches[2 * li + 1], pos)
            news.append(nk)
            news.append(nv)
        hidden = model.gpt.ln_f(x)
        logits = model.logits(hidden)   # [B, W, V]
        if W == 1:
            return (logits[:, 0], *news)
        return (logits, *news)

    return step


def export_gpt_decode(model: GPTForPretraining, path: str, batch: int,
                      context: int, width: int = 1) -> str:
    """Export the KV decode-step artifact for ``model`` at a fixed
    decode ``batch``, cache ``context`` (positions per session) and
    step ``width`` (positions fed per step — width 1 is the normal
    autoregressive step; width k+1 is the speculative-decoding verify
    artifact, see ``make_gpt_decode_step``). Returns the written
    path. Serve it with ``inference.create_server(...,
    decode_model=path)`` (width 1) or ``spec_verify_model=path``
    (width k+1), or drive it directly over
    ``ptpu_predictor_kv_plan``/``decode_step``."""
    import numpy as onp
    from ..onnx.converter import trace_to_onnx
    cfg = model.config
    step = make_gpt_decode_step(model, context, width)
    h, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    args = [jnp.zeros((batch, width), jnp.int32),
            jnp.zeros((batch,), jnp.int32)]
    for _ in range(cfg.num_layers):
        args.append(jnp.zeros((batch, context, h, hd), jnp.float32))
        args.append(jnp.zeros((batch, context, h, hd), jnp.float32))
    data = trace_to_onnx(step, tuple(args))
    if not path.endswith(".onnx"):
        path = path + ".onnx"
    with open(path, "wb") as f:
        f.write(onp.frombuffer(data, dtype=onp.uint8).tobytes()
                if not isinstance(data, bytes) else data)
    return path


def sync_params_to_model(model: GPTForPretraining, state):
    """Write (outer, stacked) back into the Layer tree (for save/eval)."""
    outer_p, stacked_p, _ = state
    nl = model.config.num_layers
    flat = dict(outer_p)
    for rel, v in stacked_p.items():
        for i in range(nl):
            flat[f"gpt.layers.{i}.{rel}"] = v[i]
    load_state(model, flat)
