"""Transformer seq2seq model (WMT-class translation; reference: the
transformer models driven throughout the reference test suite —
`unittests/dist_transformer.py`, `dygraph_to_static` transformer — built
from the op families `operators/fused/multihead_matmul_op.cu`,
`softmax_with_cross_entropy`, `math/beam_search.cc`).

TPU-first assembly over the nn.Transformer stack: learned embeddings +
sinusoidal positions, label-smoothed CE (the WMT recipe), greedy and
beam-search decode over the functional `nn.decode.beam_search` (static
[B, K] shapes, lax.scan over steps).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layer_common import Dropout, Embedding, Linear
from ..nn.layer_transformer import Transformer


def sinusoid_position_encoding(max_len: int, d_model: int) -> jnp.ndarray:
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(0, d_model, 2).astype(np.float64)
    angle = pos / np.power(10000.0, dim / d_model)
    enc = np.zeros((max_len, d_model), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle[:, : d_model // 2])  # odd d_model safe
    return jnp.asarray(enc)


class TransformerModel(Layer):
    """Encoder-decoder translation model with shared target
    embedding/generator weight (the WMT base-config convention)."""

    def __init__(self, src_vocab_size: int, trg_vocab_size: int,
                 max_length: int = 256, d_model: int = 512, n_head: int = 8,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 d_inner_hid: int = 2048, dropout: float = 0.1,
                 bos_id: int = 0, eos_id: int = 1,
                 pad_id: Optional[int] = None):
        super().__init__()
        self.d_model = d_model
        self.bos_id, self.eos_id = bos_id, eos_id
        self.pad_id = bos_id if pad_id is None else pad_id
        init = I.Normal(0.0, d_model ** -0.5)
        self.src_embedding = Embedding(src_vocab_size, d_model,
                                       weight_attr=init)
        self.trg_embedding = Embedding(trg_vocab_size, d_model,
                                       weight_attr=init)
        self.register_buffer("pos_enc",
                             sinusoid_position_encoding(max_length,
                                                        d_model))
        self.transformer = Transformer(
            d_model=d_model, nhead=n_head,
            num_encoder_layers=num_encoder_layers,
            num_decoder_layers=num_decoder_layers,
            dim_feedforward=d_inner_hid, dropout=dropout,
            normalize_before=True)
        self.dropout = Dropout(dropout)
        self.trg_vocab_size = trg_vocab_size

    # -- embedding helpers -------------------------------------------------

    def _embed(self, ids, table):
        x = F.embedding(ids, table.weight) * math.sqrt(self.d_model)
        x = x + jnp.asarray(self.pos_enc)[: ids.shape[1]][None]
        return self.dropout(x)

    def _src_mask(self, src):
        # [B, 1, 1, S] boolean keep-mask broadcast over heads/queries
        return (src != self.pad_id)[:, None, None, :]

    # -- training ----------------------------------------------------------

    def forward(self, src_word, trg_word):
        """Teacher-forced logits [B, T, V]."""
        src = self._embed(src_word, self.src_embedding)
        tgt = self._embed(trg_word, self.trg_embedding)
        t = trg_word.shape[1]
        causal = Transformer.generate_square_subsequent_mask(t)
        # memory_mask matches decode-time masking — cross-attention must
        # not train on source pad positions it won't see at inference
        out = self.transformer(src, tgt, src_mask=self._src_mask(src_word),
                               tgt_mask=causal[None, None],
                               memory_mask=self._src_mask(src_word))
        # generator shares the target embedding (weight tying)
        return out @ jnp.asarray(self.trg_embedding.weight).T

    def loss(self, logits, labels, label_smooth_eps: float = 0.1):
        """Label-smoothed CE ignoring pads (reference WMT recipe:
        `softmax_with_cross_entropy(soft_label=True)` after
        `label_smooth`)."""
        v = self.trg_vocab_size
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        smooth = label_smooth_eps / (v - 1)
        onehot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
        soft = onehot * (1.0 - label_smooth_eps - smooth) + smooth
        per_tok = -jnp.sum(soft * logp, axis=-1)
        mask = (labels != self.pad_id).astype(jnp.float32)
        return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    # -- inference ---------------------------------------------------------

    def beam_search_decode(self, src_word, beam_size: int = 4,
                           max_len: int = 32,
                           length_penalty: float = 0.6):
        """Returns (seqs [B, K, max_len], scores [B, K]) via the
        functional beam search (`math/beam_search.cc` semantics).

        The decode state is a FIXED [B, K, max_len+1] prefix buffer plus
        a step counter (lax.scan carries need static shapes; beam_search
        reorders the buffer along K when beams switch parents). Each step
        re-runs the decoder over the padded prefix — the causal mask
        keeps padded future slots out of position t's receptive field —
        and reads the logits at the current position.
        """
        from ..nn.decode import beam_search
        b = src_word.shape[0]
        k = beam_size
        was_training = self.training
        self.eval()
        try:
            src = self._embed(src_word, self.src_embedding)
            memory = self.transformer.encoder(
                src, src_mask=self._src_mask(src_word))
            mem = jnp.repeat(memory, k, axis=0)
            msk = jnp.repeat(self._src_mask(src_word), k, axis=0)
            T = max_len + 1
            causal = Transformer.generate_square_subsequent_mask(T)

            def step_fn(tokens, state):
                buf = state["prefix"]                    # [B, K, T]
                # step counter rides [B, K] so beam reordering can gather
                # it like every other state leaf
                t = state["t"]
                tc = t[0, 0]
                buf = jnp.where((jnp.arange(T) == tc)[None, None, :],
                                tokens[..., None], buf)
                flat = buf.reshape(b * k, T)
                tgt = self._embed(flat, self.trg_embedding)
                out = self.transformer.decoder(
                    tgt, mem, tgt_mask=causal[None, None],
                    memory_mask=msk)
                w = jnp.asarray(self.trg_embedding.weight)
                pos = jax.lax.dynamic_index_in_dim(out, tc, axis=1,
                                                   keepdims=False)
                logits = pos @ w.T                       # [B*K, V]
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return (logp.reshape(b, k, -1),
                        {"prefix": buf, "t": t + 1})

            init_state = {"prefix": jnp.zeros((b, k, T), jnp.int32),
                          "t": jnp.zeros((b, k), jnp.int32)}
            return beam_search(step_fn, init_state, b, k, self.bos_id,
                               self.eos_id, max_len, length_penalty)
        finally:
            if was_training:
                self.train()
