"""Model zoo — the framework's flagship model families.

Covers the reference's benchmark configs (BASELINE.md): GPT (hybrid
DP×TP×PP, config 3), BERT/ERNIE (DP pretrain, config 2 — the ≥35% MFU
north star), plus the vision zoo re-exported from `paddle_tpu.vision`
(ResNet/LeNet, config 1). The reference hosts these in PaddleNLP /
paddle.vision; here they are in-tree because they double as the perf
harness (`bench.py`) and the multi-chip dry-run (`__graft_entry__.py`).
"""
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForPretraining,
    GPTModel,
    GPTPretrainingCriterion,
    build_train_step,
    gpt_tiny,
    gpt_345m,
    gpt_760m,
    gpt_1p3b,
    gpt_2p6b,
    gpt_6p7b,
    ernie_10b,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertModel,
    bert_base,
    bert_tiny,
)
from .transformer import (  # noqa: F401
    TransformerModel,
    sinusoid_position_encoding,
)
from .ctr import DeepFM, WideDeep, build_ctr_train_step  # noqa: F401
