"""BERT — encoder LM, the data-parallel north star (BASELINE config 2:
BERT-base pretraining ≥35% MFU).

Reference model: PaddleNLP BERT on the reference's `paddle.nn` layers
(`nn/layer/transformer.py` TransformerEncoder). TPU-first build: post-LN
encoder blocks with the same stackable structure as GPT (lax.scan over
layers), bf16 matmuls, fp32 softmax/LN, MLM+NSP pretraining heads with the
tied decoder.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, functional_call, trainable_state
from ..nn.layer_common import Dropout, Embedding, LayerList, Linear
from ..nn.layer_conv_norm import LayerNorm
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    _constrain)


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528          # padded to 64 for MXU-friendly head
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.ffn_hidden is None:
            self.ffn_hidden = 4 * self.hidden_size


def bert_tiny(**kw) -> BertConfig:
    for k, v in dict(vocab_size=512, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=128).items():
        kw.setdefault(k, v)
    return BertConfig(**kw)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        # small tables — plain replicated Embeddings
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=init)
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.dropout = Dropout(cfg.dropout)
        self._dtype_ = cfg.dtype

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[-1], dtype=jnp.int32)
            position_ids = jnp.broadcast_to(position_ids, input_ids.shape)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (F.embedding(input_ids, self.word_embeddings.weight) +
             F.embedding(position_ids, self.position_embeddings.weight) +
             F.embedding(token_type_ids, self.token_type_embeddings.weight))
        return self.dropout(self.layer_norm(x)).astype(self._dtype_)


class BertEncoderLayer(Layer):
    """Post-LN encoder block (original BERT)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        d = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = d // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv = ColumnParallelLinear(d, 3 * d, weight_attr=init,
                                        gather_output=False)
        self.out_proj = RowParallelLinear(d, d, weight_attr=init,
                                          input_is_parallel=True)
        self.ln1 = LayerNorm(d)
        self.fc1 = ColumnParallelLinear(d, cfg.ffn_hidden, weight_attr=init,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(cfg.ffn_hidden, d, weight_attr=init,
                                     input_is_parallel=True)
        self.ln2 = LayerNorm(d)
        self.dropout = Dropout(cfg.dropout)
        self._dtype_ = cfg.dtype

    def forward(self, x, attn_mask=None):
        b, s, d = x.shape
        h, hd = self.num_heads, self.head_dim
        qkv = jnp.reshape(self.qkv(x), (b, s, 3, h, hd))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                              training=self.training)
        attn = jnp.reshape(attn, (b, s, d))
        x = self.ln1(x + self.dropout(self.out_proj(attn)))
        y = self.fc2(F.gelu(self.fc1(x.astype(self._dtype_)),
                            approximate=True))
        return self.ln2(x + self.dropout(y)).astype(self._dtype_)


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        return jnp.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = LayerList([BertEncoderLayer(cfg)
                                  for _ in range(cfg.num_layers)])
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [b, s] padding mask -> [b, 1, 1, s] broadcastable boolean
            attention_mask = attention_mask[:, None, None, :].astype(bool)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = _constrain(x, ("data", "sharding"), None, None)
        for blk in self.encoder:
            x = blk(x, attn_mask=attention_mask)
        return x, self.pooler(x)


class BertPretrainingHeads(Layer):
    """MLM transform + tied vocab decoder + NSP classifier."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter((cfg.vocab_size,),
                                                  is_bias=True)
        self.seq_relationship = Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output, embedding_weight,
                masked_positions=None):
        # embedding_weight passed (not stored) so the tied table stays a
        # single Parameter slot under bert.embeddings — one grad, one update
        if masked_positions is not None:
            # gather the ~15% masked positions BEFORE the transform and
            # vocab projection (reference: BertPretrainingHeads.forward
            # gathers sequence_output at masked_positions) — the MLM head
            # then costs P/S of the dense version and the [B, S, V]
            # logits tensor never exists
            pos = masked_positions.astype(jnp.int32)
            sequence_output = jnp.take_along_axis(
                sequence_output, pos[..., None], axis=1)
        x = self.layer_norm(F.gelu(self.transform(sequence_output)))
        logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                            jnp.asarray(embedding_weight).astype(jnp.float32))
        logits = logits + self.decoder_bias
        nsp = self.seq_relationship(pooled_output.astype(jnp.float32))
        return logits, nsp


class BertForPretraining(Layer):
    def __init__(self, cfg_or_model):
        super().__init__()
        self.bert = (cfg_or_model if isinstance(cfg_or_model, BertModel)
                     else BertModel(cfg_or_model))
        self.cls = BertPretrainingHeads(self.bert.config)

    @property
    def config(self):
        return self.bert.config

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None,
                masked_lm_weights=None, masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits, nsp = self.cls(
            seq, pooled, self.bert.embeddings.word_embeddings.weight,
            masked_positions=masked_positions)
        if masked_lm_labels is None:
            return logits, nsp
        # MLM loss: ignore_index = -1 (unmasked / padded prediction slots).
        # With masked_positions, labels are [B, P] aligned to the gathered
        # slots; dense labels [B, S] take a chunked scan so the fp32
        # [B, S, V] CE fusion never materializes (the one-fusion version
        # spilled 208M of vmem registers on TPU at seq 512)
        mask = (masked_lm_labels >= 0).astype(jnp.float32)
        if masked_lm_weights is not None:
            mask = mask * masked_lm_weights.astype(jnp.float32)
        lab = jnp.maximum(masked_lm_labels, 0).astype(jnp.int32)

        def ce_sum(lg, lab_c, mask_c):
            lg = lg.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, lab_c[..., None],
                                         axis=-1)[..., 0]
            return jnp.sum((lse - picked) * mask_c)

        s = logits.shape[1]
        cs = 128 if (masked_positions is None and s % 128 == 0
                     and s > 128) else s
        if cs == s:
            tot = ce_sum(logits, lab, mask)
        else:
            n = s // cs
            split = lambda a: jnp.moveaxis(  # noqa: E731
                a.reshape(a.shape[0], n, cs, *a.shape[2:]), 1, 0)

            def chunk(acc, xs):
                lg, lab_c, mask_c = xs
                return acc + ce_sum(lg, lab_c, mask_c), None

            tot, _ = jax.lax.scan(
                jax.checkpoint(chunk), jnp.zeros((), jnp.float32),
                (split(logits), split(lab), split(mask)))
        mlm = tot / jnp.maximum(jnp.sum(mask), 1.0)
        if next_sentence_labels is None:
            return mlm
        nsp32 = nsp.astype(jnp.float32)
        nsp_loss = jnp.mean(
            jax.nn.logsumexp(nsp32, axis=-1) -
            jnp.take_along_axis(
                nsp32, next_sentence_labels.astype(jnp.int32)[:, None],
                axis=-1)[:, 0])
        return mlm + nsp_loss
