"""CTR model family: Wide&Deep and DeepFM.

Reference workload class: the recsys/CTR models the reference's
PS + fleet-dataset stack exists for (`data_set.h` LoadIntoMemory +
DeviceWorker trainers; model shapes per the public wide_deep/deepfm
configs in PaddleRec-style CTR benchmarks the fleet tests drive).

TPU-first shape: sparse fields are fixed-count id slots [B, F] looked up
in ONE embedding table gather (padded vocab, MXU-friendly dims), dense
features ride alongside; everything fuses into a single jitted step.
For the billion-row vocab regime the same forward runs against the PS
sharded table (`distributed/ps/table.py`) with pulled rows.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layer_common import Embedding, Linear


class _MLP(Layer):
    def __init__(self, dims: Sequence[int]):
        super().__init__()
        from ..nn.layer_common import LayerList
        self.fcs = LayerList([Linear(dims[i], dims[i + 1])
                              for i in range(len(dims) - 1)])

    def forward(self, x):
        for i, fc in enumerate(self.fcs):
            x = fc(x)
            if i < len(self.fcs) - 1:
                x = F.relu(x)
        return x


class WideDeep(Layer):
    """Wide & Deep (Cheng et al. 2016): a linear 'wide' path over the
    sparse ids + an MLP 'deep' path over field embeddings."""

    def __init__(self, sparse_vocab: int, num_fields: int,
                 dense_dim: int = 13, embed_dim: int = 16,
                 hidden: Sequence[int] = (128, 64)):
        super().__init__()
        self.embedding = Embedding(sparse_vocab, embed_dim,
                                   weight_attr=I.Normal(0.0, 0.01))
        self.wide = Embedding(sparse_vocab, 1,
                              weight_attr=I.Normal(0.0, 0.01))
        self.dense_wide = Linear(dense_dim, 1)
        dims = [num_fields * embed_dim + dense_dim, *hidden, 1]
        self.deep = _MLP(dims)

    def forward(self, sparse_ids, dense):
        emb = self.embedding(sparse_ids)            # [B, F, E]
        deep_in = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1), dense], axis=-1)
        deep_out = self.deep(deep_in)               # [B, 1]
        wide_out = jnp.sum(self.wide(sparse_ids), axis=1) \
            + self.dense_wide(dense)                # [B, 1]
        return (deep_out + wide_out)[:, 0]          # logits [B]


class DeepFM(Layer):
    """DeepFM (Guo et al. 2017): first-order linear + pairwise FM
    interactions + deep MLP, sharing one embedding table."""

    def __init__(self, sparse_vocab: int, num_fields: int,
                 dense_dim: int = 13, embed_dim: int = 16,
                 hidden: Sequence[int] = (128, 64)):
        super().__init__()
        self.embedding = Embedding(sparse_vocab, embed_dim,
                                   weight_attr=I.Normal(0.0, 0.01))
        self.first_order = Embedding(sparse_vocab, 1,
                                     weight_attr=I.Normal(0.0, 0.01))
        self.dense_linear = Linear(dense_dim, 1)
        dims = [num_fields * embed_dim + dense_dim, *hidden, 1]
        self.deep = _MLP(dims)

    def forward(self, sparse_ids, dense):
        emb = self.embedding(sparse_ids)            # [B, F, E]
        # FM second order: 0.5 * ((Σv)² − Σv²) summed over E
        s = jnp.sum(emb, axis=1)
        fm = 0.5 * jnp.sum(s * s - jnp.sum(emb * emb, axis=1), axis=-1,
                           keepdims=True)
        first = jnp.sum(self.first_order(sparse_ids), axis=1) \
            + self.dense_linear(dense)
        deep_in = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1), dense], axis=-1)
        deep = self.deep(deep_in)
        return (first + fm + deep)[:, 0]            # logits [B]


def build_ctr_train_step(model: Layer, optimizer, donate: bool = True):
    """One jitted CTR step: (state, (ids, dense, labels)) ->
    (state, (loss, auc_proxy)). Loss = sigmoid BCE with logits."""
    import functools

    from ..nn.layer import functional_call, trainable_state

    params = trainable_state(model)
    opt_state = optimizer.init_state(params)

    def loss_fn(p, ids, dense, labels):
        logits, _ = functional_call(model, p, ids, dense)
        labels = labels.astype(logits.dtype)
        loss = jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        return loss, logits

    deco = jax.jit if not donate else functools.partial(
        jax.jit, donate_argnums=(0,))

    @deco
    def step(state, ids, dense, labels):
        p, s = state
        (loss, logits), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, ids, dense, labels)
        new_p, new_s = optimizer.apply(p, g, s)
        return (new_p, new_s), (loss, logits)

    return step, (params, opt_state)
