"""`paddle.utils` equivalent (reference: python/paddle/utils/ —
download.py, install_check.py, deprecated.py, op_version.py)."""
from __future__ import annotations

import functools
import os
import warnings


def run_check():
    """Reference: utils/install_check.py `paddle.utils.run_check` — a
    sanity forward/backward on the available device(s)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..nn.layer_common import Linear
    from ..nn.layer import functional_call, trainable_state

    lin = Linear(4, 2)
    x = jnp.ones((2, 4))

    def loss(p):
        out, _ = functional_call(lin, p, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(trainable_state(lin))
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"{n} {jax.default_backend()} device(s) available.")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    """Reference: utils/deprecated.py decorator. level 0/1 warn on call;
    level 2 raises (the reference's hard-removal stage)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            hint = f"; use {update_to} instead" if update_to else ""
            msg = (f"{fn.__name__} is deprecated since {since or 'n/a'}"
                   f"{hint}. {reason}")
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def get_weights_path_from_url(url, md5sum=None):
    """Reference: utils/download.py — zero-egress environment: only a
    pre-populated cache hit can succeed."""
    cache = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "weights", os.path.basename(url))
    if os.path.exists(cache):
        return cache
    raise RuntimeError(
        f"no network egress and {cache} not pre-populated; place the "
        "weights file there manually")


def try_import(module_name: str):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed (and this "
            "environment installs nothing)") from e


def require_version(min_version, max_version=None):
    """Reference: fluid/framework.py require_version — assert the installed
    framework version is in [min_version, max_version]."""
    import itertools
    import re

    from .. import __version__

    def parse(v):
        # leading digits of each dot segment; '1rc0' -> 1, 'dev' -> 0
        out = []
        for p in str(v).split("."):
            m = re.match(r"\d+", p)
            out.append(int(m.group()) if m else 0)
        return out

    def cmp(a, b):
        for x, y in itertools.zip_longest(a, b, fillvalue=0):
            if x != y:
                return -1 if x < y else 1
        return 0

    cur = parse(__version__)
    if cmp(parse(min_version), cur) > 0:
        raise RuntimeError(
            f"requires version >= {min_version}, installed {__version__}")
    if max_version is not None and cmp(parse(max_version), cur) < 0:
        raise RuntimeError(
            f"requires version <= {max_version}, installed {__version__}")


class OpLastCheckpointChecker:
    """Reference: utils/op_version.py — queries op-version compatibility
    checkpoints. Ops here version with the package, so every op reports
    the package version with no extra attrs."""

    def get_op_attrs(self, op_name):
        return {}

    def get_version(self, op_name):
        from .. import __version__
        return __version__


# profiler facade (reference: utils/profiler.py over fluid profiler)
class ProfilerOptions:
    def __init__(self, options=None):
        self.options = {
            "state": "All", "sorted_key": "default", "tracer_level": "Default",
            "batch_range": [0, 100], "output_thread_detail": False,
            "profile_path": "/tmp/profile",
            "timeline_path": "/tmp/timeline", "op_summary_path": None,
        }
        if options is not None:
            self.options.update(options)

    def with_state(self, state):
        new = ProfilerOptions(dict(self.options))
        new.options["state"] = state
        return new

    def __getitem__(self, name):
        return self.options[name]


class Profiler:
    """Reference: utils/profiler.py Profiler — start/stop facade over the
    native profiler (csrc RecordEvent ring + chrome-trace export)."""

    def __init__(self, enabled=True, options=None):
        self.enabled = enabled
        self.profiler_options = options or ProfilerOptions()
        self._running = False

    def start(self):
        if self.enabled and not self._running:
            from .. import profiler as prof
            prof.start_profiler(self.profiler_options["tracer_level"])
            self._running = True

    def stop(self):
        if self._running:
            from .. import profiler as prof
            prof.stop_profiler(self.profiler_options["sorted_key"],
                               self.profiler_options["profile_path"])
            self._running = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def record_step(self, change_profiler_status=True):
        pass  # steps are delimited by RecordEvent scopes here


_profiler_singleton = None


def get_profiler():
    global _profiler_singleton
    if _profiler_singleton is None:
        _profiler_singleton = Profiler()
    return _profiler_singleton


class unique_name:  # namespace-style module shim (reference: utils/unique_name)
    """Reference: `paddle.utils.unique_name` (fluid/unique_name.py):
    generate/guard/switch over a process-wide name registry."""
    _counters = {}

    @staticmethod
    def generate(key):
        n = unique_name._counters.get(key, 0)
        unique_name._counters[key] = n + 1
        return f"{key}_{n}"

    @staticmethod
    def switch(new_generator=None):
        old = dict(unique_name._counters)
        unique_name._counters = {} if new_generator is None \
            else new_generator
        return old

    @staticmethod
    def guard(new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            old = unique_name.switch({} if new_generator is None
                                     else new_generator)
            try:
                yield
            finally:
                unique_name._counters = old
        return _guard()


class image_util:  # namespace shim (reference: utils/image_util.py)
    """Reference: utils/image_util.py — PIL-based image resize/crop helpers
    used by old detection reader scripts."""

    @staticmethod
    def resize_image(img, target_size):
        from PIL import Image
        return img.resize((target_size, target_size), Image.BILINEAR)

    @staticmethod
    def crop_image(img, box):
        return img.crop(tuple(int(v) for v in box))


from . import cpp_extension  # noqa: F401,E402
from . import download  # noqa: F401,E402
