"""Cached dataset/model download (reference:
`python/paddle/utils/download.py` — `get_path_from_url`, DATA_HOME cache,
md5 validation, retries).

Zero-egress environments: callers (vision/text datasets) catch the
download failure and fall back to their synthetic generators, so tests
never need the network; when the network exists the real files land in
the same cache layout the reference uses.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import time
import zipfile

DATA_HOME = os.path.expanduser(
    os.environ.get("PTPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))
WEIGHTS_HOME = os.path.expanduser(
    os.environ.get("PTPU_WEIGHTS_HOME", "~/.cache/paddle_tpu/hapi"))

DOWNLOAD_RETRY_LIMIT = 3


def _md5check(path: str, md5sum: str | None) -> bool:
    if not md5sum:
        return True
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def _download(url: str, root_dir: str, md5sum: str | None = None,
              timeout: float = 30.0) -> str:
    os.makedirs(root_dir, exist_ok=True)
    fname = os.path.join(root_dir, url.split("/")[-1].split("?")[0])
    if os.path.exists(fname) and _md5check(fname, md5sum):
        return fname
    import urllib.request
    last = None
    for attempt in range(DOWNLOAD_RETRY_LIMIT):
        try:
            tmp = fname + ".tmp"
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            if not _md5check(tmp, md5sum):
                os.remove(tmp)
                raise IOError(f"md5 mismatch for {url}")
            os.replace(tmp, fname)
            return fname
        except Exception as e:  # noqa: BLE001 — retry then surface
            last = e
            time.sleep(min(2 ** attempt, 5))
    raise RuntimeError(f"download failed after {DOWNLOAD_RETRY_LIMIT} "
                       f"tries: {url} ({last})")


def _top_dir(names, dst):
    """Extracted location: the archive's single top-level entry when it
    has one (the common dataset layout), else the extraction root."""
    tops = {n.split("/")[0] for n in names if n and not n.startswith("/")}
    if len(tops) == 1:
        return os.path.join(dst, next(iter(tops)))
    return dst


def _decompress(fname: str) -> str:
    if tarfile.is_tarfile(fname):
        dst = os.path.dirname(fname)
        with tarfile.open(fname) as tf:
            tf.extractall(dst, filter="data")
            return _top_dir(tf.getnames(), dst)
    if zipfile.is_zipfile(fname):
        dst = os.path.dirname(fname)
        with zipfile.ZipFile(fname) as zf:
            zf.extractall(dst)
            return _top_dir(zf.namelist(), dst)
    return fname


def get_path_from_url(url: str, root_dir: str = DATA_HOME,
                      md5sum: str | None = None,
                      check_exist: bool = True,
                      decompress: bool = False) -> str:
    """Download `url` into the cache (once) and return the local path
    (reference: `download.py get_path_from_url`)."""
    path = _download(url, root_dir, md5sum)
    if decompress:
        return _decompress(path)
    return path


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
