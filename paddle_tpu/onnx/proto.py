"""Minimal protobuf wire-format writer/reader for ONNX.

The environment has no `onnx` package, so `paddle_tpu.onnx.export` emits
the ONNX ModelProto wire format directly (reference consumer:
python/paddle/onnx/export.py delegates to the external paddle2onnx
package; here the emitter is self-contained). Field numbers follow
onnx/onnx.proto (IR version 7 / opset 13 era); only the message subset
the exporter needs is modeled.

Wire format: each field is a varint key ``(field_number << 3) | wire_type``
followed by a varint (type 0), 8 bytes (type 1), length-delimited bytes
(type 2) or 4 bytes (type 5). Nested messages are length-delimited.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

# -------------------------------------------------------------- data types
# onnx.TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = range(1, 10)
FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
BFLOAT16 = 16

NP_TO_ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16,
    np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8,
    np.dtype(np.int16): INT16,
    np.dtype(np.uint16): UINT16,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.uint32): UINT32,
    np.dtype(np.uint64): UINT64,
    np.dtype(np.bool_): BOOL,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR, AT_GRAPH = 1, 2, 3, 4, 5
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ---------------------------------------------------------------- encoding

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's complement, 10-byte varint
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def w_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def w_bytes(field: int, value: bytes) -> bytes:
    return _key(field, 2) + _varint(len(value)) + value


def w_string(field: int, value: str) -> bytes:
    return w_bytes(field, value.encode("utf-8"))


def w_float(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(value))


def w_packed_varints(field: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return w_bytes(field, payload)


def w_packed_floats(field: int, values) -> bytes:
    return w_bytes(field, struct.pack(f"<{len(values)}f", *values))


# ------------------------------------------------------------ ONNX builders

def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = NP_TO_ONNX[arr.dtype]
    msg = w_packed_varints(1, arr.shape)        # dims
    msg += w_varint(2, dt)                      # data_type
    msg += w_string(8, name)                    # name
    msg += w_bytes(9, arr.tobytes())            # raw_data
    return msg


def _attr(name: str, value) -> bytes:
    msg = w_string(1, name)
    if isinstance(value, float):
        msg += w_float(2, value) + w_varint(20, AT_FLOAT)
    elif isinstance(value, bool) or isinstance(value, int):
        msg += w_varint(3, int(value)) + w_varint(20, AT_INT)
    elif isinstance(value, str):
        msg += w_bytes(4, value.encode()) + w_varint(20, AT_STRING)
    elif isinstance(value, bytes):
        msg += w_bytes(4, value) + w_varint(20, AT_STRING)
    elif isinstance(value, np.ndarray):
        msg += w_bytes(5, tensor_proto(name, value)) + w_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            msg += w_packed_floats(7, value) + w_varint(20, AT_FLOATS)
        else:
            msg += w_packed_varints(8, value) + w_varint(20, AT_INTS)
    else:
        raise TypeError(f"unsupported attribute type {type(value)}")
    return msg


def node_proto(op_type: str, inputs: List[str], outputs: List[str],
               name: str = "", **attrs) -> bytes:
    msg = b"".join(w_string(1, s) for s in inputs)
    msg += b"".join(w_string(2, s) for s in outputs)
    if name:
        msg += w_string(3, name)
    msg += w_string(4, op_type)
    msg += b"".join(w_bytes(5, _attr(k, v)) for k, v in attrs.items())
    return msg


def value_info(name: str, dtype: np.dtype, shape: Tuple[int, ...]) -> bytes:
    dims = b"".join(w_bytes(1, w_varint(1, d)) for d in shape)
    shape_proto = dims
    tensor_type = w_varint(1, NP_TO_ONNX[np.dtype(dtype)]) \
        + w_bytes(2, shape_proto)
    type_proto = w_bytes(1, tensor_type)
    return w_string(1, name) + w_bytes(2, type_proto)


def graph_proto(name: str, nodes: List[bytes], initializers: List[bytes],
                inputs: List[bytes], outputs: List[bytes]) -> bytes:
    msg = b"".join(w_bytes(1, n) for n in nodes)
    msg += w_string(2, name)
    msg += b"".join(w_bytes(5, t) for t in initializers)
    msg += b"".join(w_bytes(11, v) for v in inputs)
    msg += b"".join(w_bytes(12, v) for v in outputs)
    return msg


def model_proto(graph: bytes, opset: int = 13,
                producer: str = "paddle_tpu") -> bytes:
    msg = w_varint(1, 7)                        # ir_version 7 (opset 13 era)
    msg += w_string(2, producer)
    msg += w_string(3, "0.0")
    msg += w_bytes(7, graph)
    msg += w_bytes(8, w_string(1, "") + w_varint(2, opset))  # opset_import
    return msg


# ---------------------------------------------------------------- decoding
# A reader for the same subset, used by the offline reference runtime to
# load exported models back without the onnx package.

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse_message(buf: bytes) -> Dict[int, list]:
    """Parse one message into {field_number: [raw values]} (wire order)."""
    fields: Dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def parse_packed_varints(buf: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        out.append(v)
    return out


def signed(v: int) -> int:
    return v - (1 << 64) if v >= 1 << 63 else v
