"""Offline ONNX reference runtime (numpy).

Loads models written by `paddle_tpu.onnx.export` — plain ONNX wire format —
and executes them with numpy, covering exactly the op set the converter
emits. Purpose: (a) numeric verification of exports in environments with no
onnxruntime (this image), (b) a last-resort CPU executor for exported
graphs. Not a general ONNX runtime.
"""
from __future__ import annotations

import math
import struct
from typing import Dict, List

import numpy as np

from . import proto


class Node:
    def __init__(self, op_type, inputs, outputs, attrs):
        self.op_type = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs


class OnnxModel:
    def __init__(self, nodes, initializers, input_names, output_names):
        self.nodes: List[Node] = nodes
        self.initializers: Dict[str, np.ndarray] = initializers
        self.input_names = input_names
        self.output_names = output_names


def _parse_attr(buf: bytes):
    f = proto.parse_message(buf)
    name = f[1][0].decode()
    atype = f.get(20, [0])[0]
    if atype == proto.AT_FLOAT:
        return name, struct.unpack("<f", f[2][0])[0]
    if atype == proto.AT_INT:
        return name, proto.signed(f[3][0])
    if atype == proto.AT_STRING:
        return name, f[4][0].decode()
    if atype == proto.AT_INTS:
        vals = []
        for raw in f.get(8, []):
            if isinstance(raw, bytes):
                vals.extend(proto.signed(v)
                            for v in proto.parse_packed_varints(raw))
            else:
                vals.append(proto.signed(raw))
        return name, vals
    if atype == proto.AT_FLOATS:
        vals = []
        for raw in f.get(7, []):
            vals.extend(struct.unpack(f"<{len(raw) // 4}f", raw))
        return name, list(vals)
    if atype == proto.AT_TENSOR:
        return name, _parse_tensor(f[5][0])
    raise ValueError(f"unsupported attribute type {atype}")


def _parse_tensor(buf: bytes) -> np.ndarray:
    f = proto.parse_message(buf)
    dims = []
    for raw in f.get(1, []):
        if isinstance(raw, bytes):
            dims.extend(proto.parse_packed_varints(raw))
        else:
            dims.append(raw)
    dt = proto.ONNX_TO_NP[f[2][0]]
    raw = f.get(9, [b""])[0]
    arr = np.frombuffer(raw, dtype=dt).reshape(dims)
    return arr.copy()


def _tensor_name(buf: bytes) -> str:
    return proto.parse_message(buf)[8][0].decode()


def load(path_or_bytes) -> OnnxModel:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as fh:
            data = fh.read()
    model = proto.parse_message(data)
    graph = proto.parse_message(model[7][0])
    nodes = []
    for nb in graph.get(1, []):
        nf = proto.parse_message(nb)
        attrs = dict(_parse_attr(a) for a in nf.get(5, []))
        nodes.append(Node(nf[4][0].decode(),
                          [s.decode() for s in nf.get(1, [])],
                          [s.decode() for s in nf.get(2, [])], attrs))
    inits = {_tensor_name(t): _parse_tensor(t)
             for t in graph.get(5, [])}
    def names(field):
        return [proto.parse_message(v)[1][0].decode()
                for v in graph.get(field, [])]
    return OnnxModel(nodes, inits, names(11), names(12))


# ---------------------------------------------------------------- executor

_erf = np.vectorize(math.erf)


def _pool2d(x, kernel, strides, pads, mode, count_include_pad=False):
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = strides
    ph0, pw0, ph1, pw1 = pads if len(pads) == 4 else (0, 0, 0, 0)
    fill = -np.inf if mode == "max" else 0.0
    xp = np.full((n, c, h + ph0 + ph1, w + pw0 + pw1), fill, x.dtype)
    xp[:, :, ph0:ph0 + h, pw0:pw0 + w] = x
    oh = (xp.shape[2] - kh) // sh + 1
    ow = (xp.shape[3] - kw) // sw + 1
    out = np.empty((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


def _conv2d(x, w, strides, pads, dilations, group):
    n, cin, h, wid = x.shape
    cout, cing, kh, kw = w.shape
    sh, sw = strides
    dh, dw = dilations
    ph0, pw0, ph1, pw1 = pads
    xp = np.zeros((n, cin, h + ph0 + ph1, wid + pw0 + pw1), x.dtype)
    xp[:, :, ph0:ph0 + h, pw0:pw0 + wid] = x
    ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (xp.shape[2] - ekh) // sh + 1
    ow = (xp.shape[3] - ekw) // sw + 1
    out = np.zeros((n, cout, oh, ow), np.result_type(x, w))
    og = cout // group
    for gi in range(group):
        xg = xp[:, gi * cing:(gi + 1) * cing]
        wg = w[gi * og:(gi + 1) * og]
        # im2col over the group
        cols = np.empty((n, cing, kh, kw, oh, ow), x.dtype)
        for a in range(kh):
            for b in range(kw):
                cols[:, :, a, b] = xg[:, :, a * dh:a * dh + oh * sh:sh,
                                      b * dw:b * dw + ow * sw:sw]
        out[:, gi * og:(gi + 1) * og] = np.einsum(
            "nkabhw,okab->nohw", cols, wg)
    return out


def run(model: OnnxModel, inputs: Dict[str, np.ndarray]) -> List[np.ndarray]:
    env: Dict[str, np.ndarray] = dict(model.initializers)
    env.update(inputs)

    for node in model.nodes:
        i = [env[x] for x in node.inputs]
        a = node.attrs
        t = node.op_type
        if t == "Add":
            o = [i[0] + i[1]]
        elif t == "Sub":
            o = [i[0] - i[1]]
        elif t == "Mul":
            o = [i[0] * i[1]]
        elif t == "Div":
            if np.issubdtype(np.result_type(i[0], i[1]), np.floating):
                o = [i[0] / i[1]]
            else:  # ONNX/XLA integer div truncates toward zero, not floor
                o = [(np.sign(i[0]) * np.sign(i[1]) *
                      (np.abs(i[0]) // np.abs(i[1]))).astype(
                          np.result_type(i[0], i[1]))]
        elif t == "MatMul":
            o = [np.matmul(i[0], i[1])]
        elif t == "Einsum":
            o = [np.einsum(a["equation"], *i)]
        elif t == "Conv":
            o = [_conv2d(i[0], i[1], a.get("strides", [1, 1]),
                         a.get("pads", [0, 0, 0, 0]),
                         a.get("dilations", [1, 1]), a.get("group", 1))]
        elif t == "MaxPool":
            o = [_pool2d(i[0], a["kernel_shape"], a.get("strides", [1, 1]),
                         a.get("pads", [0, 0, 0, 0]), "max")]
        elif t == "AveragePool":
            o = [_pool2d(i[0], a["kernel_shape"], a.get("strides", [1, 1]),
                         a.get("pads", [0, 0, 0, 0]), "avg")]
        elif t == "Max":
            o = [np.maximum(i[0], i[1])]
        elif t == "Min":
            o = [np.minimum(i[0], i[1])]
        elif t == "Neg":
            o = [-i[0]]
        elif t == "Abs":
            o = [np.abs(i[0])]
        elif t == "Exp":
            o = [np.exp(i[0])]
        elif t == "Log":
            o = [np.log(i[0])]
        elif t == "Tanh":
            o = [np.tanh(i[0])]
        elif t == "Sigmoid":
            o = [1.0 / (1.0 + np.exp(-i[0]))]
        elif t == "Sqrt":
            o = [np.sqrt(i[0])]
        elif t == "Reciprocal":
            o = [1.0 / i[0]]
        elif t == "Erf":
            o = [_erf(i[0]).astype(i[0].dtype)]
        elif t == "Pow":
            o = [np.power(i[0], i[1]).astype(i[0].dtype)]
        elif t == "Sign":
            o = [np.sign(i[0])]
        elif t in ("Floor", "Ceil"):
            o = [getattr(np, t.lower())(i[0])]
        elif t == "Round":
            o = [np.round(i[0])]
        elif t in ("Sin", "Cos", "Tan", "Sinh", "Cosh"):
            o = [getattr(np, t.lower())(i[0])]
        elif t in ("Asin", "Acos", "Atan", "Asinh", "Acosh", "Atanh"):
            o = [getattr(np, "arc" + t.lower()[1:])(i[0])]
        elif t == "And":
            o = [np.logical_and(i[0], i[1])]
        elif t == "Or":
            o = [np.logical_or(i[0], i[1])]
        elif t == "Xor":
            o = [np.logical_xor(i[0], i[1])]
        elif t == "Not":
            o = [np.logical_not(i[0])]
        elif t == "Mod":
            o = [np.fmod(i[0], i[1]) if a.get("fmod") else
                 np.mod(i[0], i[1])]
        elif t == "Identity":
            o = [i[0]]
        elif t == "Clip":
            o = [np.clip(i[0], i[1], i[2])]
        elif t == "Where":
            o = [np.where(i[0], i[1], i[2])]
        elif t == "Cast":
            o = [i[0].astype(proto.ONNX_TO_NP[a["to"]])]
        elif t == "Equal":
            o = [i[0] == i[1]]
        elif t == "Less":
            o = [i[0] < i[1]]
        elif t == "LessOrEqual":
            o = [i[0] <= i[1]]
        elif t == "Greater":
            o = [i[0] > i[1]]
        elif t == "GreaterOrEqual":
            o = [i[0] >= i[1]]
        elif t == "ReduceSum":
            axes = tuple(int(v) for v in i[1]) if len(i) > 1 else None
            o = [np.sum(i[0], axis=axes, keepdims=bool(a.get(
                "keepdims", 1)))]
        elif t in ("ReduceMax", "ReduceMin", "ReduceProd", "ReduceMean"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod, "ReduceMean": np.mean}[t]
            o = [fn(i[0], axis=tuple(a["axes"]),
                    keepdims=bool(a.get("keepdims", 1)))]
        elif t == "ArgMax":
            o = [np.argmax(i[0], axis=a["axis"]).astype(np.int64)]
        elif t == "ArgMin":
            o = [np.argmin(i[0], axis=a["axis"]).astype(np.int64)]
        elif t == "Reshape":
            o = [i[0].reshape([int(v) for v in i[1]])]
        elif t == "Transpose":
            o = [np.transpose(i[0], a["perm"])]
        elif t == "Expand":
            o = [np.broadcast_to(i[0], [int(v) for v in i[1]]).copy()]
        elif t == "Concat":
            o = [np.concatenate(i, axis=a["axis"])]
        elif t == "Slice":
            starts, ends = i[1], i[2]
            axes = i[3] if len(i) > 3 else np.arange(len(starts))
            steps = i[4] if len(i) > 4 else np.ones(len(starts), np.int64)
            sl = [slice(None)] * i[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                s, e, st = int(s), int(e), int(st)
                lo = None if (st < 0 and s == -1) else s
                hi = None if (st < 0 and e <= -(1 << 62)) else e
                sl[int(ax)] = slice(lo, hi, st)
            o = [i[0][tuple(sl)]]
        elif t == "Gather":
            o = [np.take(i[0], i[1].astype(np.int64), axis=a.get(
                "axis", 0))]
        elif t == "CumSum":
            o = [np.cumsum(i[0], axis=int(i[1]))]
        elif t == "Pad":
            pads = [int(v) for v in i[1]]
            half = len(pads) // 2
            width = list(zip(pads[:half], pads[half:]))
            cval = float(i[2]) if len(i) > 2 else 0.0
            o = [np.pad(i[0], width, constant_values=cval)]
        else:
            raise NotImplementedError(f"reference runtime: op {t}")
        for nm, val in zip(node.outputs, o):
            env[nm] = val
    return [env[nm] for nm in model.output_names]
