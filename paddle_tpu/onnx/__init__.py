"""`paddle.onnx` equivalent (reference: python/paddle/onnx/export.py — a
thin wrapper over the external paddle2onnx package, which walks the
ProgramDesc op graph).

TPU-native design: the exporter traces the layer's forward to a jaxpr —
the same IR every transform here uses — and emits the ONNX ModelProto wire
format directly (`converter.py` + `proto.py`; no onnx package needed).
Parameters become initializers, so the `.onnx` file is self-contained and
loadable by any ONNX runtime. `reference_runtime.py` is a numpy executor
for the emitted op set, used to verify exports offline.

Primitives with no ONNX mapping raise `UnsupportedPrimitive`; pass
`fallback_stablehlo=True` to write the StableHLO `.pdmodel` artifact
instead (the TPU deployment format, `paddle_tpu.jit.save`).
"""
from __future__ import annotations

import warnings

from .converter import UnsupportedPrimitive, trace_to_onnx  # noqa: F401
from . import proto, reference_runtime  # noqa: F401


def export(layer, path, input_spec=None, opset_version=13,
           fallback_stablehlo=False, **configs):
    """Export `layer` to a real ONNX protobuf at `<path>.onnx`.

    Reference: onnx/export.py `paddle.onnx.export`. Returns the written
    path. `input_spec` is a list of `paddle_tpu.static.InputSpec` (or
    arrays) describing example inputs; shapes are exported statically.
    """
    import jax.numpy as jnp
    from ..nn.layer import buffer_state, functional_call, trainable_state
    from ..static import InputSpec

    if input_spec is None:
        raise ValueError("paddle_tpu.onnx.export requires input_spec")
    if opset_version < 13:
        # the converter emits opset-13 op forms (ReduceSum axes input,
        # Clip min/max inputs, Pad pads input, Slice starts/ends inputs);
        # reference scripts pass the old default of 9 — clamp, don't break
        warnings.warn(
            f"opset_version={opset_version} not supported; emitting "
            "opset 13 op forms instead", UserWarning, stacklevel=2)
        opset_version = 13
    example = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            if any(d in (None, -1) for d in spec.shape):
                # ONNX dims here are static (taken from traced avals)
                warnings.warn(
                    f"dynamic dims in {list(spec.shape)} are exported "
                    "statically as 1; re-export per shape or use "
                    "jit.save (StableHLO) for shape polymorphism",
                    UserWarning, stacklevel=2)
            shape = [1 if d in (None, -1) else int(d) for d in spec.shape]
            example.append(jnp.zeros(shape, spec.dtype or jnp.float32))
        else:
            example.append(jnp.asarray(spec))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    params = trainable_state(layer)
    buffers = buffer_state(layer)

    def fwd(*args):
        out, _ = functional_call(layer, params, *args, buffers=buffers)
        return out

    if path.endswith(".onnx"):
        path = path[:-len(".onnx")]
    try:
        model_bytes = trace_to_onnx(
            fwd, example,
            input_names=[f"x{i}" for i in range(len(example))],
            opset=opset_version)
    except UnsupportedPrimitive as e:
        if not fallback_stablehlo:
            raise
        warnings.warn(
            f"ONNX conversion failed ({e}); writing StableHLO .pdmodel "
            "artifact instead (loadable with paddle_tpu.jit.load).",
            UserWarning, stacklevel=2)
        from ..jit import save as jit_save
        jit_save(layer, path, input_spec=input_spec)
        return path + ".pdmodel"
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()
    out_path = path + ".onnx"
    with open(out_path, "wb") as fh:
        fh.write(model_bytes)
    return out_path
