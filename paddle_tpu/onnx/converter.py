"""jaxpr → ONNX graph converter.

Reference: python/paddle/onnx/export.py (delegates to the external
paddle2onnx converter, which walks the ProgramDesc op graph). The TPU-native
equivalent walks the *jaxpr* of the layer's forward — the same IR every
other transform here uses — and emits one ONNX node (or a small cluster)
per primitive. Parameters closed over the trace arrive as jaxpr consts and
become ONNX initializers, so the exported file is self-contained.

Static shapes only (ONNX dims are taken from traced avals). Higher-order
primitives (pjit/custom_jvp/remat/closed_call) are inlined recursively.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import proto


class UnsupportedPrimitive(NotImplementedError):
    pass


class _Graph:
    """Accumulates nodes/initializers and names jaxpr vars."""

    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.names: Dict[int, str] = {}   # id(var) -> name
        self._counter = 0
        self._init_cache: Dict[bytes, str] = {}

    def fresh(self, hint: str = "t") -> str:
        self._counter += 1
        return f"{hint}_{self._counter}"

    def name_of(self, var) -> str:
        if isinstance(var, jcore.Literal):
            arr = np.asarray(var.val)
            return self.constant(arr)
        key = id(var)
        if key not in self.names:
            self.names[key] = self.fresh("v")
        return self.names[key]

    def constant(self, arr: np.ndarray, hint: str = "const") -> str:
        arr = np.asarray(arr)
        if arr.dtype == np.dtype(jnp.bfloat16):
            arr = arr.astype(np.float32)
        cache_key = arr.tobytes() + str(arr.dtype).encode() \
            + str(arr.shape).encode()
        if cache_key in self._init_cache:
            return self._init_cache[cache_key]
        name = self.fresh(hint)
        self.initializers.append(proto.tensor_proto(name, arr))
        self._init_cache[cache_key] = name
        return name

    def add(self, op_type: str, inputs: List[str], n_out: int = 1,
            outputs=None, **attrs) -> List[str]:
        if outputs is None:
            outputs = [self.fresh(op_type.lower()) for _ in range(n_out)]
        self.nodes.append(proto.node_proto(op_type, inputs, outputs,
                                           name=self.fresh("n"), **attrs))
        return outputs

    def set_name(self, var, name: str):
        self.names[id(var)] = name


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "neg": "Neg", "abs": "Abs",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "erf": "Erf", "pow": "Pow", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round",
    "sin": "Sin", "cos": "Cos", "tan": "Tan",
    "asin": "Asin", "acos": "Acos", "atan": "Atan",
    "sinh": "Sinh", "cosh": "Cosh",
    "asinh": "Asinh", "acosh": "Acosh", "atanh": "Atanh",
    "stop_gradient": "Identity", "copy": "Identity",
    # sharding annotations are compile-time placement hints; the
    # serialized inference graph is single-host, so they erase
    "sharding_constraint": "Identity",
    # name_p is a debug-labelling no-op
    "name": "Identity",
}

# ONNX And/Or/Not/Xor are boolean-only; jax's primitives are bitwise
_LOGICAL = {"and": "And", "or": "Or", "not": "Not", "xor": "Xor"}

_COMPARE = {"eq": "Equal", "lt": "Less", "le": "LessOrEqual",
            "gt": "Greater", "ge": "GreaterOrEqual"}

_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
           "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}

def _conv(g: _Graph, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    if any(d != 1 for d in p.get("lhs_dilation") or ()):
        raise UnsupportedPrimitive("conv with lhs_dilation (transpose conv)")
    if p.get("batch_group_count", 1) != 1:
        raise UnsupportedPrimitive("conv batch_group_count != 1")
    n_sp = len(dn.lhs_spec) - 2
    lhs_perm = (dn.lhs_spec[0], dn.lhs_spec[1]) + tuple(dn.lhs_spec[2:])
    rhs_perm = (dn.rhs_spec[0], dn.rhs_spec[1]) + tuple(dn.rhs_spec[2:])
    x, w = ins
    if lhs_perm != tuple(range(n_sp + 2)):
        x = g.add("Transpose", [x], perm=list(lhs_perm))[0]
    if rhs_perm != tuple(range(n_sp + 2)):
        w = g.add("Transpose", [w], perm=list(rhs_perm))[0]
    pads = [int(b) for b, _ in p["padding"]] + [int(e) for _, e in
                                               p["padding"]]
    y = g.add("Conv", [x, w],
              strides=[int(s) for s in p["window_strides"]],
              pads=pads,
              dilations=[int(d) for d in p.get("rhs_dilation")
                         or (1,) * n_sp],
              group=int(p.get("feature_group_count", 1)))[0]
    out_spec = (dn.out_spec[0], dn.out_spec[1]) + tuple(dn.out_spec[2:])
    if out_spec != tuple(range(n_sp + 2)):
        inv = [0] * (n_sp + 2)
        for i, s in enumerate(out_spec):
            inv[s] = i
        y = g.add("Transpose", [y], perm=inv)[0]
    return [y]


def _pool(g: _Graph, eqn, ins, kind: str):
    p = eqn.params
    wd = tuple(int(d) for d in p["window_dimensions"])
    ws = tuple(int(s) for s in (p["window_strides"] or (1,) * len(wd)))
    pad = tuple(p["padding"])
    if any(d != 1 for d in p.get("base_dilation") or ()):
        raise UnsupportedPrimitive("reduce_window base_dilation")
    if any(d != 1 for d in p.get("window_dilation") or ()):
        raise UnsupportedPrimitive("reduce_window window_dilation")
    post_perm = None
    if len(wd) == 4 and wd[0] == 1 and wd[-1] == 1 and ws[0] == 1 \
            and ws[-1] == 1 and pad[0] == (0, 0) and pad[-1] == (0, 0) \
            and (wd[1] != 1 or wd[2] != 1):
        # channels-last window (NHWC trunks): pool in NCHW between
        # transposes — ONNX pooling is channels-first only
        ins = [g.add("Transpose", ins, perm=[0, 3, 1, 2])[0]]
        wd = (1, 1, wd[1], wd[2])
        ws = (1, 1, ws[1], ws[2])
        pad = ((0, 0), (0, 0), pad[1], pad[2])
        post_perm = [0, 2, 3, 1]
    if wd[0] != 1 or wd[1] != 1 or ws[0] != 1 or ws[1] != 1 \
            or pad[0] != (0, 0) or pad[1] != (0, 0):
        raise UnsupportedPrimitive(
            f"reduce_window over non-spatial dims: {wd}")
    pads = [int(b) for b, _ in pad[2:]] + [int(e) for _, e in pad[2:]]
    if kind == "max":
        y = g.add("MaxPool", ins, kernel_shape=list(wd[2:]),
                  strides=list(ws[2:]), pads=pads)[0]
    else:
        # sum pool = AveragePool(count_include_pad) * prod(window)
        y = g.add("AveragePool", ins, kernel_shape=list(wd[2:]),
                  strides=list(ws[2:]), pads=pads, count_include_pad=1)[0]
        out_dt = np.dtype(eqn.outvars[0].aval.dtype)
        if out_dt == np.dtype(jnp.bfloat16):
            out_dt = np.dtype(np.float32)
        scale = g.constant(np.asarray(float(np.prod(wd)), out_dt),
                           "winsize")
        y = g.add("Mul", [y, scale])[0]
    if post_perm is not None:
        return g.add("Transpose", [y], perm=post_perm)
    return [y]


def _gather(g: _Graph, eqn, ins):
    """jnp.take(operand, idx, axis=k) pattern → ONNX Gather."""
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, start = eqn.invars
    op_shape = tuple(operand.aval.shape)
    slice_sizes = tuple(int(s) for s in p["slice_sizes"])
    if len(dn.start_index_map) != 1 or getattr(
            dn, "operand_batching_dims", ()):
        raise UnsupportedPrimitive("general gather")
    axis = dn.start_index_map[0]
    if dn.collapsed_slice_dims != (axis,) or slice_sizes[axis] != 1:
        raise UnsupportedPrimitive("general gather (non-take pattern)")
    for d in range(len(op_shape)):
        if d != axis and slice_sizes[d] != op_shape[d]:
            raise UnsupportedPrimitive("general gather (partial slice)")
    idx_shape = tuple(start.aval.shape)
    if idx_shape[-1] != 1:
        raise UnsupportedPrimitive("gather with index vector > 1")
    idx = g.add("Reshape", [ins[1], g.constant(
        np.asarray(idx_shape[:-1], np.int64), "shape")])[0]
    batch_rank = len(idx_shape) - 1
    # ONNX Gather(axis=k) output = op[:k] + idx_shape + op[k+1:]; the jaxpr
    # gather matches only when its offset dims sit at exactly those slots.
    out_rank = len(op_shape) - 1 + batch_rank
    expect_offset = tuple(range(axis)) \
        + tuple(range(axis + batch_rank, out_rank))
    if tuple(dn.offset_dims) != expect_offset:
        raise UnsupportedPrimitive("gather offset dims not take-like")
    return g.add("Gather", [ins[0], idx], axis=int(axis))


def _convert_eqn(g: _Graph, eqn):
    prim = eqn.primitive.name
    ins = [g.name_of(v) for v in eqn.invars]

    if prim in ("jit", "pjit", "closed_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "remat2", "checkpoint", "custom_jvp_call_jaxpr"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
            or eqn.params.get("fun_jaxpr")
        if inner is None:
            raise UnsupportedPrimitive(f"{prim} without inner jaxpr")
        if hasattr(inner, "jaxpr"):          # ClosedJaxpr
            consts, inner = inner.consts, inner.jaxpr
        else:
            consts = ()
        for cv, cval in zip(inner.constvars, consts):
            g.set_name(cv, g.constant(np.asarray(cval), "const"))
        for iv, outer in zip(inner.invars, eqn.invars):
            g.set_name(iv, g.name_of(outer))
        for ieq in inner.eqns:
            _convert_eqn(g, ieq)
        for ov, outer in zip(inner.outvars, eqn.outvars):
            # alias: emit Identity so the outer name exists as node output
            g.add("Identity", [g.name_of(ov)],
                  outputs=[g.name_of(outer)])
        return

    def out(names):
        for v, n in zip(eqn.outvars, names):
            g.set_name(v, n)

    if prim in _ELEMENTWISE:
        out(g.add(_ELEMENTWISE[prim], ins))
    elif prim in _LOGICAL:
        if np.dtype(eqn.invars[0].aval.dtype) != np.bool_:
            raise UnsupportedPrimitive(
                f"bitwise {prim} on non-bool inputs (ONNX opset 13 has "
                "no integer bitwise ops)")
        out(g.add(_LOGICAL[prim], ins))
    elif prim in _COMPARE:
        out(g.add(_COMPARE[prim], ins))
    elif prim == "ne":
        e = g.add("Equal", ins)[0]
        out(g.add("Not", [e]))
    elif prim == "rsqrt":
        s = g.add("Sqrt", ins)[0]
        out(g.add("Reciprocal", [s]))
    elif prim == "log1p":
        one = g.constant(np.asarray(1.0, eqn.invars[0].aval.dtype))
        s = g.add("Add", [ins[0], one])[0]
        out(g.add("Log", [s]))
    elif prim == "expm1":
        e = g.add("Exp", ins)[0]
        one = g.constant(np.asarray(1.0, eqn.invars[0].aval.dtype))
        out(g.add("Sub", [e, one]))
    elif prim == "erfc":
        e = g.add("Erf", ins)[0]
        one = g.constant(np.asarray(1.0, eqn.invars[0].aval.dtype))
        out(g.add("Sub", [one, e]))
    elif prim == "square":
        out(g.add("Mul", [ins[0], ins[0]]))
    elif prim == "integer_pow":
        y = eqn.params["y"]
        exp = g.constant(np.asarray(float(y), eqn.invars[0].aval.dtype))
        out(g.add("Pow", [ins[0], exp]))
    elif prim == "rem":
        out(g.add("Mod", ins, fmod=1))
    elif prim == "clamp":
        lo, x, hi = ins
        out(g.add("Clip", [x, lo, hi]))
    elif prim == "select_n":
        if len(ins) != 3:
            raise UnsupportedPrimitive("select_n with >2 cases")
        out(g.add("Where", [ins[0], ins[2], ins[1]]))
    elif prim == "convert_element_type":
        dt = proto.NP_TO_ONNX.get(np.dtype(eqn.params["new_dtype"]))
        if dt is None:   # bf16 → export as f32
            dt = proto.FLOAT
        out(g.add("Cast", ins, to=int(dt)))
    elif prim == "dot_general":
        dn = eqn.params["dimension_numbers"]
        lhs_rank = len(eqn.invars[0].aval.shape)
        rhs_rank = len(eqn.invars[1].aval.shape)
        (lc, rc), (lb, rb) = dn
        # MatMul only when rhs is a plain matrix/vector: for rhs rank >= 3
        # with no batch dims, XLA's output layout (lhs free dims then rhs
        # free dims) differs from numpy/ONNX MatMul broadcasting.
        if not lb and rhs_rank <= 2 and len(lc) == 1 \
                and lc[0] == lhs_rank - 1 \
                and rc[0] == rhs_rank - 2 + (rhs_rank == 1):
            out(g.add("MatMul", ins))
        else:
            # general case: transpose each side to
            # [batch..., free..., contract...] / [batch, contract, free],
            # flatten to rank-3, batched MatMul, reshape to XLA's output
            # order (batch, lhs free, rhs free). Standard ops only —
            # ONNX Einsum is opset-12+ and absent from many runtimes
            # (incl. csrc/ptpu_predictor.cc)
            lshape = tuple(eqn.invars[0].aval.shape)
            rshape = tuple(eqn.invars[1].aval.shape)
            lfree = [d for d in range(lhs_rank)
                     if d not in lb and d not in lc]
            rfree = [d for d in range(rhs_rank)
                     if d not in rb and d not in rc]

            def prod(dims, shape):
                p = 1
                for d in dims:
                    p *= shape[d]
                return p

            bsz = prod(lb, lshape)
            msz, ksz = prod(lfree, lshape), prod(lc, lshape)
            nsz = prod(rfree, rshape)
            lt = g.add("Transpose", [ins[0]],
                       perm=[int(d) for d in (*lb, *lfree, *lc)])[0]
            l3 = g.add("Reshape", [lt, g.constant(
                np.asarray([bsz, msz, ksz], np.int64), "lshape")])[0]
            rt = g.add("Transpose", [ins[1]],
                       perm=[int(d) for d in (*rb, *rc, *rfree)])[0]
            r3 = g.add("Reshape", [rt, g.constant(
                np.asarray([bsz, ksz, nsz], np.int64), "rshape")])[0]
            mm = g.add("MatMul", [l3, r3])[0]
            oshape = np.asarray(eqn.outvars[0].aval.shape, np.int64)
            out(g.add("Reshape", [mm, g.constant(oshape, "oshape")]))
    elif prim == "conv_general_dilated":
        out(_conv(g, eqn, ins))
    elif prim == "reduce_window_max":
        out(_pool(g, eqn, ins, "max"))
    elif prim == "reduce_window_sum":
        out(_pool(g, eqn, ins, "sum"))
    elif prim in _REDUCE:
        axes = [int(a) for a in eqn.params["axes"]]
        if prim == "reduce_sum":
            ax = g.constant(np.asarray(axes, np.int64), "axes")
            out(g.add("ReduceSum", [ins[0], ax], keepdims=0))
        else:
            out(g.add(_REDUCE[prim], ins, axes=axes, keepdims=0))
    elif prim in ("argmax", "argmin"):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise UnsupportedPrimitive(f"{prim} over multiple axes")
        op = "ArgMax" if prim == "argmax" else "ArgMin"
        y = g.add(op, ins, axis=int(axes[0]), keepdims=0)[0]
        dt = proto.NP_TO_ONNX[np.dtype(eqn.params["index_dtype"])]
        out(g.add("Cast", [y], to=int(dt)))
    elif prim in ("reshape", "squeeze", "expand_dims"):
        shape = g.constant(np.asarray(eqn.outvars[0].aval.shape, np.int64),
                           "shape")
        out(g.add("Reshape", [ins[0], shape]))
    elif prim == "transpose":
        out(g.add("Transpose", ins,
                  perm=[int(p) for p in eqn.params["permutation"]]))
    elif prim == "broadcast_in_dim":
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(eqn.outvars[0].aval.shape)
        bdims = tuple(eqn.params["broadcast_dimensions"])
        mid = [1] * len(out_shape)
        for i, d in enumerate(bdims):
            mid[d] = in_shape[i]
        x = ins[0]
        if tuple(mid) != in_shape:
            x = g.add("Reshape", [x, g.constant(
                np.asarray(mid, np.int64), "shape")])[0]
        if tuple(mid) != out_shape:
            x = g.add("Expand", [x, g.constant(
                np.asarray(out_shape, np.int64), "shape")])[0]
            out([x])
        elif x == ins[0]:
            out(g.add("Identity", [x]))
        else:
            out([x])
    elif prim == "concatenate":
        out(g.add("Concat", ins, axis=int(eqn.params["dimension"])))
    elif prim == "slice":
        p = eqn.params
        rank = len(eqn.invars[0].aval.shape)
        starts = g.constant(np.asarray(p["start_indices"], np.int64), "st")
        ends = g.constant(np.asarray(p["limit_indices"], np.int64), "en")
        axes = g.constant(np.asarray(range(rank), np.int64), "ax")
        steps = g.constant(np.asarray(p["strides"] or [1] * rank,
                                      np.int64), "sp")
        out(g.add("Slice", [ins[0], starts, ends, axes, steps]))
    elif prim == "rev":
        # Reverse via Slice with negative steps
        rank = len(eqn.invars[0].aval.shape)
        dims = [int(d) for d in eqn.params["dimensions"]]
        starts = g.constant(np.asarray([-1] * len(dims), np.int64), "st")
        ends = g.constant(np.asarray([np.iinfo(np.int64).min + 1]
                                     * len(dims), np.int64), "en")
        axes = g.constant(np.asarray(dims, np.int64), "ax")
        steps = g.constant(np.asarray([-1] * len(dims), np.int64), "sp")
        out(g.add("Slice", [ins[0], starts, ends, axes, steps]))
    elif prim == "pad":
        p = eqn.params["padding_config"]
        if any(i != 0 for _, _, i in p):
            raise UnsupportedPrimitive("pad with interior padding")
        if any(lo < 0 or hi < 0 for lo, hi, _ in p):
            raise UnsupportedPrimitive("negative padding")
        pads = [lo for lo, _, _ in p] + [hi for _, hi, _ in p]
        out(g.add("Pad", [ins[0],
                          g.constant(np.asarray(pads, np.int64), "pads"),
                          ins[1]]))
    elif prim == "iota":
        dt = np.dtype(eqn.params["dtype"])
        shape = tuple(eqn.params["shape"])
        dim = int(eqn.params["dimension"])
        arr = np.arange(shape[dim], dtype=dt if dt != np.dtype(
            jnp.bfloat16) else np.float32)
        # store only the 1-D arange; broadcast with graph ops so a
        # (1,1,S,S) position/mask iota doesn't embed an S*S initializer
        mid = [shape[dim] if i == dim else 1 for i in range(len(shape))]
        x = g.constant(arr, "iota")
        x = g.add("Reshape", [x, g.constant(
            np.asarray(mid, np.int64), "shape")])[0]
        if tuple(mid) != shape:
            x = g.add("Expand", [x, g.constant(
                np.asarray(shape, np.int64), "shape")])[0]
        out([x])
    elif prim == "gather":
        out(_gather(g, eqn, ins))
    elif prim == "cumsum":
        ax = g.constant(np.asarray(eqn.params["axis"], np.int64), "axis")
        if eqn.params.get("reverse"):
            raise UnsupportedPrimitive("reverse cumsum")
        out(g.add("CumSum", [ins[0], ax]))
    elif prim == "dynamic_slice":
        starts = []
        for v in eqn.invars[1:]:
            if not isinstance(v, jcore.Literal):
                raise UnsupportedPrimitive("dynamic_slice (dynamic start)")
            starts.append(int(v.val))
        sizes = eqn.params["slice_sizes"]
        rank = len(sizes)
        st = g.constant(np.asarray(starts, np.int64), "st")
        en = g.constant(np.asarray([s + z for s, z in zip(starts, sizes)],
                                   np.int64), "en")
        ax = g.constant(np.asarray(range(rank), np.int64), "ax")
        out(g.add("Slice", [ins[0], st, en, ax]))
    elif prim == "split":
        # one Slice per piece: ONNX Split exists, but Slice keeps the
        # artifact runnable on the minimal runtimes
        axis = int(eqn.params["axis"])
        sizes = [int(v) for v in eqn.params["sizes"]]
        ax = g.constant(np.asarray([axis], np.int64), "ax")
        offset = 0
        names = []
        for sz in sizes:
            st = g.constant(np.asarray([offset], np.int64), "st")
            en = g.constant(np.asarray([offset + sz], np.int64), "en")
            names.append(g.add("Slice", [ins[0], st, en, ax])[0])
            offset += sz
        out(names)
    elif prim == "scan":
        _scan_unroll(g, eqn, ins)
    else:
        raise UnsupportedPrimitive(
            f"primitive '{prim}' has no ONNX mapping")


_SCAN_UNROLL_MAX = 512


def _scan_unroll(g: _Graph, eqn, ins):
    """lax.scan → static unroll (length is a traced constant). ONNX has
    Scan/Loop, but unrolling keeps artifacts runnable on minimal
    runtimes (the C predictor, the numpy reference) — RNN/LSTM/GRU
    layers run time steps through scan (`nn/layer_rnn.py RNN.forward`),
    so this is what makes CRNN-class models exportable. Body vars are
    REBOUND each iteration (names are keyed by var identity)."""
    p = eqn.params
    length = int(p["length"])
    if length == 0:
        raise UnsupportedPrimitive("scan with length 0 (empty unroll "
                                   "would emit a zero-input Concat)")
    if length > _SCAN_UNROLL_MAX:
        raise UnsupportedPrimitive(
            f"scan length {length} > unroll limit {_SCAN_UNROLL_MAX}")
    closed = p["jaxpr"]
    consts_j, body = closed.consts, closed.jaxpr
    n_consts = int(p["num_consts"])
    n_carry = int(p["num_carry"])
    reverse = bool(p.get("reverse", False))
    const_names = list(ins[:n_consts])
    carry_names = list(ins[n_consts:n_consts + n_carry])
    xs_names = list(ins[n_consts + n_carry:])
    n_ys = len(eqn.outvars) - n_carry
    ys_steps = [[] for _ in range(n_ys)]
    order = range(length - 1, -1, -1) if reverse else range(length)
    for t in order:
        xt_names = []
        for xi, xn in enumerate(xs_names):
            idx = g.constant(np.asarray(t, np.int64), "t")
            xt = g.add("Gather", [xn, idx], axis=0)[0]
            # 0-d index round-trips as [1] through the wire format on
            # some runtimes; pin the step slice to the body's static
            # input shape
            bshape = tuple(
                body.invars[n_consts + n_carry + xi].aval.shape)
            xt = g.add("Reshape", [xt, g.constant(
                np.asarray(bshape, np.int64), "xshape")])[0]
            xt_names.append(xt)
        # clear every body binding from the previous iteration
        for v in list(body.invars) + list(body.constvars):
            g.names.pop(id(v), None)
        for beq in body.eqns:
            for ov in beq.outvars:
                g.names.pop(id(ov), None)
        for cv, cval in zip(body.constvars, consts_j):
            g.set_name(cv, g.constant(np.asarray(cval), "const"))
        for bv, nm in zip(body.invars,
                          const_names + carry_names + xt_names):
            g.set_name(bv, nm)
        for beq in body.eqns:
            _convert_eqn(g, beq)
        outs_names = [g.name_of(ov) for ov in body.outvars]
        carry_names = list(outs_names[:n_carry])
        for yi in range(n_ys):
            ys_steps[yi].append(outs_names[n_carry + yi])
    for ci in range(n_carry):
        g.add("Identity", [carry_names[ci]],
              outputs=[g.name_of(eqn.outvars[ci])])
    for yi in range(n_ys):
        steps = ys_steps[yi]
        if reverse:
            steps = steps[::-1]
        y_shape = tuple(eqn.outvars[n_carry + yi].aval.shape)
        step_shape = g.constant(
            np.asarray((1,) + y_shape[1:], np.int64), "yshape")
        expanded = [g.add("Reshape", [s_, step_shape])[0] for s_ in steps]
        if len(expanded) == 1:
            g.add("Identity", expanded,
                  outputs=[g.name_of(eqn.outvars[n_carry + yi])])
        else:
            g.add("Concat", expanded, axis=0,
                  outputs=[g.name_of(eqn.outvars[n_carry + yi])])


def jaxpr_to_onnx_graph(closed_jaxpr, input_names=None,
                        graph_name="paddle_tpu"):
    """Convert a ClosedJaxpr (static shapes) to a serialized GraphProto."""
    jaxpr = closed_jaxpr.jaxpr
    g = _Graph()
    for cv, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
        g.set_name(cv, g.constant(np.asarray(cval), "param"))
    inputs = []
    for i, iv in enumerate(jaxpr.invars):
        name = (input_names[i] if input_names and i < len(input_names)
                else f"input_{i}")
        g.set_name(iv, name)
        dt = np.dtype(iv.aval.dtype)
        if dt == np.dtype(jnp.bfloat16):
            dt = np.dtype(np.float32)
        inputs.append(proto.value_info(name, dt, tuple(iv.aval.shape)))
    for eqn in jaxpr.eqns:
        _convert_eqn(g, eqn)
    outputs = []
    for i, ov in enumerate(jaxpr.outvars):
        name = g.name_of(ov)
        if isinstance(ov, (jcore.Literal,)) or name in (
                g.name_of(iv) for iv in jaxpr.invars):
            name2 = g.add("Identity", [name],
                          outputs=[g.fresh("output")])[0]
            name = name2
        dt = np.dtype(ov.aval.dtype)
        if dt == np.dtype(jnp.bfloat16):
            dt = np.dtype(np.float32)
        outputs.append(proto.value_info(name, dt, tuple(ov.aval.shape)))
    return proto.graph_proto(graph_name, g.nodes, g.initializers,
                             inputs, outputs)


def trace_to_onnx(fn, example_args, input_names=None, opset=13):
    """Trace `fn(*example_args)` and return serialized ONNX ModelProto."""
    closed = jax.make_jaxpr(fn)(*example_args)
    graph = jaxpr_to_onnx_graph(closed, input_names=input_names)
    return proto.model_proto(graph, opset=opset)
