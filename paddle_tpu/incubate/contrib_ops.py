"""`paddle.fluid.contrib.layers` op tranche — the TBCNN/PaddleRec/HDRNet
contrib kernels, re-designed as closed-form XLA programs.

References:
- tree_conv: `paddle/fluid/operators/tree_conv_op.cc` +
  `operators/math/tree2col.{h,cc}` (TBCNN continuous binary tree conv,
  python wrapper `fluid/contrib/layers/nn.py:401`).
- rank_attention: `paddle/fluid/operators/rank_attention_op.cu` +
  `rank_attention.cu.h` (PaddleRec rank-aware attention, wrapper
  `fluid/contrib/layers/nn.py:1320`).
- bilateral_slice: `paddle/fluid/operators/bilateral_slice_op.cu`
  (HDRNet bilateral-grid slice+apply, wrapper
  `fluid/contrib/layers/nn.py:1498`).

Design: none of these translate the reference loops. The tree traversal
becomes adjacency-matrix powers (one [N, N] matmul per depth level — MXU
work, not pointer chasing); the CUDA gather kernels become jnp gathers
with mask algebra, so every op is jit-able and differentiable end to end
(the reference backward kernels are subsumed by autodiff).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["tree_conv", "rank_attention", "bilateral_slice"]


def _tree_conv_single(feats, edges, filt, max_depth):
    """One tree: feats [N, F], edges [M, 2] int (1-indexed, (0,0) pad),
    filt [F, 3, O, K]."""
    n = feats.shape[0]
    u = edges[:, 0].astype(jnp.int32)
    v = edges[:, 1].astype(jnp.int32)
    valid = (u > 0) & (v > 0)
    ui = jnp.where(valid, u - 1, 0)
    vi = jnp.where(valid, v - 1, 0)
    # adjacency (parent -> child), padded edges scatter 0
    adj = jnp.zeros((n, n), feats.dtype).at[ui, vi].add(
        valid.astype(feats.dtype))
    adj = jnp.minimum(adj, 1.0)
    # sibling stats per edge: index = 1 + #earlier edges with same parent,
    # pclen = #children of the parent (reference TreeNode(index+1, sz))
    m = edges.shape[0]
    same = (u[:, None] == u[None, :]) & valid[:, None] & valid[None, :]
    earlier = same & (jnp.arange(m)[None, :] < jnp.arange(m)[:, None])
    index = 1.0 + jnp.sum(earlier, axis=1).astype(feats.dtype)
    pclen = jnp.sum(same, axis=1).astype(feats.dtype)
    sib_e = jnp.where(pclen == 1.0, 0.5, (index - 1.0)
                      / jnp.maximum(pclen - 1.0, 1.0))
    # per-node sibling position (each node has one parent in a tree)
    sib = jnp.zeros((n,), feats.dtype).at[vi].add(
        jnp.where(valid, sib_e, 0.0))
    # depth-d reachability walk: R_0 = I, R_d = (R_{d-1} @ adj) > 0
    depth = jnp.float32(max_depth).astype(feats.dtype)
    reach = jnp.eye(n, dtype=feats.dtype)
    t_mat = jnp.zeros((n, n), feats.dtype)
    c_mat = jnp.zeros((n, n), feats.dtype)
    c2_mat = jnp.zeros((n, n), feats.dtype)
    for d in range(max_depth):
        eta_t = (depth - d) / depth
        c = 1.0 - eta_t
        t_mat = t_mat + eta_t * reach
        c_mat = c_mat + c * reach
        c2_mat = c2_mat + c * c * reach
        reach = jnp.minimum(reach @ adj, 1.0)
    # patch features for the three filter slots:
    # eta_l = c*sib, eta_r = c*(1 - eta_l) = c - c^2*sib  (tree2col.h —
    # note eta_r folds eta_l itself, not the bare sibling fraction)
    p_t = t_mat @ feats
    p_l = (c_mat * sib[None, :]) @ feats
    p_r = c_mat @ feats - (c2_mat * sib[None, :]) @ feats
    out = (jnp.einsum("nc,cok->nok", p_t, filt[:, 0])
           + jnp.einsum("nc,cok->nok", p_l, filt[:, 1])
           + jnp.einsum("nc,cok->nok", p_r, filt[:, 2]))
    return out


def tree_conv(nodes_vector, edge_set, filter, max_depth=2, name=None):
    """TBCNN tree convolution (`tree_conv_op.cc`, `math/tree2col.cc`).

    nodes_vector [B, N, F]; edge_set [B, M, 2] int directed parent->child
    edges, 1-indexed node ids, (0, 0) rows are padding; filter
    [F, 3, output_size, num_filters] (the reference's W_shape).
    Returns [B, N, output_size, num_filters]: row u is the tree-patch
    convolution rooted at node u+1. The reference emits rows only for
    nodes reachable from the edge list; here every row is produced
    (static shapes) — nodes without edges reduce to the self-patch
    eta_t=1 term, which is 0 for zero-padded feature rows.

    Patch weights (tree2col.h TreeNode): eta_t = (D - depth)/D,
    eta_l = (1 - eta_t) * sib, eta_r = (1 - eta_t) * (1 - eta_l) with
    sib = 0.5 for an only child else (index-1)/(pclen-1).
    """
    feats = jnp.asarray(nodes_vector)
    edges = jnp.asarray(edge_set)
    filt = jnp.asarray(filter)
    if feats.ndim == 2:
        return _tree_conv_single(feats, edges, filt, int(max_depth))
    return jax.vmap(lambda f, e: _tree_conv_single(
        f, e, filt, int(max_depth)))(feats, edges)


def rank_attention(input, rank_offset, rank_param, max_rank=3, max_size=0,
                   name=None):
    """PaddleRec rank attention (`rank_attention_op.cu`).

    input [N, d]; rank_offset [N, 2*max_rank+1] int32 — column 0 is the
    instance's own rank (1-based, <=0 missing), then (rank_k, index_k)
    pairs naming the k-th related instance's rank and its row in
    `input`; rank_param [d*max_rank*max_rank, p].

    For instance i with own rank `lower`, block k of the expanded input
    is input[index_k] and block k of the expanded parameter is
    rank_param rows [(lower*max_rank + rank_k)*d : ...+d]; the output is
    the [1, max_rank*d] x [max_rank*d, p] product (zero blocks where
    either rank is missing — the CUDA kernel's `continue`).
    `max_size` is a CUDA workspace hint; unused here.
    """
    x = jnp.asarray(input)
    ro = jnp.asarray(rank_offset, jnp.int32)
    param = jnp.asarray(rank_param)
    n, d = x.shape
    p = param.shape[1]
    lower = ro[:, 0] - 1                              # [N]
    faster = ro[:, 1::2] - 1                          # [N, max_rank]
    index = ro[:, 2::2]                               # [N, max_rank]
    ok = (lower[:, None] >= 0) & (faster >= 0)        # [N, max_rank]
    xg = x[jnp.clip(index, 0, n - 1)]                 # [N, max_rank, d]
    xg = jnp.where(ok[..., None], xg, 0.0)
    start = lower[:, None] * max_rank + faster        # [N, max_rank]
    start = jnp.clip(start, 0, max_rank * max_rank - 1)
    p3 = param.reshape(max_rank * max_rank, d, p)
    pg = p3[start]                                    # [N, max_rank, d, p]
    pg = jnp.where(ok[..., None, None], pg, 0.0)
    return jnp.einsum("nkd,nkdp->np", xg, pg)


def _tent(x):
    return jnp.maximum(1.0 - jnp.abs(x), 0.0)


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """HDRNet bilateral-grid slice + apply (`bilateral_slice_op.cu`).

    x [B, Ci, H, W]; guide [B, H, W] in [0, 1]; grid
    [B, Co*(Ci [+1 if has_offset]), gd, gh, gw]. Per output pixel the
    grid is sampled trilinearly at (gx, gy, guide*gd) — tent weights on
    all three axes, the z tent using the kernel's smoothed |.|
    (sqrt(z^2 + 1e-8)) — and the sampled [Co, Ci(+1)] matrix is applied
    as a per-pixel affine map. Returns [B, Co, H, W].
    """
    x = jnp.asarray(x)
    g = jnp.asarray(guide)
    grid = jnp.asarray(grid)
    b, ci, h, w = x.shape
    gd, gh, gw = grid.shape[2:]
    stride = ci + 1 if has_offset else ci
    co = grid.shape[1] // stride
    gxx = (jnp.arange(w, dtype=x.dtype) + 0.5) * gw / w     # [W]
    gyy = (jnp.arange(h, dtype=x.dtype) + 0.5) * gh / h     # [H]
    gz = g * gd                                             # [B, H, W]
    fx = jnp.floor(gxx - 0.5).astype(jnp.int32)
    fy = jnp.floor(gyy - 0.5).astype(jnp.int32)
    fz = jnp.floor(gz - 0.5).astype(jnp.int32)
    grid5 = grid.reshape(b, co, stride, gd, gh, gw)
    coeff = jnp.zeros((b, co, stride, h, w), x.dtype)
    for dx in range(2):
        xx = fx + dx
        x_ = jnp.clip(xx, 0, gw - 1)
        wx = _tent(xx.astype(x.dtype) + 0.5 - gxx)          # [W]
        for dy in range(2):
            yy = fy + dy
            y_ = jnp.clip(yy, 0, gh - 1)
            wy = _tent(yy.astype(x.dtype) + 0.5 - gyy)      # [H]
            for dz in range(2):
                zz = fz + dz                                 # [B, H, W]
                z_ = jnp.clip(zz, 0, gd - 1)
                # kernel WeightZ: smoothed-abs tent
                dzv = zz.astype(x.dtype) + 0.5 - gz
                wz = jnp.maximum(
                    1.0 - jnp.sqrt(dzv * dzv + 1e-8), 0.0)   # [B, H, W]
                # advanced indexing groups the indexed axes in FRONT:
                # grid5[b, :, :, z, y, x] -> [B, H, W, Co, S]
                gat = grid5[jnp.arange(b)[:, None, None],
                            :, :, z_, y_[None, :, None],
                            x_[None, None, :]]
                gat = jnp.transpose(gat, (0, 3, 4, 1, 2))    # B,Co,S,H,W
                wgt = (wz[:, None, None]
                       * wy[None, None, None, :, None]
                       * wx[None, None, None, None, :])
                coeff = coeff + gat * wgt
    out = jnp.einsum("boshw,bshw->bohw", coeff[:, :, :ci], x)
    if has_offset:
        out = out + coeff[:, :, ci]
    return out
