"""`paddle.incubate` equivalent."""
from . import optimizer  # noqa: F401
from .optimizer import (  # noqa: F401
    ExponentialMovingAverage,
    GradientMergeOptimizer,
    LookAhead,
    ModelAverage,
)
from . import checkpoint  # noqa: F401
from . import contrib_ops  # noqa: F401
from .contrib_ops import (  # noqa: F401
    bilateral_slice,
    rank_attention,
    tree_conv,
)
