"""`paddle.inference` equivalent — deployment API.

Reference: `AnalysisPredictor`/`AnalysisConfig`
(`inference/api/analysis_predictor.cc:381`, `paddle_inference_api.h`) —
a C++ engine that loads a ProgramDesc, runs IR optimization passes, and
executes with zero-copy tensors. TPU-native: the saved artifact is
shape-polymorphic StableHLO (`paddle_tpu.jit.save`); "optimization passes"
are XLA's; `Predictor.run` feeds/fetches jax arrays (zero-copy on device).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np


class Config:
    """Reference: AnalysisConfig. Model path + toggles (most reference
    knobs — TensorRT, MKLDNN, IR passes — have no TPU meaning and are
    accepted as no-ops for script parity)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._path = prog_file
        self._device = None
        self._memory_pool_mb = 0

    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._path = prog_file

    def model_dir(self):
        return self._path

    # accepted-for-parity toggles. Each is a documented no-op on the TPU
    # backend (XLA owns memory/fusion/threading); a one-time info warning
    # tells the caller instead of silently ignoring the request
    # (VERDICT r2 weak 8).
    @staticmethod
    def _parity_noop(name: str, subsumed_by: str):
        import warnings
        warnings.warn(
            f"inference.Config.{name}() is accepted for API parity but is "
            f"a no-op on the TPU backend ({subsumed_by})", stacklevel=3)

    def enable_use_gpu(self, memory_pool_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_mb
        self._parity_noop("enable_use_gpu",
                          "device placement is the TPU runtime's")

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        self._parity_noop("enable_memory_optim",
                          "XLA buffer assignment already reuses memory")

    def switch_ir_optim(self, flag=True):
        self._parity_noop("switch_ir_optim",
                          "XLA runs its own pass pipeline")

    def enable_mkldnn(self):
        self._parity_noop("enable_mkldnn", "XLA CPU backend")

    def set_cpu_math_library_num_threads(self, n):
        self._parity_noop("set_cpu_math_library_num_threads",
                          "XLA thread pool")


class Tensor:
    """Zero-copy-ish handle (reference: ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._feeds[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._outputs[self.name])

    def shape(self):
        src = self._owner._feeds if self._is_input else self._owner._outputs
        return list(np.asarray(src[self.name]).shape)


class Predictor:
    """Reference: AnalysisPredictor (`analysis_predictor.cc:381` Run,
    `:889` ZeroCopyRun). Wraps the jit-saved StableHLO artifact."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        if config.model_dir() is None:
            raise ValueError("Config has no model path")
        self._layer = jit_load(config.model_dir())
        self._feeds = {}
        self._outputs = {}
        self._scrub_dropout()

    def _scrub_dropout(self):
        """Load-time dropout-removal (reference:
        `OptimizeInferenceProgram` running delete_dropout_op_pass).

        `jit.save` traces in eval mode AND runs the registered
        `dropout_removal` ir pass before export, so a paddle_tpu
        artifact arrives clean and this check is the cheap no-op
        branch. An artifact that still carries RNG ops (produced by
        external tooling or an old save) is serialized StableHLO — the
        jaxpr-level pass cannot see inside it, so the predictor flags
        it loudly instead of serving nondeterministic outputs
        silently."""
        self._dropout_scrubbed = False
        try:
            mlir = self._layer._exported.mlir_module()
        except Exception:
            return
        if "stablehlo.rng" in mlir or "threefry" in mlir:
            import warnings
            warnings.warn(
                "inference.Predictor: the loaded artifact samples "
                "randomness (train-mode dropout was baked in at "
                "export). Re-export it with paddle_tpu.jit.save — its "
                "dropout_removal pass strips the mask — or apply "
                "ir.Program.apply_pass('dropout_removal') before "
                "export.", stacklevel=3)
        else:
            self._dropout_scrubbed = True

    def get_input_names(self) -> List[str]:
        return self._layer.input_names() or ["x"]

    def get_output_names(self) -> List[str]:
        return list(self._outputs.keys()) or ["out"]

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, False)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Positional-run (new API) or handle-based (copy_from_cpu then
        run())."""
        if inputs is None:
            names = self.get_input_names()
            inputs = [self._feeds[n] for n in names]
        outs = self._layer(*[np.asarray(a) for a in inputs])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        self._outputs = {f"out{i}" if i else "out": o
                         for i, o in enumerate(outs)}
        return [np.asarray(o) for o in outs]

    def warmup(self, *example_inputs, block: bool = True):
        """Ahead-of-time compile for the given input shapes/dtypes.

        The reference predictor pays its optimization cost in
        `OptimizeInferenceProgram` at load; XLA pays at first run per
        shape. `warmup` moves that cost to deployment init: run once on
        zeros with the serving shapes so the compiled executable is
        cached before traffic arrives.
        """
        zeros = [np.zeros(np.asarray(a).shape,
                          np.asarray(a).dtype) if not hasattr(a, "shape")
                 else np.zeros(tuple(a.shape), getattr(a, "dtype",
                                                       np.float32))
                 for a in example_inputs]
        outs = self._layer(*zeros)
        if block:
            jax.block_until_ready(outs)
        return self

    def clone(self) -> "Predictor":
        """Share the loaded model (and XLA compile cache) — reference
        `AnalysisPredictor::Clone` for multi-thread serving."""
        p = object.__new__(Predictor)
        p._layer = self._layer
        p._feeds = {}
        p._outputs = {}
        return p


class PredictorPool:
    """Reference: `paddle_infer::services::PredictorPool` — N cloned
    predictors over one loaded model for concurrent serving threads."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)


def create_predictor(config: Config) -> Predictor:
    """Reference: CreatePaddlePredictor (`analysis_predictor.cc:1183`)."""
    return Predictor(config)


def create_server(model_path: str, **kwargs):
    """Start the C-hosted concurrent serving runtime for an exported
    ONNX artifact: dynamic micro-batching, N parallel predictor
    instances, framed-HMAC TCP data plane (csrc/ptpu_serving.cc). See
    paddle_tpu.inference.serving.create_server for the knobs; returns
    an InferenceServer (use .client() for a connected
    InferenceClient)."""
    from .serving import create_server as _cs
    return _cs(model_path, **kwargs)


class DataType:
    """Reference: paddle_infer.DataType enum (inference/api/paddle_api.h)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType:
    """Reference: paddle_infer.PlaceType — kCPU/kGPU/kXPU; TPU is the
    accelerator here."""
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 3


class PrecisionType:
    """Reference: AnalysisConfig::Precision."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


_NUM_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
              DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
              DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype) -> int:
    """Reference: paddle_infer.get_num_bytes_of_data_type."""
    if dtype not in _NUM_BYTES:
        raise ValueError(f"unknown inference DataType {dtype!r}")
    return _NUM_BYTES[dtype]


def get_version() -> str:
    """Reference: paddle_infer.get_version."""
    from .. import __version__
    import jax
    return f"paddle_tpu {__version__} (jax {jax.__version__})"
