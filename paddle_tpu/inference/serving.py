"""Concurrent native serving — Python face of csrc/ptpu_serving.cc.

Reference counterpart: `paddle_infer::services::PredictorPool` plus
the request server every production deployment wraps around it. Here
the whole hot path is C-hosted: `create_server` starts the in-process
C serving runtime (dynamic micro-batcher flushing at `max_batch` rows
or `deadline_us`, N parallel predictor instances each on a private
worker sub-pool, a pre-planned bucket ladder of batch sizes so batched
runs stay on the zero-alloc arena path), serving u32-LE framed INFER
requests over TCP behind the same HMAC-SHA256 nonce handshake the PS
data plane uses. Python only starts/stops the server and polls stats;
no request ever touches the interpreter.

`InferenceClient` is the reference client: it speaks the framed wire
protocol directly (handshake, META, INFER), supports `infer` (one
round trip) and `infer_many` (pipelined — several requests in flight
on one connection, which is how a single client still benefits from
server-side batching).
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac as _hmac
import json
import os
import socket
import struct
import time
from typing import List, Optional, Sequence

import numpy as np

WIRE_VERSION = 1
TAG_INFER_REQ = 0x60
TAG_INFER_REP = 0x61
TAG_INFER_ERR = 0x62
TAG_META_REQ = 0x63
TAG_META_REP = 0x64
# KV-cached decode ops (r9) — csrc/ptpu_serving.cc kTagDecode* twins.
# Layouts (payload offsets): OPEN [ver][tag][u64 req_id]; SESS
# [ver][tag][u64 req_id][u64 session]; STEP [ver][tag][u64 req_id]
# [u64 session][i64 token]; REP [ver][tag][u64 req_id][u64 session]
# [u32 n][f32 x n]; CLOSE mirrors SESS.
TAG_DECODE_OPEN = 0x65
TAG_DECODE_SESS = 0x66
TAG_DECODE_STEP = 0x67
TAG_DECODE_REP = 0x68
TAG_DECODE_CLOSE = 0x69
# Paged-engine ops (r12) — csrc/ptpu_serving.cc kTagDecodeOpen2/
# OpenRep/Fork twins. Layouts (payload offsets): OPEN2 [ver][tag]
# [u64 req_id][u32 n_tokens @10][u32 flags=0 @14][n x i64 @18] — the
# server adopts cached prefix pages, chunk-prefills the rest through
# the decode batcher, and answers OPEN_REP [ver][tag][u64 req_id]
# [u64 session][u32 adopted @18][u32 n_logits @22][f32 x n @26] with
# the LAST prompt token's logits. FORK [ver][tag][u64 req_id]
# [u64 session] clones a session copy-on-write -> SESS echo of the
# NEW id. (+8 on every offset past [ver][tag] for traced v2 frames.)
TAG_DECODE_OPEN2 = 0x6a
TAG_DECODE_OPEN_REP = 0x6b
TAG_DECODE_FORK = 0x6c
# Speculative-decoding ops (r13) — csrc/ptpu_serving.cc
# kTagDecodeSpec* twins. Layouts (payload offsets): SPEC_OPEN
# [ver][tag][u64 req_id][u32 n_tokens @10][u32 flags @14, bit0 =
# sampling][u64 seed @18][n x i64 @26] — the server opens a target
# session AND its draft twin, prefills the prompt, and answers
# SPEC_REP with the first generated token. SPEC_STEP [ver][tag]
# [u64 req_id][u64 session] runs ONE draft/verify round. SPEC_REP
# [ver][tag][u64 req_id][u64 session][u32 accepted @18][u32 n @22]
# [n x i64 @26]: on open accepted = prefix-cache adopted tokens and
# n = 1; on step accepted = draft tokens accepted this round and
# n = accepted + 1 (the bonus/correction token is target-sourced).
# (+8 on every offset past [ver][tag] for traced v2 frames.)
TAG_DECODE_SPEC_OPEN = 0x6d
TAG_DECODE_SPEC_STEP = 0x6e
TAG_DECODE_SPEC_REP = 0x6f

# Traced frames (ISSUE 10): version 2 inserts a client-generated
# [u64-LE trace id] between [ver][tag] and the v1 body; REP frames for
# a traced request echo the same extension (ERR frames stay v1). The
# server records the request's lifecycle spans (net.read ->
# batch.queue -> batch.fill -> predictor.run -> net.flush) under that
# id — GET /tracez returns them, and profiler.timeline.
# merge_request_trace joins them with the client-side spans captured
# by InferenceClient(trace=True). C twins: kSvWireVersionTraced /
# ptpu::trace::kTraceExt in csrc/ptpu_serving.cc.
WIRE_VERSION_TRACED = 2
TRACE_EXT = 8

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")


def _frame_trace_id(f) -> int:
    """Echoed trace id of a reply frame (0 for v1 frames)."""
    if len(f) >= 2 + TRACE_EXT and f[0] == WIRE_VERSION_TRACED:
        return _U64.unpack_from(f, 2)[0]
    return 0


def _frame_base(f) -> int:
    """Byte shift of every v1 body offset for this frame (0 or 8)."""
    return TRACE_EXT if f[0] == WIRE_VERSION_TRACED else 0


def _now_us() -> int:
    """CLOCK_MONOTONIC microseconds — same clock domain as the C
    server's steady_clock span stamps, so same-host client/server
    spans merge with no skew correction."""
    return time.monotonic_ns() // 1000

# ONNX TensorProto codes on the wire
_DT_F32, _DT_I32, _DT_I64 = 1, 6, 7
_NP_TO_DT = {"float32": _DT_F32, "int32": _DT_I32, "int64": _DT_I64}
_DT_TO_NP = {_DT_F32: np.float32, _DT_I32: np.int32, _DT_I64: np.int64}


class ServingError(RuntimeError):
    """Server-side INFER_ERR reply (validation or execution failure)."""


class InferenceServer:
    """One C-hosted serving runtime bound to a TCP port.

    The handle owns the C server: predictor instances, batcher threads
    and the accept loop all live in _native_predictor.so. `stats()` /
    `config()` parse the C snapshots; `stop()` (or GC) tears the
    runtime down."""

    def __init__(self, model_path: str, port: int = 0,
                 authkey: Optional[bytes] = None, max_batch: int = 8,
                 deadline_us: int = 2000, instances: int = 2,
                 threads_per_instance: int = 0,
                 loopback_only: bool = True,
                 decode_model: Optional[str] = None,
                 kv_sessions: int = 0,
                 http_port: Optional[int] = None,
                 spec_model: Optional[str] = None,
                 spec_verify_model: Optional[str] = None):
        from ..core.native import _predictor_lib
        lib = _predictor_lib()
        if not getattr(lib, "_ptpu_has_serving", False):
            raise RuntimeError(
                "native serving unavailable (stale "
                "_native_predictor.so: delete it and re-import)")
        self._lib = lib
        self.authkey = authkey if authkey is not None else os.urandom(16)
        err = ctypes.create_string_buffer(512)
        has_http = getattr(lib, "_ptpu_has_http", False)
        has_spec = getattr(lib, "_ptpu_has_spec", False)
        if http_port is not None and not has_http:
            raise RuntimeError(
                "telemetry HTTP needs the r10 ABI (stale "
                "_native_predictor.so: delete it and re-import)")
        if (spec_model or spec_verify_model) and not has_spec:
            raise RuntimeError(
                "speculative decoding needs the r13 ABI (stale "
                "_native_predictor.so: delete it and re-import)")
        if spec_model or spec_verify_model:
            self._h = lib.ptpu_serving_start4(
                model_path.encode(),
                decode_model.encode() if decode_model else None,
                spec_model.encode() if spec_model else None,
                spec_verify_model.encode() if spec_verify_model
                else None, port, self.authkey, len(self.authkey),
                max_batch, deadline_us, instances,
                threads_per_instance, 1 if loopback_only else 0,
                kv_sessions, -1 if http_port is None else http_port,
                err, 512)
        elif has_http:
            self._h = lib.ptpu_serving_start3(
                model_path.encode(),
                decode_model.encode() if decode_model else None, port,
                self.authkey, len(self.authkey), max_batch, deadline_us,
                instances, threads_per_instance,
                1 if loopback_only else 0, kv_sessions,
                -1 if http_port is None else http_port, err, 512)
        elif decode_model is not None or kv_sessions:
            if not getattr(lib, "_ptpu_has_decode", False):
                raise RuntimeError(
                    "decode serving needs the r9 ABI (stale "
                    "_native_predictor.so: delete it and re-import)")
            self._h = lib.ptpu_serving_start2(
                model_path.encode(),
                decode_model.encode() if decode_model else None, port,
                self.authkey, len(self.authkey), max_batch, deadline_us,
                instances, threads_per_instance,
                1 if loopback_only else 0, kv_sessions, err, 512)
        else:
            self._h = lib.ptpu_serving_start(
                model_path.encode(), port, self.authkey,
                len(self.authkey), max_batch, deadline_us, instances,
                threads_per_instance, 1 if loopback_only else 0, err,
                512)
        if not self._h:
            raise RuntimeError("ptpu_serving_start: " +
                               err.value.decode())
        self.port = int(lib.ptpu_serving_port(self._h))
        # telemetry HTTP port (-1 when disabled); PTPU_NET_HTTP can
        # force it on even through the old start forms
        self.http_port = (int(lib.ptpu_serving_http_port(self._h))
                          if has_http else -1)

    def _handle(self):
        # a NULL handle would segfault inside the C runtime; fail here
        if not getattr(self, "_h", None):
            raise RuntimeError("InferenceServer is stopped")
        return self._h

    def config(self) -> dict:
        """Effective configuration (buckets built after probing,
        instances, input signature)."""
        return json.loads(
            self._lib.ptpu_serving_config_json(self._handle()).decode())

    def stats(self) -> dict:
        """{"server": wire counters, "batcher": batching counters +
        queue_depth/batch_fill/e2e_us/run_us log2 histograms,
        dynamic_shape_fallback}."""
        return json.loads(
            self._lib.ptpu_serving_stats_json(self._handle()).decode())

    def stats_reset(self) -> None:
        self._lib.ptpu_serving_stats_reset(self._handle())

    def prom_text(self) -> str:
        """Prometheus exposition text of the live stats — the same
        bytes ``GET /metrics`` serves (C-rendered; byte-identical to
        ``profiler.stats.prometheus_text(self.stats(),
        prefix="ptpu_serving")``)."""
        if not getattr(self._lib, "_ptpu_has_http", False):
            raise RuntimeError("prom_text needs the r10 ABI")
        return self._lib.ptpu_serving_prom_text(
            self._handle()).decode()

    def drain_begin(self) -> None:
        """Two-phase shutdown, half one: stop accepting framed
        connections and flip ``GET /healthz`` to 503 "draining" while
        existing connections (and the HTTP listener) keep answering —
        take the node out of the load balancer, let in-flight work
        finish, then call :meth:`stop`. Idempotent."""
        if not getattr(self._lib, "_ptpu_has_http", False):
            raise RuntimeError("drain_begin needs the r10 ABI")
        self._lib.ptpu_serving_drain_begin(self._handle())

    def client(self, host: str = "127.0.0.1",
               trace: bool = False) -> "InferenceClient":
        self._handle()   # a stopped server has no port to dial
        return InferenceClient(self.port, self.authkey, host=host,
                               trace=trace)

    def stop(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ptpu_serving_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:   # interpreter teardown
            pass


def create_server(model_path: str, **kwargs) -> InferenceServer:
    """Start the C serving runtime for an exported artifact.

    Keyword knobs: `port` (0 = pick free), `authkey` (bytes; random by
    default — read it back from `.authkey`), `max_batch`,
    `deadline_us`, `instances`, `threads_per_instance` (0 = split host
    cores evenly), `loopback_only`, `decode_model` (path of a KV
    decode-step artifact from models.gpt.export_gpt_decode — enables
    the DECODE wire ops), `kv_sessions` (max concurrent decode
    sessions; 0 = $PTPU_KV_SESSIONS, default 4096 paged / 64 legacy).

    Speculative decoding (r13): pass ``spec_model`` (a SMALL draft
    model's width-1 decode artifact) AND ``spec_verify_model`` (the
    TARGET model exported at width k+1 via
    ``models.gpt.export_gpt_decode(width=k+1)``) to enable the
    DECODE_SPEC wire ops — the server proposes k tokens per round with
    the draft, verifies all of them (+ the bonus position) in one
    batched multi-position target pass, and rolls rejected tokens back
    by truncating the session's paged block table. Greedy rounds
    reproduce non-speculative greedy decoding exactly; sampling rounds
    use the modified-rejection rule (distribution-exact). Knobs:
    ``PTPU_SPEC_K`` caps k below the verify artifact's width - 1.

    The decode plane defaults to the PAGED generation engine (r12):
    sessions draw fixed-size pages from one shared pool (RAM scales
    with tokens held, not sessions x max-context), prompts sent via
    ``client.decode_open(prompt=...)`` are chunk-prefilled server-side
    and served from the prefix cache, and steps batch onto a
    {1,2,4,...,B} bucket ladder. Env knobs: ``PTPU_KV_PAGE``
    (tokens/page, 16), ``PTPU_KV_POOL_TOKENS`` (pool size; default
    64 x context, or kv_sessions x context when kv_sessions is
    explicit), ``PTPU_KV_PREFIX`` (prefix cache on/off),
    ``PTPU_PREFILL_CHUNK`` (tokens admitted per session per chunk),
    ``PTPU_KV_PAGED=0`` (the r9 fixed-slot engine)."""
    return InferenceServer(model_path, **kwargs)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("serving connection closed")
        buf.extend(chunk)
    return bytes(buf)


class InferenceClient:
    """Framed-wire client for the native serving runtime.

    Connecting retries transient ``ECONNREFUSED``/``ECONNRESET``/
    EOF-before-nonce failures (server still starting, draining, or
    shedding above its max-conns cap) with exponential backoff for up
    to ``connect_retry_s`` seconds, then raises a clear
    :class:`ServingError`. A REJECTED handshake (wrong authkey) is
    never retried."""

    def __init__(self, port: int, authkey: bytes,
                 host: str = "127.0.0.1", timeout_s: float = 60.0,
                 connect_retry_s: float = 5.0, trace: bool = False):
        # trace=True sends v2 frames carrying a fresh 8-byte trace id
        # per request, checks the server's echo, and records a
        # client-side span per call into `trace_spans` — merge them
        # with the server's GET /tracez via
        # profiler.timeline.merge_request_trace. Only enable against
        # r10+ servers: old servers close on v2 frames.
        self.trace = trace
        self.trace_spans: List[dict] = []
        deadline = time.monotonic() + connect_retry_s
        delay = 0.02
        while True:
            sock = None
            try:
                sock = socket.create_connection((host, port),
                                                timeout=timeout_s)
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                self._sock = sock
                nonce = _read_exact(sock, 16)
                break
            # refused (starting), reset, or EOF-before-nonce (draining
            # / shed) — all transient; rejection happens after this
            # loop and is never retried
            except (ConnectionError, BrokenPipeError) as e:
                if sock is not None:
                    sock.close()
                if time.monotonic() + delay > deadline:
                    raise ServingError(
                        f"serving runtime at {host}:{port} not "
                        f"reachable within {connect_retry_s:.0f}s "
                        f"({type(e).__name__}: {e}) — server down, "
                        f"still starting, or shedding connections"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
        self._next_id = 0
        self._rbuf = bytearray(4096)
        mac = _hmac.new(authkey, nonce, hashlib.sha256).digest()
        self._sock.sendall(_U32.pack(len(mac)) + mac)
        if _read_exact(self._sock, 1) != b"\x01":
            raise ConnectionError("serving handshake rejected")

    # ------------------------------------------------------- framing
    def _send_frame(self, payload: bytes) -> None:
        self._sock.sendall(_U32.pack(len(payload)) + payload)

    def _read_frame(self) -> bytes:
        n = _U32.unpack(_read_exact(self._sock, 4))[0]
        return _read_exact(self._sock, n)

    # One bounded receive buffer per connection (ISSUE 17): the
    # pipelined *_many drains read every reply frame into this
    # bytearray via recv_into and parse a borrowed memoryview — no
    # fresh bytes object per frame. It grows to the largest frame
    # seen, then shrinks back to _RBUF_CAP once an oversized frame
    # has been consumed. Parsers copy what they keep (every returned
    # array owns its storage), so the view dies when the next frame
    # lands.
    _RBUF_CAP = 1 << 20

    def _read_frame_reused(self) -> memoryview:
        n = _U32.unpack(_read_exact(self._sock, 4))[0]
        buf = self._rbuf
        want = max(n, self._RBUF_CAP)
        if len(buf) < n or len(buf) > want:
            try:
                del buf[want:]          # shrink an oversized carryover
                if len(buf) < n:
                    buf.extend(bytes(n - len(buf)))
            except BufferError:
                # a caller kept the previous view alive — leave that
                # buffer to it and start a fresh one
                buf = self._rbuf = bytearray(n)
        mv = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(mv[got:n])
            if not r:
                raise ConnectionError("serving connection closed")
            got += r
        return mv[:n]

    def meta(self) -> dict:
        self._send_frame(bytes([WIRE_VERSION, TAG_META_REQ]))
        f = self._read_frame()
        if len(f) < 6 or f[1] != TAG_META_REP:
            raise ConnectionError("bad META reply")
        (mlen,) = _U32.unpack_from(f, 2)
        return json.loads(f[6:6 + mlen].decode())

    # ------------------------------------------------------- tracing
    @staticmethod
    def _new_trace_id() -> int:
        """A fresh nonzero 8-byte trace id."""
        tid = 0
        while not tid:
            tid = int.from_bytes(os.urandom(8), "little")
        return tid

    def _trace_begin(self):
        """-> (trace_id, t0_us) — (0, 0) when tracing is off."""
        if not self.trace:
            return 0, 0
        return self._new_trace_id(), _now_us()

    # client-side span list cap: a long-lived traced client (soak
    # test, always-on sidecar) must not grow memory without bound —
    # the OLDEST half is dropped past this, mirroring the server
    # ring's keep-the-newest semantics
    TRACE_SPANS_MAX = 4096

    def _trace_end(self, tid: int, t0_us: int, name: str,
                   f: bytes) -> None:
        """Record the client-side span and verify the server echo."""
        if not tid:
            return
        got = _frame_trace_id(f)
        # ERR replies are v1 by contract; REP frames must echo
        if f[1] not in (TAG_INFER_ERR,) and got != tid:
            raise ConnectionError(
                f"trace id echo mismatch: sent {tid:#x}, got {got:#x}")
        if len(self.trace_spans) >= self.TRACE_SPANS_MAX:
            del self.trace_spans[:self.TRACE_SPANS_MAX // 2]
        self.trace_spans.append({"trace_id": tid, "name": name,
                                 "t0_us": t0_us, "t1_us": _now_us()})

    # --------------------------------------------------------- infer
    def _encode_request(self, req_id: int,
                        arrays: Sequence[np.ndarray],
                        trace_id: int = 0) -> bytes:
        if trace_id:
            parts = [bytes([WIRE_VERSION_TRACED, TAG_INFER_REQ]),
                     _U64.pack(trace_id)]
        else:
            parts = [bytes([WIRE_VERSION, TAG_INFER_REQ])]
        parts += [_U64.pack(req_id), struct.pack("<H", len(arrays))]
        for a in arrays:
            a = np.ascontiguousarray(a)
            dt = _NP_TO_DT.get(a.dtype.name)
            if dt is None:
                raise TypeError(f"unsupported input dtype {a.dtype}")
            parts.append(bytes([dt, a.ndim]))
            parts.append(b"".join(_I64.pack(d) for d in a.shape))
            parts.append(a.tobytes())
        return b"".join(parts)

    @staticmethod
    def _decode_reply(f: bytes):
        """-> (req_id, outputs-list | ServingError). Server-side
        request errors come back as a VALUE so pipelined readers can
        keep draining the stream in sync; plain infer() raises it.
        Traced (v2) replies shift every body offset by TRACE_EXT."""
        base = _frame_base(f)
        req_id = _U64.unpack_from(f, 2 + base)[0]
        if f[1] == TAG_INFER_ERR:
            (mlen,) = _U32.unpack_from(f, 10 + base)
            return req_id, ServingError(
                bytes(f[14 + base:14 + base + mlen]).decode())
        if f[1] != TAG_INFER_REP:
            raise ConnectionError(f"unexpected reply tag {f[1]:#x}")
        (nout,) = struct.unpack_from("<H", f, 10 + base)
        off = 12 + base
        outs = []
        for _ in range(nout):
            nd = f[off]
            off += 1
            dims = [_I64.unpack_from(f, off + 8 * k)[0]
                    for k in range(nd)]
            off += 8 * nd
            n = int(np.prod(dims)) if dims else 1
            arr = np.frombuffer(f, np.float32, n, off).reshape(dims)
            off += n * 4
            outs.append(arr.copy())
        return req_id, outs

    def infer(self, *arrays) -> List[np.ndarray]:
        """One request, one reply (float32 outputs). Raises
        ServingError on a server-side INFER_ERR."""
        rid = self._next_id
        self._next_id += 1
        tid, t0 = self._trace_begin()
        self._send_frame(self._encode_request(rid, arrays, tid))
        f = self._read_frame()
        got_id, outs = self._decode_reply(f)
        if got_id != rid:
            raise ConnectionError(
                f"reply id {got_id} != request id {rid}")
        self._trace_end(tid, t0, "client.infer", f)
        if isinstance(outs, ServingError):
            raise outs
        return outs

    def infer_many(self, requests: Sequence[Sequence[np.ndarray]],
                   depth: int = 8, return_exceptions: bool = False):
        """Pipelined inference: keep up to `depth` requests in flight
        on this connection — a single client's requests then batch
        server-side. Results come back in request order. A per-request
        server error never desyncs the stream: every in-flight reply
        is still drained; with `return_exceptions` the failed entries
        are the ServingError instances, otherwise the first error
        re-raises after the pipeline is drained."""
        results: List[object] = [None] * len(requests)
        pending = {}
        sent = 0
        done = 0
        while done < len(requests):
            while sent < len(requests) and len(pending) < depth:
                rid = self._next_id
                self._next_id += 1
                tid, t0 = self._trace_begin()
                pending[rid] = (sent, tid, t0)
                self._send_frame(
                    self._encode_request(rid, requests[sent], tid))
                sent += 1
            f = self._read_frame_reused()
            got_id, outs = self._decode_reply(f)
            idx, tid, t0 = pending.pop(got_id)
            self._trace_end(tid, t0, "client.infer", f)
            results[idx] = outs
            done += 1
        if not return_exceptions:
            for r in results:
                if isinstance(r, ServingError):
                    raise r
        return results

    # -------------------------------------------------------- decode
    def _decode_reply_expect(self, want_tag: int, rid: int):
        f = self._read_frame()
        base = _frame_base(f)
        got = _U64.unpack_from(f, 2 + base)[0]
        if got != rid:
            raise ConnectionError(
                f"decode reply id {got} != request id {rid}")
        if f[1] == TAG_INFER_ERR:
            (mlen,) = _U32.unpack_from(f, 10 + base)
            raise ServingError(f[14 + base:14 + base + mlen].decode())
        if f[1] != want_tag:
            raise ConnectionError(
                f"unexpected decode reply tag {f[1]:#x}")
        return f

    def decode_open(self, prompt: Optional[Sequence[int]] = None,
                    timeout: Optional[float] = None):
        """Open a server-side KV decode session.

        Without ``prompt`` (the r9 form) returns the session id; the
        caller feeds tokens one ``decode_step`` at a time. With
        ``prompt`` (r12, DECODE_OPEN2) the SERVER prefills the whole
        prompt — adopting shared prefix pages from the prompt cache,
        then chunk-prefilling the rest interleaved with running decode
        steps — and returns ``(session, logits, adopted)``: the last
        prompt token's next-token logits plus how many leading tokens
        were satisfied from the prefix cache. ``timeout`` temporarily
        widens the socket timeout (long prompts queue behind live
        decode traffic by design)."""
        rid = self._next_id
        self._next_id += 1
        if prompt is None:
            self._send_frame(bytes([WIRE_VERSION, TAG_DECODE_OPEN]) +
                             _U64.pack(rid))
            f = self._decode_reply_expect(TAG_DECODE_SESS, rid)
            return _U64.unpack_from(f, 10 + _frame_base(f))[0]
        toks = np.ascontiguousarray(prompt, np.int64)
        if toks.ndim != 1 or toks.size < 1:
            raise ValueError("decode_open: prompt must be a non-empty "
                             "1-D token sequence")
        payload = (bytes([WIRE_VERSION, TAG_DECODE_OPEN2]) +
                   _U64.pack(rid) + _U32.pack(toks.size) +
                   _U32.pack(0) + toks.tobytes())
        old_to = self._sock.gettimeout()
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            self._send_frame(payload)
            f = self._decode_reply_expect(TAG_DECODE_OPEN_REP, rid)
        finally:
            if timeout is not None:
                self._sock.settimeout(old_to)
        base = _frame_base(f)
        sess = _U64.unpack_from(f, 10 + base)[0]
        (adopted,) = _U32.unpack_from(f, 18 + base)
        (n,) = _U32.unpack_from(f, 22 + base)
        logits = np.frombuffer(f, np.float32, n, 26 + base).copy()
        return sess, logits, int(adopted)

    def decode_open_many(self, prompts, timeout: Optional[float] = None,
                         return_exceptions: bool = False):
        """Pipelined ``decode_open(prompt=...)``: all OPEN2 frames are
        written before replies are drained, so the server prefills the
        prompts CONCURRENTLY (chunked through the decode batcher,
        shared prefixes adopted from the prompt cache). Returns
        ``[(session, logits, adopted), ...]`` in input order.
        Server-side errors (session pressure, pool exhaustion) drain
        like ``infer_many``: every in-flight reply is consumed before
        the first error raises (the stream stays usable), or — with
        ``return_exceptions`` — surfaces as a per-entry
        :class:`ServingError`."""
        pending = {}
        old_to = self._sock.gettimeout()
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            for i, prompt in enumerate(prompts):
                toks = np.ascontiguousarray(prompt, np.int64)
                if toks.ndim != 1 or toks.size < 1:
                    raise ValueError("decode_open_many: each prompt "
                                     "must be a non-empty 1-D "
                                     "sequence")
                rid = self._next_id
                self._next_id += 1
                pending[rid] = i
                self._send_frame(
                    bytes([WIRE_VERSION, TAG_DECODE_OPEN2]) +
                    _U64.pack(rid) + _U32.pack(toks.size) +
                    _U32.pack(0) + toks.tobytes())
            results = [None] * len(pending)
            while pending:
                f = self._read_frame()
                base = _frame_base(f)
                got = _U64.unpack_from(f, 2 + base)[0]
                if got not in pending:
                    raise ConnectionError(
                        f"unexpected open reply id {got}")
                i = pending.pop(got)
                if f[1] == TAG_INFER_ERR:
                    (mlen,) = _U32.unpack_from(f, 10 + base)
                    results[i] = ServingError(
                        f[14 + base:14 + base + mlen].decode())
                    continue
                if f[1] != TAG_DECODE_OPEN_REP:
                    raise ConnectionError(
                        f"unexpected open reply tag {f[1]:#x}")
                sess = _U64.unpack_from(f, 10 + base)[0]
                (adopted,) = _U32.unpack_from(f, 18 + base)
                (n,) = _U32.unpack_from(f, 22 + base)
                logits = np.frombuffer(f, np.float32, n,
                                       26 + base).copy()
                results[i] = (sess, logits, int(adopted))
            if not return_exceptions:
                for r in results:
                    if isinstance(r, ServingError):
                        raise r
            return results
        finally:
            if timeout is not None:
                self._sock.settimeout(old_to)

    def decode_fork(self, session: int) -> int:
        """Clone a live session copy-on-write (shared KV pages until
        divergence) — parallel sampling from one prefix. Returns the
        NEW session id."""
        rid = self._next_id
        self._next_id += 1
        self._send_frame(bytes([WIRE_VERSION, TAG_DECODE_FORK]) +
                         _U64.pack(rid) + _U64.pack(session))
        f = self._decode_reply_expect(TAG_DECODE_SESS, rid)
        return _U64.unpack_from(f, 10 + _frame_base(f))[0]

    def decode_close(self, session: int) -> None:
        rid = self._next_id
        self._next_id += 1
        self._send_frame(bytes([WIRE_VERSION, TAG_DECODE_CLOSE]) +
                         _U64.pack(rid) + _U64.pack(session))
        self._decode_reply_expect(TAG_DECODE_SESS, rid)

    @staticmethod
    def _decode_step_payload(rid: int, session: int, token: int,
                             trace_id: int = 0) -> bytes:
        if trace_id:
            return (bytes([WIRE_VERSION_TRACED, TAG_DECODE_STEP]) +
                    _U64.pack(trace_id) + _U64.pack(rid) +
                    _U64.pack(session) + _I64.pack(token))
        return (bytes([WIRE_VERSION, TAG_DECODE_STEP]) +
                _U64.pack(rid) + _U64.pack(session) + _I64.pack(token))

    @staticmethod
    def _decode_rep_logits(f: bytes) -> np.ndarray:
        base = _frame_base(f)
        (n,) = _U32.unpack_from(f, 18 + base)
        return np.frombuffer(f, np.float32, n, 22 + base).copy()

    def decode_step(self, session: int, token: int) -> np.ndarray:
        """Feed one token into a session; returns the session's
        next-token logits (float32 vector)."""
        rid = self._next_id
        self._next_id += 1
        tid, t0 = self._trace_begin()
        self._send_frame(
            self._decode_step_payload(rid, session, token, tid))
        f = self._decode_reply_expect(TAG_DECODE_REP, rid)
        self._trace_end(tid, t0, "client.decode_step", f)
        return self._decode_rep_logits(f)

    def decode_step_many(self, pairs, return_exceptions: bool = False):
        """Pipelined decode steps: ``pairs`` is a sequence of
        ``(session, token)`` — all frames are written before replies
        are drained, so steps of DIFFERENT sessions batch server-side
        (one session's steps stay ordered). Returns per-pair logits in
        input order; server-side errors surface like infer_many."""
        results = [None] * len(pairs)
        pending = {}
        for i, (sess, tok) in enumerate(pairs):
            rid = self._next_id
            self._next_id += 1
            tid, t0 = self._trace_begin()
            pending[rid] = (i, tid, t0)
            self._send_frame(
                self._decode_step_payload(rid, sess, tok, tid))
        while pending:
            f = self._read_frame_reused()
            got = _U64.unpack_from(f, 2 + _frame_base(f))[0]
            if got not in pending:
                raise ConnectionError(
                    f"unexpected decode reply id {got}")
            i, tid, t0 = pending.pop(got)
            base = _frame_base(f)
            if f[1] == TAG_INFER_ERR:
                (mlen,) = _U32.unpack_from(f, 10 + base)
                results[i] = ServingError(
                    bytes(f[14 + base:14 + base + mlen]).decode())
            elif f[1] == TAG_DECODE_REP:
                self._trace_end(tid, t0, "client.decode_step", f)
                results[i] = self._decode_rep_logits(f)
            else:
                raise ConnectionError(
                    f"unexpected decode reply tag {f[1]:#x}")
        if not return_exceptions:
            for r in results:
                if isinstance(r, ServingError):
                    raise r
        return results

    # -------------------------------------------- speculative decode
    @staticmethod
    def _spec_rep_parse(f: bytes):
        """-> (session, accepted, tokens) of a DECODE_SPEC_REP."""
        base = _frame_base(f)
        sess = _U64.unpack_from(f, 10 + base)[0]
        (accepted,) = _U32.unpack_from(f, 18 + base)
        (n,) = _U32.unpack_from(f, 22 + base)
        toks = [int(_I64.unpack_from(f, 26 + base + 8 * k)[0])
                for k in range(n)]
        return sess, int(accepted), toks

    def spec_open(self, prompt: Sequence[int], seed: int = 0,
                  sample: bool = False,
                  timeout: Optional[float] = None):
        """Open a SPECULATIVE decode session: the server prefills the
        prompt into a target session AND a draft twin, then returns
        ``(session, tokens, adopted)`` where ``tokens`` holds the
        first generated token (greedy argmax, or one draw from the
        target softmax when ``sample=True`` — ``seed`` makes the
        server-side sampler deterministic). Generate with
        :meth:`spec_step`; tokens arrive in bursts of ``accepted + 1``
        per round with zero distribution drift vs plain decoding."""
        toks = np.ascontiguousarray(prompt, np.int64)
        if toks.ndim != 1 or toks.size < 1:
            raise ValueError("spec_open: prompt must be a non-empty "
                             "1-D token sequence")
        rid = self._next_id
        self._next_id += 1
        payload = (bytes([WIRE_VERSION, TAG_DECODE_SPEC_OPEN]) +
                   _U64.pack(rid) + _U32.pack(toks.size) +
                   _U32.pack(1 if sample else 0) +
                   _U64.pack(seed & (2 ** 64 - 1)) + toks.tobytes())
        old_to = self._sock.gettimeout()
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            self._send_frame(payload)
            f = self._decode_reply_expect(TAG_DECODE_SPEC_REP, rid)
        finally:
            if timeout is not None:
                self._sock.settimeout(old_to)
        sess, adopted, tokens = self._spec_rep_parse(f)
        return sess, tokens, adopted

    def spec_step(self, session: int):
        """One speculative round: the draft proposes k tokens, the
        target verifies them in one pass. Returns ``(tokens,
        accepted)`` — the 1..k+1 tokens committed this round and how
        many came from the draft (the last token is always
        target-sourced)."""
        rid = self._next_id
        self._next_id += 1
        tid, t0 = self._trace_begin()
        if tid:
            payload = (bytes([WIRE_VERSION_TRACED,
                              TAG_DECODE_SPEC_STEP]) +
                       _U64.pack(tid) + _U64.pack(rid) +
                       _U64.pack(session))
        else:
            payload = (bytes([WIRE_VERSION, TAG_DECODE_SPEC_STEP]) +
                       _U64.pack(rid) + _U64.pack(session))
        self._send_frame(payload)
        f = self._decode_reply_expect(TAG_DECODE_SPEC_REP, rid)
        self._trace_end(tid, t0, "client.spec_step", f)
        _, accepted, tokens = self._spec_rep_parse(f)
        return tokens, accepted

    def spec_step_many(self, sessions,
                       return_exceptions: bool = False):
        """Pipelined speculative rounds across sessions: one
        SPEC_STEP per session id, all frames written before replies
        drain, so different sessions' draft bursts and verify passes
        batch server-side. Returns ``[(tokens, accepted), ...]`` in
        input order; server errors surface like infer_many."""
        results = [None] * len(sessions)
        pending = {}
        for i, sess in enumerate(sessions):
            rid = self._next_id
            self._next_id += 1
            pending[rid] = i
            self._send_frame(bytes([WIRE_VERSION,
                                    TAG_DECODE_SPEC_STEP]) +
                             _U64.pack(rid) + _U64.pack(sess))
        while pending:
            f = self._read_frame()
            base = _frame_base(f)
            got = _U64.unpack_from(f, 2 + base)[0]
            if got not in pending:
                raise ConnectionError(
                    f"unexpected spec reply id {got}")
            i = pending.pop(got)
            if f[1] == TAG_INFER_ERR:
                (mlen,) = _U32.unpack_from(f, 10 + base)
                results[i] = ServingError(
                    f[14 + base:14 + base + mlen].decode())
            elif f[1] == TAG_DECODE_SPEC_REP:
                _, accepted, tokens = self._spec_rep_parse(f)
                results[i] = (tokens, accepted)
            else:
                raise ConnectionError(
                    f"unexpected spec reply tag {f[1]:#x}")
        if not return_exceptions:
            for r in results:
                if isinstance(r, ServingError):
                    raise r
        return results

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
