"""`paddle.io` equivalent namespace."""
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    WeightedRandomSampler,
)
from .worker import WorkerInfo, get_worker_info  # noqa: F401
from .device_buffer import (DeviceBufferedReader, HostPrefetcher,  # noqa: F401
                            device_buffered, host_prefetched)
