"""DataLoader.

Mirrors `python/paddle/fluid/reader.py` + `dataloader/dataloader_iter.py`
(multiprocess workers, SIGCHLD watchdog, shared-mem tensors, C++
`buffered_reader.cc` device prefetch).

TPU-native design: worker parallelism uses a thread pool (numpy batch
assembly releases the GIL; TPU input pipelines are host-CPU bound on decode,
not on Python), and device prefetch double-buffers batches onto the TPU with
`jax.device_put` ahead of consumption — the `buffered_reader.cc` equivalent.
"""
from __future__ import annotations

import collections
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import jax
import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    `fluid/dataloader/collate.py`)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items))
                     for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    if isinstance(sample, jax.Array):
        import jax.numpy as jnp
        return jnp.stack(batch)
    return batch


class DataLoader:
    """`paddle.io.DataLoader` equivalent."""

    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if not self._iterable_mode:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])
            return
        # threaded fetch: overlap batch assembly with device compute
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            pending = collections.deque()
            depth = self.num_workers * self.prefetch_factor

            def fetch(indices):
                return self.collate_fn([self.dataset[i] for i in indices])

            it = iter(self.batch_sampler)
            try:
                for _ in range(depth):
                    pending.append(pool.submit(fetch, next(it)))
            except StopIteration:
                it = None
            while pending:
                out = pending.popleft().result()
                if it is not None:
                    try:
                        pending.append(pool.submit(fetch, next(it)))
                    except StopIteration:
                        it = None
                yield out

    def __iter__(self):
        if not self.use_buffer_reader:
            yield from self._batches()
            return
        # device double-buffering (buffered_reader.cc equivalent)
        import jax.numpy as jnp

        def to_device(batch):
            return jax.tree.map(
                lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
                batch)

        prev = None
        for batch in self._batches():
            cur = to_device(batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
