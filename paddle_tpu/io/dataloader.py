"""DataLoader.

Mirrors `python/paddle/fluid/reader.py` + `dataloader/dataloader_iter.py`
(multiprocess workers, SIGCHLD watchdog, shared-mem tensors, C++
`buffered_reader.cc` device prefetch).

TPU-native design: worker parallelism uses a thread pool (numpy batch
assembly releases the GIL; TPU input pipelines are host-CPU bound on decode,
not on Python), and device prefetch double-buffers batches onto the TPU with
`jax.device_put` ahead of consumption — the `buffered_reader.cc` equivalent.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    `fluid/dataloader/collate.py`)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items))
                     for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    if isinstance(sample, jax.Array):
        import jax.numpy as jnp
        return jnp.stack(batch)
    return batch


class DataLoader:
    """`paddle.io.DataLoader` equivalent."""

    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 worker_mode: str = "process"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        if worker_mode not in ("process", "thread"):
            raise ValueError("worker_mode must be 'process' or 'thread'")
        self.worker_mode = worker_mode
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if not self._iterable_mode:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])
            return
        if self.worker_mode == "process":
            # forked worker processes + shared-memory batches + watchdog —
            # the reference's default worker model (`dataloader_iter.py:317`
            # + `worker.py:251` + mmap_allocator shared mem). Python-heavy
            # decode pipelines scale past the GIL here.
            from .worker import MultiprocessBatchIterator
            it = MultiprocessBatchIterator(
                self.dataset, self.collate_fn, list(self.batch_sampler),
                num_workers=self.num_workers,
                prefetch=self.prefetch_factor,
                use_shm=self.use_shared_memory,
                worker_init_fn=self.worker_init_fn,
                timeout_s=self.timeout if self.timeout else 120.0)
            yield from it
            return
        # worker threads + native blocking queue: the reference's
        # DataLoader worker model (`dataloader_iter.py:317` workers feeding
        # `lod_tensor_blocking_queue`); synchronization lives in C++
        # (csrc BlockingQueue), falling back to queue.Queue without it
        from ..core.native import make_queue
        depth = max(2, self.num_workers * self.prefetch_factor)
        out_q = make_queue(depth)
        work = list(self.batch_sampler)
        state = {"claim": 0, "served": 0, "stop": False}
        cond = threading.Condition()
        errors = []

        def worker():
            while True:
                with cond:
                    # claim the next batch index, but stay inside the
                    # prefetch window so in-flight batches stay bounded at
                    # `depth` even when one worker is slow (backpressure
                    # the bounded queue alone can't give once the consumer
                    # buffers out-of-order arrivals)
                    while (not state["stop"]
                           and state["claim"] >= state["served"] + depth):
                        cond.wait(timeout=0.1)
                    if state["stop"] or state["claim"] >= len(work):
                        return
                    i = state["claim"]
                    state["claim"] = i + 1
                try:
                    batch = self.collate_fn(
                        [self.dataset[j] for j in work[i]])
                except Exception as e:  # surface to consumer
                    errors.append(e)
                    out_q.close()
                    return
                while True:
                    try:
                        if out_q.push((i, batch), timeout_ms=100):
                            break
                    except RuntimeError:
                        return  # closed (consumer bailed)
                    if state["stop"]:
                        return

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            reorder = {}
            nxt = 0
            while nxt < len(work):
                if nxt in reorder:
                    yield reorder.pop(nxt)
                    nxt += 1
                    with cond:
                        state["served"] = nxt
                        cond.notify_all()
                    continue
                got = out_q.pop(timeout_ms=100)
                if got is out_q.closed_sentinel:
                    break
                if got is None:
                    if errors:
                        break
                    continue
                seq, batch = got
                reorder[seq] = batch
            if errors:
                raise errors[0]
        finally:
            with cond:
                state["stop"] = True
                cond.notify_all()
            out_q.close()
            for t in threads:
                t.join(timeout=5)

    def __iter__(self):
        import os
        src = self._batches()
        # host-side double buffering: with in-process loading
        # (num_workers <= 0) batch prep runs inline on the consumer
        # thread; a HostPrefetcher worker pulls `prefetch_factor`
        # batches ahead so collate overlaps the consumer's compute
        # (worker modes already overlap via their own threads/procs).
        # The thread lives only while this iterator does; a process
        # that os.fork()s WHILE another loader is mid-iteration can
        # set PTPU_HOST_PREFETCH=0 to keep iteration thread-free
        # (fork-with-threads hazard — jax's runtime threads make fork
        # unsafe in principle already).
        if self.num_workers <= 0 and self.use_buffer_reader and \
                os.environ.get("PTPU_HOST_PREFETCH", "1") != "0":
            from .device_buffer import host_prefetched
            src = host_prefetched(src, depth=self.prefetch_factor)
        if not self.use_buffer_reader:
            yield from src
            return
        # device double-buffering (buffered_reader.cc equivalent) — one
        # implementation, shared with the standalone reader
        from .device_buffer import device_buffered
        yield from device_buffered(src, buffer_size=2)
