"""Device-prefetching reader.

Reference: `operators/reader/buffered_reader.h` — BufferedReader stages
the next batches onto the device on a dedicated stream so compute never
waits on H2D copies. TPU-native: `jax.device_put` is asynchronous (the
transfer is enqueued and overlaps with the running step), so prefetching
means issuing the put for the NEXT `buffer_size` batches before the
current one is consumed.
"""
from __future__ import annotations

import queue as _queue
import threading
from collections import deque
from typing import Iterable, Iterator

import jax


def _put(batch, device):
    return jax.tree.map(
        lambda x: jax.device_put(x, device) if hasattr(x, "shape") else x,
        batch)


class HostPrefetcher:
    """Background-thread double buffering for the HOST side of the
    pipeline: a worker thread pulls up to `depth` batches ahead of the
    consumer, so batch prep (decode + collate in `DataLoader._batches`)
    overlaps the consumer's compute instead of running inline on every
    `next()`. The device half (`DeviceBufferedReader`) overlaps the
    H2D transfer; this overlaps producing the bytes to transfer —
    together they are the full buffered_reader.cc story.

    Ordering is preserved exactly (single worker, FIFO queue) and
    producer exceptions re-raise at the consumer's next pull."""

    _END = object()

    def __init__(self, loader: Iterable, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._loader = loader
        self._depth = depth

    def __iter__(self) -> Iterator:
        q: _queue.Queue = _queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        errors = []

        def _offer(item) -> bool:
            # bounded put that gives up when the consumer bailed, so
            # an early-exiting consumer never leaks a blocked thread
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self._loader:
                    if not _offer(item):
                        return
            except BaseException as e:  # noqa: BLE001 — relayed below
                errors.append(e)
            _offer(self._END)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._END:
                    if errors:
                        raise errors[0]
                    return
                yield item
        finally:
            stop.set()
            t.join(timeout=5)


def host_prefetched(loader: Iterable, depth: int = 2) -> HostPrefetcher:
    """Functional spelling: `for batch in host_prefetched(gen): ...`"""
    return HostPrefetcher(loader, depth=depth)


class DeviceBufferedReader:
    """Wrap any batch iterable; yields device-resident batches with
    `buffer_size` transfers in flight (reference buffered_reader.h:36)."""

    def __init__(self, loader: Iterable, buffer_size: int = 2,
                 device=None):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        self._loader = loader
        self._size = buffer_size
        self._device = device or jax.devices()[0]

    def __iter__(self) -> Iterator:
        buf: deque = deque()
        it = iter(self._loader)
        try:
            for _ in range(self._size):
                buf.append(_put(next(it), self._device))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                buf.append(_put(next(it), self._device))
            except StopIteration:
                pass
            yield out

    def __len__(self):
        return len(self._loader)


def device_buffered(loader: Iterable, buffer_size: int = 2,
                    device=None) -> DeviceBufferedReader:
    """Functional spelling: `for batch in device_buffered(loader): ...`"""
    return DeviceBufferedReader(loader, buffer_size=buffer_size,
                                device=device)
