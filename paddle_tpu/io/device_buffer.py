"""Device-prefetching reader.

Reference: `operators/reader/buffered_reader.h` — BufferedReader stages
the next batches onto the device on a dedicated stream so compute never
waits on H2D copies. TPU-native: `jax.device_put` is asynchronous (the
transfer is enqueued and overlaps with the running step), so prefetching
means issuing the put for the NEXT `buffer_size` batches before the
current one is consumed.
"""
from __future__ import annotations

from collections import deque
from typing import Iterable, Iterator

import jax


def _put(batch, device):
    return jax.tree.map(
        lambda x: jax.device_put(x, device) if hasattr(x, "shape") else x,
        batch)


class DeviceBufferedReader:
    """Wrap any batch iterable; yields device-resident batches with
    `buffer_size` transfers in flight (reference buffered_reader.h:36)."""

    def __init__(self, loader: Iterable, buffer_size: int = 2,
                 device=None):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        self._loader = loader
        self._size = buffer_size
        self._device = device or jax.devices()[0]

    def __iter__(self) -> Iterator:
        buf: deque = deque()
        it = iter(self._loader)
        try:
            for _ in range(self._size):
                buf.append(_put(next(it), self._device))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                buf.append(_put(next(it), self._device))
            except StopIteration:
                pass
            yield out

    def __len__(self):
        return len(self._loader)


def device_buffered(loader: Iterable, buffer_size: int = 2,
                    device=None) -> DeviceBufferedReader:
    """Functional spelling: `for batch in device_buffered(loader): ...`"""
    return DeviceBufferedReader(loader, buffer_size=buffer_size,
                                device=device)
